"""Chaos drill: a supervised fit under a scripted kill schedule.

The executable proof of ISSUE 7's fault-domain layer: launch a training
gang under ``parallel.supervisor.Supervisor``, arm a deterministic
``GLINT_FAULTS`` kill on rank 0 (``worker.step:kill@G`` — SIGKILL at the
G-th dispatch group, placed early in epoch 2 so at least one checkpoint
has committed), and assert the whole story end to end:

  * the supervisor detects the crash, tears the gang down (the surviving
    rank is wedged in a collective — exactly the hang this layer exists
    for), and relaunches exactly once;
  * the relaunch resumes from the last committed checkpoint
    (integrity-verified through ``utils.integrity.resolve_train_state``);
  * the fit completes and the final model clears the same vienna/berlin
    quality gates the CI smoke jobs use;
  * restarts and recovery latency land in ``FAULT_BENCH.json`` (repo
    root), comparable across PRs.

Env: GLINT_CHAOS_WORKERS (gang size, default 2; 1 = supervised
single-process fit), GLINT_CHAOS_ITERATIONS (default 6),
GLINT_CHAOS_OUT (artifact path override). Exits nonzero if any gate
fails.
"""

import json
import math
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "tests"))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from conftest import _make_tiny_corpus  # noqa: E402

# Scrub the virtual-8-device XLA flag the test conftest just installed
# (and anything the harness set): each WORKER must see exactly its own
# real device count, or the gang's (workers, 1) mesh covers only rank
# 0's devices and the cross-process collectives are malformed. The
# supervisor itself never touches a device.
os.environ.pop("XLA_FLAGS", None)

OUT = os.environ.get(
    "GLINT_CHAOS_OUT", os.path.join(ROOT, "FAULT_BENCH.json")
)

BATCH = 256
SPC = 4
WINDOW = 5
MIN_COUNT = 5


def _groups_per_epoch(sentences, workers: int) -> int:
    """Dispatch groups per epoch for this corpus/config — the unit the
    ``worker.step`` injection point counts in. Computed exactly the way
    the fit loops size their epochs so the kill schedule is
    deterministic: single-process runs the device-resident grid scan
    (ceil(positions/B) steps), multi-process runs the host-batcher
    lockstep schedule (ceil(max-shard-words/local-batch) steps)."""
    from glint_word2vec_tpu.corpus.batching import (
        chunk_sentences,
        encode_sentences,
    )
    from glint_word2vec_tpu.corpus.vocab import build_vocab
    from glint_word2vec_tpu.parallel.distributed import (
        per_process_word_counts,
    )

    vocab = build_vocab(sentences, min_count=MIN_COUNT)
    encoded = chunk_sentences(encode_sentences(sentences, vocab), 1000)
    lens = np.array([s.size for s in encoded], dtype=np.int64)
    if workers > 1:
        counts = per_process_word_counts(lens, workers)
        steps = max(1, math.ceil(int(counts.max()) / (BATCH // workers)))
    else:
        steps = max(1, math.ceil(int(lens.sum()) / BATCH))
    return max(1, math.ceil(steps / SPC))


def main() -> int:
    workers = int(os.environ.get("GLINT_CHAOS_WORKERS", 2))
    iterations = int(os.environ.get("GLINT_CHAOS_ITERATIONS", 6))
    import tempfile

    from glint_word2vec_tpu.parallel.supervisor import Supervisor

    tmp = tempfile.mkdtemp(prefix="chaos_drill_")
    corpus = os.path.join(tmp, "capitals.txt")
    model_dir = os.path.join(tmp, "model")
    ck_dir = os.path.join(tmp, "ck")
    sentences = _make_tiny_corpus()
    with open(corpus, "w") as f:
        for s in sentences:
            f.write(" ".join(s) + "\n")

    gpe = _groups_per_epoch(sentences, workers)
    # Early in epoch 2 for the multi-process gang (its epoch-boundary
    # checkpoints are blocking + barriered, so ckpt-1 is committed
    # before any epoch-2 group dispatches); one epoch later for the
    # single-process async-checkpoint path, giving the background
    # writer a whole epoch of margin to commit.
    kill_at = (gpe if workers > 1 else 2 * gpe) + 2
    fault = f"worker.step:kill@{kill_at}"

    train_rest = [
        "--corpus", corpus, "--output", model_dir,
        "--vector-size", "48", "--window", str(WINDOW),
        "--step-size", "0.025", "--batch-size", str(BATCH),
        "--negatives", "5", "--min-count", str(MIN_COUNT),
        "--iterations", str(iterations), "--seed", "1",
        "--steps-per-call", str(SPC),
        "--checkpoint-dir", ck_dir, "--checkpoint-every", "1",
    ]
    if workers > 1:
        train_rest += [
            "--num-partitions", str(workers), "--num-shards", "1",
        ]

    from glint_word2vec_tpu.parallel.supervisor import (
        cli_train_build_argv,
    )

    build_argv = cli_train_build_argv(train_rest)

    print(
        f"chaos drill: {workers} worker(s), {gpe} groups/epoch, "
        f"armed {fault!r} on rank 0 generation 0",
        flush=True,
    )
    t0 = time.time()
    report = Supervisor(
        build_argv,
        workers,
        status_dir=os.path.join(tmp, "supervisor"),
        checkpoint_dir=ck_dir,
        # The kill schedule arms ONLY generation 0 of rank 0 — a
        # re-armed relaunch would die at the same group forever.
        rank_env_first_launch={0: {"GLINT_FAULTS": fault}},
        heartbeat_stale_seconds=300.0,
        startup_grace_seconds=600.0,
        max_restarts=3,
        backoff_base_seconds=0.5,
        backoff_cap_seconds=5.0,
    ).run()
    wall = time.time() - t0

    out = {
        "metric": "chaos_drill",
        "workers": workers,
        "iterations": iterations,
        "groups_per_epoch": gpe,
        "fault": fault,
        "wall_seconds": round(wall, 2),
        "supervisor": report.to_dict(),
    }

    checks = {
        "completed": report.completed,
        "restarts_exactly_one": report.restarts == 1,
        "resumed_from_committed_checkpoint": bool(
            report.restart_records
            and report.restart_records[0].resumed_from
        ),
    }
    quality = {}
    if report.completed:
        from glint_word2vec_tpu.utils.platform import force_platform

        force_platform()
        from glint_word2vec_tpu import load_model

        m = load_model(model_dir)
        syns = m.find_synonyms("austria", 10)
        ana = m.analogy(
            positive=["vienna", "germany"], negative=["austria"], num=10
        )
        quality = {
            "vienna_in_top10": "vienna" in [w for w, _ in syns],
            "vienna_score": round(dict(syns).get("vienna", 0.0), 4),
            "berlin_in_analogy_top10": "berlin" in [w for w, _ in ana],
        }
        checks["vienna_gate"] = bool(
            quality["vienna_in_top10"] and quality["vienna_score"] > 0.5
        )
        checks["berlin_gate"] = quality["berlin_in_analogy_top10"]
        state = json.load(open(os.path.join(ck_dir, "train_state.json")))
        checks["all_epochs_committed"] = (
            state["epochs_completed"] == iterations
        )
        out["final_train_state"] = {
            "epochs_completed": state["epochs_completed"],
            "ckpt": state["ckpt"],
            "prev_ckpt": (state.get("prev") or {}).get("ckpt"),
        }
        import jax

        dev = jax.devices()[0]
        out["platform"] = dev.platform
        if dev.platform != "tpu":
            out["fallback"] = dev.platform
    out["quality"] = quality
    out["checks"] = checks

    with open(OUT, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out, indent=2))
    if not all(checks.values()):
        print("chaos drill FAILED gates:", [
            k for k, v in checks.items() if not v
        ], file=sys.stderr)
        return 1
    print("chaos drill ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
