"""Chaos drill: a supervised fit under a scripted kill schedule.

The executable proof of ISSUE 7's fault-domain layer AND ISSUE 8's
fleet-observability layer: run ``cli supervise`` (the real operator
entry point) over a training gang, arm a deterministic ``GLINT_FAULTS``
kill on rank 0 (``worker.step:kill@G`` — SIGKILL at the G-th dispatch
group, placed early in epoch 2 so at least one checkpoint has
committed), and assert the whole story end to end:

  * the supervisor detects the crash, tears the gang down (the surviving
    rank is wedged in a collective — exactly the hang this layer exists
    for), and relaunches exactly once;
  * the relaunch resumes from the last committed checkpoint
    (integrity-verified through ``utils.integrity.resolve_train_state``);
  * while the gang trains, the supervisor's MERGED ``/metrics`` endpoint
    answers with gang counters that equal the sum of the per-rank values
    and a ``rank_skew`` straggler gauge, and its Prometheus rendering
    lints clean;
  * the kill leaves a ``postmortem-0-0/`` flight-recorder bundle holding
    rank 0's event ring + last heartbeat, referenced from the
    supervisor's JSON report (``--report-out`` — this script consumes
    that report instead of re-deriving anything);
  * the per-rank event JSONLs merge into one rank-laned Chrome trace
    (``trace_summarize.py --merge-ranks``) with one lane per rank;
  * the fit completes and the final model clears the same vienna/berlin
    quality gates the CI smoke jobs use;
  * everything lands in ``FAULT_BENCH.json`` (repo root), comparable
    across PRs.

Env: GLINT_CHAOS_WORKERS (gang size, default 2; 1 = supervised
single-process fit), GLINT_CHAOS_ITERATIONS (default 6),
GLINT_CHAOS_OUT (artifact path override). Exits nonzero if any gate
fails.
"""

import json
import math
import os
import subprocess
import sys
import time
import urllib.request

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "tests"))
sys.path.insert(0, os.path.join(ROOT, "scripts"))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from conftest import _make_tiny_corpus  # noqa: E402

# Scrub the virtual-8-device XLA flag the test conftest just installed
# (and anything the harness set): each WORKER must see exactly its own
# real device count, or the gang's (workers, 1) mesh covers only rank
# 0's devices and the cross-process collectives are malformed. The
# supervisor itself never touches a device.
os.environ.pop("XLA_FLAGS", None)

OUT = os.environ.get(
    "GLINT_CHAOS_OUT", os.path.join(ROOT, "FAULT_BENCH.json")
)

BATCH = 256
SPC = 4
WINDOW = 5
MIN_COUNT = 5


def _groups_per_epoch(sentences, workers: int) -> int:
    """Dispatch groups per epoch for this corpus/config — the unit the
    ``worker.step`` injection point counts in. Computed exactly the way
    the fit loops size their epochs so the kill schedule is
    deterministic: single-process runs the device-resident grid scan
    (ceil(positions/B) steps), multi-process runs the host-batcher
    lockstep schedule (ceil(max-shard-words/local-batch) steps)."""
    from glint_word2vec_tpu.corpus.batching import (
        chunk_sentences,
        encode_sentences,
    )
    from glint_word2vec_tpu.corpus.vocab import build_vocab
    from glint_word2vec_tpu.parallel.distributed import (
        per_process_word_counts,
    )

    vocab = build_vocab(sentences, min_count=MIN_COUNT)
    encoded = chunk_sentences(encode_sentences(sentences, vocab), 1000)
    lens = np.array([s.size for s in encoded], dtype=np.int64)
    if workers > 1:
        counts = per_process_word_counts(lens, workers)
        steps = max(1, math.ceil(int(counts.max()) / (BATCH // workers)))
    else:
        steps = max(1, math.ceil(int(lens.sum()) / BATCH))
    return max(1, math.ceil(steps / SPC))


def _fetch(url: str, timeout: float = 2.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def _scrape_merged(port: int, workers: int, proc) -> dict:
    """Poll the supervisor's merged endpoint while the gang trains;
    keep the best sample (all ranks reporting) plus one lint-checked
    Prometheus scrape. Never fails the drill by itself — missing
    samples turn into failed checks downstream."""
    from glint_word2vec_tpu.obs.prometheus import lint_prometheus_text

    best, prom_ok, healthz_seen = None, False, False
    while proc.poll() is None:
        try:
            merged = json.loads(
                _fetch(f"http://127.0.0.1:{port}/metrics")
            )
        except Exception:
            time.sleep(0.25)
            continue
        if merged.get("ranks_reporting"):
            if best is None or (
                merged["ranks_reporting"]
                >= best.get("ranks_reporting", 0)
            ):
                best = merged
        if not healthz_seen:
            try:
                _fetch(f"http://127.0.0.1:{port}/healthz")
                healthz_seen = True
            except Exception:
                pass
        if not prom_ok and merged.get("ranks_reporting") == workers:
            try:
                lint_prometheus_text(_fetch(
                    f"http://127.0.0.1:{port}/metrics?format=prometheus"
                ))
                prom_ok = True
            except Exception as e:
                print(f"prometheus scrape failed lint: {e}",
                      file=sys.stderr)
        time.sleep(0.25)
    return {"sample": best, "prometheus_lint_ok": prom_ok,
            "healthz_ok": healthz_seen}


def main() -> int:
    workers = int(os.environ.get("GLINT_CHAOS_WORKERS", 2))
    iterations = int(os.environ.get("GLINT_CHAOS_ITERATIONS", 6))
    import tempfile

    tmp = tempfile.mkdtemp(prefix="chaos_drill_")
    corpus = os.path.join(tmp, "capitals.txt")
    model_dir = os.path.join(tmp, "model")
    ck_dir = os.path.join(tmp, "ck")
    sup_dir = os.path.join(tmp, "supervisor")
    report_path = os.path.join(tmp, "report.json")
    sentences = _make_tiny_corpus()
    # graftlint: ignore[atomic-persist] corpus fixture in this drill's private tmp dir; nothing reads it across a crash
    with open(corpus, "w") as f:
        for s in sentences:
            f.write(" ".join(s) + "\n")

    gpe = _groups_per_epoch(sentences, workers)
    # Early in epoch 2 for the multi-process gang (its epoch-boundary
    # checkpoints are blocking + barriered, so ckpt-1 is committed
    # before any epoch-2 group dispatches); one epoch later for the
    # single-process async-checkpoint path, giving the background
    # writer a whole epoch of margin to commit.
    from glint_word2vec_tpu.parallel.supervisor import free_port

    kill_at = (gpe if workers > 1 else 2 * gpe) + 2
    fault = f"worker.step:kill@{kill_at}"
    metrics_port = free_port()

    train_rest = [
        "--corpus", corpus, "--output", model_dir,
        "--vector-size", "48", "--window", str(WINDOW),
        "--step-size", "0.025", "--batch-size", str(BATCH),
        "--negatives", "5", "--min-count", str(MIN_COUNT),
        "--iterations", str(iterations), "--seed", "1",
        "--steps-per-call", str(SPC),
        "--checkpoint-dir", ck_dir, "--checkpoint-every", "1",
    ]
    if workers > 1:
        train_rest += [
            "--num-partitions", str(workers), "--num-shards", "1",
        ]

    # The REAL operator entry point: cli supervise persists the report
    # (--report-out) and serves the merged gang endpoint; this script
    # consumes both instead of re-deriving anything in-process.
    argv = [
        sys.executable, "-m", "glint_word2vec_tpu.cli", "supervise",
        "--workers", str(workers),
        "--max-restarts", "3",
        "--backoff-base", "0.5", "--backoff-cap", "5",
        "--heartbeat-stale", "300", "--startup-grace", "600",
        "--supervise-dir", sup_dir,
        "--report-out", report_path,
        "--metrics-port", str(metrics_port),
        # Armed for rank 0's FIRST launch only — a re-armed relaunch
        # would die at the same group forever.
        "--rank0-env", f"GLINT_FAULTS={fault}",
        "train", *train_rest,
    ]

    print(
        f"chaos drill: {workers} worker(s), {gpe} groups/epoch, "
        f"armed {fault!r} on rank 0 generation 0; merged metrics on "
        f"port {metrics_port}",
        flush=True,
    )
    t0 = time.time()
    sup_log = os.path.join(tmp, "supervise.log")
    # graftlint: ignore[atomic-persist] live stdout/stderr sink for the supervise subprocess — a stream, not an artifact
    with open(sup_log, "wb") as logf:
        proc = subprocess.Popen(argv, stdout=logf,
                                stderr=subprocess.STDOUT)
        gang = _scrape_merged(metrics_port, workers, proc)
        rc = proc.wait()
    wall = time.time() - t0
    with open(sup_log, "rb") as f:
        print(f.read()[-4000:].decode(errors="replace"), flush=True)

    report = None
    if os.path.exists(report_path):
        report = json.load(open(report_path))

    out = {
        "metric": "chaos_drill",
        "workers": workers,
        "iterations": iterations,
        "groups_per_epoch": gpe,
        "fault": fault,
        "wall_seconds": round(wall, 2),
        "supervise_rc": rc,
        "supervisor": report,
    }

    checks = {
        "report_written": report is not None,
        "completed": bool(report and report["completed"]),
        "restarts_exactly_one": bool(report and report["restarts"] == 1),
        "resumed_from_committed_checkpoint": bool(
            report
            and report["restart_records"]
            and report["restart_records"][0]["resumed_from"]
        ),
        "merged_healthz_answered": gang["healthz_ok"],
        "merged_prometheus_lints": gang["prometheus_lint_ok"],
    }

    # -- merged gang endpoint: counters are sums, rank_skew present ----
    sample = gang["sample"]
    out["gang_metrics"] = sample
    merged_ok = sums_ok = skew_present = False
    if sample:
        merged_ok = sample.get("ranks_reporting", 0) >= 1
        per_rank = sample.get("per_rank") or {}
        counters = sample.get("counters") or {}
        sums_ok = (
            counters.get("steps_total")
            == sum(r.get("step") or 0 for r in per_rank.values())
            and counters.get("words_done_total")
            == sum(r.get("words_done") or 0 for r in per_rank.values())
        )
        # Not just key presence (the merge always emits the key): a
        # full-gang sample must carry a REAL skew number, or the
        # straggler gauge silently died (e.g. step_time vanished from
        # the heartbeat snapshot).
        skew = sample.get("rank_skew")
        skew_present = (
            isinstance(skew, (int, float)) and skew >= 1.0
            if sample.get("ranks_reporting") == workers
            else skew is not None
        )
    checks["merged_metrics_scraped"] = merged_ok
    checks["merged_counters_equal_rank_sums"] = sums_ok
    checks["rank_skew_present"] = skew_present

    # -- crash flight recorder: the killed rank's bundle ---------------
    bundle_ok = False
    if report and report["restart_records"]:
        bundles = report["restart_records"][0].get("postmortem") or []
        rank0 = [b for b in bundles if b.endswith("-0")]
        if rank0 and os.path.isdir(rank0[0]):
            files = set(os.listdir(rank0[0]))
            bundle_ok = {"heartbeat.json", "events.jsonl",
                         "meta.json"} <= files
            out["postmortem_bundle"] = {
                "path": rank0[0], "files": sorted(files),
            }
    checks["postmortem_bundle_collected"] = bundle_ok

    # -- rank-laned merged Chrome trace --------------------------------
    from trace_summarize import merge_rank_traces

    event_logs = [
        os.path.join(sup_dir, f"events-{r}.jsonl")
        for r in range(workers)
    ]
    trace_lanes_ok = False
    if all(os.path.exists(p) for p in event_logs):
        doc = merge_rank_traces(event_logs)
        lanes = {
            ev["pid"] for ev in doc["traceEvents"]
            if ev.get("ph") != "M"
        }
        trace_lanes_ok = len(lanes) == workers
        out["merged_trace"] = {
            "ranks": doc["otherData"]["ranks"],
            "events": len(doc["traceEvents"]),
            "lanes": sorted(lanes),
        }
    checks["merged_trace_one_lane_per_rank"] = trace_lanes_ok

    quality = {}
    if checks["completed"]:
        from glint_word2vec_tpu.utils.platform import force_platform

        force_platform()
        from glint_word2vec_tpu import load_model

        m = load_model(model_dir)
        syns = m.find_synonyms("austria", 10)
        ana = m.analogy(
            positive=["vienna", "germany"], negative=["austria"], num=10
        )
        quality = {
            "vienna_in_top10": "vienna" in [w for w, _ in syns],
            "vienna_score": round(dict(syns).get("vienna", 0.0), 4),
            "berlin_in_analogy_top10": "berlin" in [w for w, _ in ana],
        }
        checks["vienna_gate"] = bool(
            quality["vienna_in_top10"] and quality["vienna_score"] > 0.5
        )
        checks["berlin_gate"] = quality["berlin_in_analogy_top10"]
        state = json.load(open(os.path.join(ck_dir, "train_state.json")))
        checks["all_epochs_committed"] = (
            state["epochs_completed"] == iterations
        )
        out["final_train_state"] = {
            "epochs_completed": state["epochs_completed"],
            "ckpt": state["ckpt"],
            "prev_ckpt": (state.get("prev") or {}).get("ckpt"),
        }
        import jax

        dev = jax.devices()[0]
        out["platform"] = dev.platform
        if dev.platform != "tpu":
            out["fallback"] = dev.platform
    out["quality"] = quality
    out["checks"] = checks

    from glint_word2vec_tpu.utils import atomic_write_json

    atomic_write_json(OUT, out, indent=2)
    print(json.dumps(out, indent=2))
    if not all(checks.values()):
        print("chaos drill FAILED gates:", [
            k for k, v in checks.items() if not v
        ], file=sys.stderr)
        return 1
    print("chaos drill ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
