"""The sharded embedding engine — the parameter-server replacement.

This is the in-tree, TPU-native re-implementation of the external Glint
fork's capability surface (SURVEY.md §2.2): the ``BigWord2VecMatrix`` whose
vocab rows are sharded 1/n per server (README.md:69) becomes two jax arrays
sharded ``P("model", None)`` over a device mesh, and every server-side op
maps to a jitted SPMD function:

  Glint op (call site)                     -> engine method
  ------------------------------------------------------------------
  dotprod + adjust (mllib:421,425)         -> train_step (one fused op)
  pull (mllib:514,539,639,652; ml:353)     -> pull
  pullAverage (ml:453)                     -> pull_average
  norms (mllib:486)                        -> norms
  multiply (mllib:598)                     -> multiply (+ top_k_cosine,
                                              replacing the O(vocab) driver
                                              scan at mllib:601-617)
  save (mllib:494) / loadWord2vecMatrix    -> save / load
  destroy / cols (mllib:665,473)           -> destroy / dim

Communication design: a ``psum`` over the "model" axis replaces the
client<->server pull round-trip (each shard contributes its owned rows,
zeros elsewhere); an ``all_gather`` over the "data" axis replaces the
async gradient push. The data-axis exchange carries ONLY the batch's
center representations ``h`` (B x d), the scalar gradient coefficients
(the reference's gPlus/gMinus payload, mllib:422-425), and int32 indices —
O(batch * (d + pairs)) bytes, never the O(batch * pairs * d) expanded
rank-1 updates, and never O(vocab). Consuming shards re-form the
``coef x h`` outer products locally, fused by XLA into the scatter-add
(locked in by the HLO-bytes test, tests/test_engine.py). There is no
message-size ceiling, so the reference's ``GranularBigWord2VecMatrix``
splitter (mllib:83-85,362) has no analogue; request batching survives only
as ``max_query_rows`` chunking in the model layer to bound HBM spikes.

Negative sampling is mesh-invariant AND shard-local: each rank derives
per-row keys from the shared per-step key and its rows' GLOBAL batch
indices (``fold_in(key, global_row)``), reproducing exactly the draws any
other mesh shape makes for the same rows — the (seed -> identical
negatives) contract the reference implements by broadcasting a seed to
all servers (``dotprod(..., seed)``, mllib:420-421) — while sampling only
O(local rows) draws.
"""

from __future__ import annotations

import json
import os
import time
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

from glint_word2vec_tpu.corpus.alias import build_unigram_alias
from glint_word2vec_tpu.obs import events as obs_events
from glint_word2vec_tpu.ops import sgns
from glint_word2vec_tpu.utils import (
    atomic_write_json,
    atomic_write_npy,
    next_pow2,
)
from glint_word2vec_tpu.ops.sampling import (
    sample_negatives,
    sample_negatives_per_row,
)
from glint_word2vec_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    pad_to_multiple,
    table_sharding,
    table_sharding_dims,
)


def _host_or_device(a, dtype=None):
    """Normalize a batch input WITHOUT moving it across the host/device
    boundary: device-resident ``jax.Array`` inputs are kept on device
    (cast in place if needed); anything else becomes a numpy array. The
    previous unconditional ``np.asarray`` forced a blocking device->host
    copy (plus a re-upload) whenever a caller fed device-resident batches
    — exactly the zero-copy path a device-side data pipeline wants."""
    if isinstance(a, jax.Array):
        return a.astype(dtype) if dtype is not None and a.dtype != dtype else a
    return np.asarray(a) if dtype is None else np.asarray(a, dtype=dtype)


def _pull_rows(table_l, idx, start, rows_per_shard, pallas_mode=0):
    """Gather global rows from a shard-local table: contribute owned rows,
    zeros elsewhere, then psum over the model axis. The TPU analogue of the
    servers each answering a pull with their slice (SURVEY.md §2.2 pull).

    ``pallas_mode``: 0 = XLA gather (default), 1 = Pallas row pipeline
    (ops/pallas_rows.py), 2 = Pallas in interpret mode (CPU tests).
    """
    loc = idx - start
    own = (loc >= 0) & (loc < rows_per_shard)
    clipped = jnp.clip(loc, 0, rows_per_shard - 1)
    if pallas_mode:
        from glint_word2vec_tpu.ops.pallas_rows import gather_rows

        rows = gather_rows(
            table_l, clipped, interpret=pallas_mode == 2
        ).astype(jnp.float32)
    else:
        rows = table_l[clipped].astype(jnp.float32)
    rows = jnp.where(own[:, None], rows, 0.0)
    return lax.psum(rows, MODEL_AXIS)


def _dup_sum_f32(idx, upd):
    """Collapse duplicate target rows to ONE fp32-summed update row per
    id run (the remaining duplicate slots carry exact zeros), so a
    low-precision table's scatter-add rounds each row's BATCH TOTAL
    once instead of once per duplicate — the XLA restatement of the
    fused kernel's fp32 VMEM run accumulation (ops/pallas_sgns), used
    by :func:`_bf16_safe_scatter_add` whenever storage is narrower than
    fp32. Without it the dense pair form is quality-lossy on bf16
    tables: a center's per-context d_center contributions (summed in
    the fp32 einsum under the grid shape) would each round against the
    table separately, and sub-ulp contributions vanish entirely (the
    dense+bf16 quality regression pinned in tests/test_pallas_sgns.py).

    Sorted-run form: sort ids (duplicates become adjacent), fp32
    inclusive cumsum over the sorted updates, per-run total = cum at
    the run end minus cum just before the run start."""
    N = idx.shape[0]
    sid, order = lax.sort_key_val(
        idx.astype(jnp.int32), jnp.arange(N, dtype=jnp.int32)
    )
    su = upd[order].astype(jnp.float32)
    cum = jnp.cumsum(su, axis=0)
    change = sid[1:] != sid[:-1]
    is_start = jnp.concatenate([jnp.ones(1, bool), change])
    is_end = jnp.concatenate([change, jnp.ones(1, bool)])
    pos = jnp.arange(N, dtype=jnp.int32)
    run_start = lax.cummax(jnp.where(is_start, pos, 0))
    prev_cum = jnp.where(
        (run_start > 0)[:, None], cum[jnp.maximum(run_start - 1, 0)], 0.0
    )
    return sid, jnp.where(is_end[:, None], cum - prev_cum, 0.0)


def _bf16_safe_scatter_add(table_l, idx, upd):
    """``table_l.at[idx].add(upd)`` with fp32 duplicate-row sums when
    the table stores less than fp32 (see :func:`_dup_sum_f32`); the
    fp32 path keeps the plain scatter-add (exactness-tested numerics,
    no extra sort/cumsum work)."""
    if jnp.dtype(table_l.dtype).itemsize >= 4:
        return table_l.at[idx].add(upd.astype(table_l.dtype))
    sid, summed = _dup_sum_f32(idx, upd)
    return table_l.at[sid].add(summed.astype(table_l.dtype))


def _scatter_rows(table_l, idx, upd, start, rows_per_shard, pallas_mode=0):
    """Apply global rank-1 updates to the owned slice of a sharded table
    (the servers' half of ``adjust``, SURVEY.md §2.2). Disowned updates are
    zeroed and land harmlessly on a clipped row. ``pallas_mode`` as in
    :func:`_pull_rows`."""
    loc = idx - start
    own = (loc >= 0) & (loc < rows_per_shard)
    upd = jnp.where(own[:, None], upd, 0.0)
    clipped = jnp.clip(loc, 0, rows_per_shard - 1)
    if pallas_mode:
        from glint_word2vec_tpu.ops.pallas_rows import scatter_add_rows

        if jnp.dtype(table_l.dtype).itemsize < 4:
            # The pallas_rows run accumulator is TABLE dtype; pre-sum
            # duplicate rows in fp32 so low-precision storage still
            # rounds each row's batch total once (same contract as the
            # XLA branch below and the fused kernels).
            clipped, upd = _dup_sum_f32(clipped, upd)
        return scatter_add_rows(
            table_l, clipped, upd, interpret=pallas_mode == 2
        )
    return _bf16_safe_scatter_add(table_l, clipped, upd)


#: VMEM budget for pinning h_g whole in the fused rank-1 scatter kernel
#: (ops/pallas_rows.scatter_add_rank1): ~16 MB/core minus block buffers.
_RANK1_FUSE_VMEM_BYTES = 10_000_000

#: Process-wide memo of the jitted corpus-scan programs, keyed by every
#: engine attribute their closures capture (:meth:`EmbeddingEngine
#:._scan_memo_key`) plus the scan shape. Short-lived engines with
#: identical configuration — test suites, notebooks, repeated small
#: fits — otherwise recompile the identical XLA program per engine
#: (each engine's fresh ``jax.jit`` closures cannot share an in-memory
#: jit cache), and the packed scan's program is the most expensive
#: compile in the repo. Plain python-level reuse of the jit objects:
#: every input that differs between engines (tables, noise tables,
#: corpus buffers, scalars) is a traced ARGUMENT, so a memo hit is the
#: same program by construction. The memo holds each entry's BUILDER
#: engine alive via the jit closures (and with it that engine's
#: current table pair, unless ``destroy()`` ran) — so it is BOUNDED:
#: insertion past ``_SCAN_MEMO_MAX`` evicts the oldest entry, keeping
#: the worst-case retention a fixed number of table pairs instead of
#: one per distinct config ever seen by the process.
_SCAN_MEMO: "dict" = {}
_SCAN_MEMO_MAX = 32


def _scan_memo_put(key, fn):
    while len(_SCAN_MEMO) >= _SCAN_MEMO_MAX:
        _SCAN_MEMO.pop(next(iter(_SCAN_MEMO)))
    _SCAN_MEMO[key] = fn
    return fn


#: Process-wide memo of the QUERY program family (pull, pull_average,
#: norms, multiply, and the per-k top-k / batch-top-k factories),
#: keyed on :meth:`EmbeddingEngine._query_memo_key` — the mesh
#: geometry plus the query-relevant engine attributes ONLY. Unlike the
#: scan memo, training-only attributes (negatives, compute dtype,
#: fused-kernel mode) are deliberately EXCLUDED from the key: two
#: models trained differently but serving the same (V, d) shape share
#: every compiled query program, because tables and norms are traced
#: ARGUMENTS to all of them (ISSUE 20 — loading model #2..N of a
#: same-shape catalog triggers zero new XLA compiles). Entries hold
#: only jit closures over specs and scalars, never table buffers.
_QUERY_MEMO: "dict" = {}
_QUERY_MEMO_MAX = 64

#: Process-wide first-seen (geometry, op, shape) set + build counter:
#: the number of REAL XLA query compiles this process has paid. A
#: per-engine ``query_compiles`` tick whose (op, shape) was already
#: seen under the same geometry is a shared-program cache hit, counted
#: on the engine as ``shared_program_hits`` instead.
_QUERY_SHAPES_SEEN: "set" = set()
_QUERY_PROGRAM_BUILDS = [0]


def query_program_builds() -> int:
    """Process-wide count of distinct query (op, shape-bucket) programs
    actually compiled — flat when a same-shape engine joins the warm
    family (the multi-model zero-compile assertion)."""
    return _QUERY_PROGRAM_BUILDS[0]


def _query_memo_put(key, fn):
    while len(_QUERY_MEMO) >= _QUERY_MEMO_MAX:
        _QUERY_MEMO.pop(next(iter(_QUERY_MEMO)))
    _QUERY_MEMO[key] = fn
    return fn

#: Floor of the top-k k-bucket family. Requested k is rounded up to
#: ``max(next_pow2(k), TOPK_MIN_K_BUCKET)`` (capped at padded_vocab) and
#: the result truncated to k, so every small-k request — num defaults,
#: analogy exclusion fudge, coalesced maxima — lands on ONE compiled
#: program instead of one per distinct k. Top-16 vs top-2 on device is
#: free; a serving-path recompile is seconds of tail latency.
TOPK_MIN_K_BUCKET = 16

#: Floor of the batched top-k Q-bucket family for Q > 1. Batches of
#: 2..7 queries pad to 8 rows: skinny (Q=2..4)-row gemms fall off the
#: fast blocked path on some backends (XLA CPU runs them ~6x SLOWER
#: than the same scoring at Q=8), and matmul units pad small batches
#: internally anyway. Q=1 keeps its own bucket — the dominant
#: low-concurrency shape, served by the bandwidth-bound matvec.
TOPK_MIN_Q_BUCKET = 8

#: Per-dispatch query cap of the approximate top-k path: the rerank
#: gathers (Q, nprobe * slots, d) rows, so Q is chunked to bound the
#: transient at ~tens of MB regardless of the serving coalescer's
#: max_batch. Buckets {1, 8, 16} cover every chunk.
ANN_MAX_Q = 16


def _rank1_payload(cpos_g, cneg_g, C: int, n: int):
    """(coefs, hidx) for the fused rank-1 scatter, matching the update
    ordering ids1_g = [contexts.flat | negs.flat] (rank-major batch axis).
    Shared by both layouts' step bodies — the ordering contract lives in
    exactly one place."""
    B = cpos_g.shape[0]
    coefs = jnp.concatenate([cpos_g.reshape(-1), cneg_g.reshape(-1)])
    hidx = jnp.concatenate([
        jnp.repeat(jnp.arange(B, dtype=jnp.int32), C),
        jnp.repeat(jnp.arange(B, dtype=jnp.int32), C * n),
    ])
    return coefs, hidx


def _apply_rank1_updates(
    syn1_l, ids1_g, cpos_g, cneg_g, h_g, C, n, pm, own_range=None
):
    """Apply the per-pair syn1 rank-1 updates, choosing between the fused
    Pallas scatter (Pallas mode on AND h_g fits the VMEM budget) and the
    dense outer-product payload. Returns (syn1_l, upd1_g) where upd1_g is
    None when the update was already applied (fused path) or the (N, d)
    payload for the caller's scatter otherwise. ``own_range=(start, Vs)``
    applies the rows layout's ownership masking; None = every row local
    (dims layout). ONE implementation for both step bodies — the fuse
    gate, payload ordering, and fallback stay in lockstep by construction.
    """
    fuse = (
        pm
        and h_g.shape[0] * h_g.shape[1] * 4 <= _RANK1_FUSE_VMEM_BYTES
        # scatter_add_rank1 accumulates runs in TABLE dtype; under bf16
        # storage take the payload path instead, whose scatter pre-sums
        # duplicates in fp32 (_dup_sum_f32) — round-once semantics.
        and jnp.dtype(syn1_l.dtype).itemsize >= 4
    )
    if fuse:
        from glint_word2vec_tpu.ops.pallas_rows import scatter_add_rank1

        coefs, hidx = _rank1_payload(cpos_g, cneg_g, C, n)
        ids = ids1_g
        if own_range is not None:
            start, Vs = own_range
            loc = ids1_g - start
            own = (loc >= 0) & (loc < Vs)
            coefs = jnp.where(own, coefs, 0.0)
            ids = jnp.clip(loc, 0, Vs - 1)
        syn1_l = scatter_add_rank1(
            syn1_l, ids, coefs, h_g, hidx, interpret=pm == 2
        )
        return syn1_l, None
    d = h_g.shape[-1]
    d_upos = cpos_g[..., None] * h_g[:, None, :]
    d_uneg = cneg_g[..., None] * h_g[:, None, None, :]
    upd1_g = jnp.concatenate(
        [d_upos.reshape(-1, d), d_uneg.reshape(-1, d)]
    )
    return syn1_l, upd1_g


class EmbeddingEngine:
    """Owns the sharded syn0/syn1 tables and all device-side ops.

    Args:
      mesh: a ("data", "model") mesh from parallel.mesh.make_mesh.
      vocab_size: unpadded vocabulary size.
      dim: embedding dimension (reference ``vectorSize``; ``matrix.cols``).
      counts: per-word corpus counts driving the noise distribution
        (the broadcast ``bcVocabCns`` the servers build their unigram table
        from, mllib:355; SURVEY.md §2.2 Word2VecArguments).
      num_negatives / unigram_power / unigram_table_size: noise geometry.
      seed: table-init seed.
      dtype: table dtype (float32 | bfloat16); compute is always float32.
    """

    def __init__(
        self,
        mesh,
        vocab_size: int,
        dim: int,
        counts: np.ndarray,
        *,
        num_negatives: int = 5,
        unigram_power: float = 0.75,
        unigram_table_size: Optional[int] = None,
        seed: int = 1,
        dtype: str = "float32",
        extra_rows: int = 0,
        shared_negatives: int = 0,
        use_pallas: Optional[bool] = None,
        compute_dtype: Optional[str] = None,
        layout: str = "rows",
    ):
        """``extra_rows`` appends non-vocabulary rows to both tables (e.g.
        fastText char-ngram buckets, models/fasttext.py): they are trained
        through subword center groups but are never negative-sampled (the
        noise table spans the vocab only) and never surface from the query
        ops (top-k masks them; norms/multiply callers slice).

        ``layout`` selects the model-axis partitioning:
          * "rows" (default): vocab rows split 1/n per shard, full width.
            Pulls psum whole rows over the model axis.
          * "dims": every shard holds ALL rows x 1/n of the columns — the
            CIKM'16 column partitioning the reference's servers use
            (SURVEY.md §2.2): gathers/scatters are shard-local, and the
            ONLY model-axis exchange in the train step is the psum of
            scalar logit partials (the dot products the reference's
            ``dotprod`` servers return). Per-chip HBM traffic for the
            sparse row accesses divides by the model-axis size.

        Guidance: per-chip table memory is identical (V*d/n either way).
        For TRAINING at num_model > 1, "dims" is the better default —
        its model-axis collectives are ~d/(1+overlap) times smaller and
        its sparse HBM traffic scales down with the axis. "rows" wins
        for query-heavy serving at huge vocab (top-k batch scores stay
        (Q, V/n) per shard instead of (Q, V)) and when d is too small to
        split usefully (d < 128 * num_model leaves sublane-starved
        slices). Both train bit-equivalently up to reduction order, and
        checkpoints re-home across layouts, so the choice is reversible.
        """
        if vocab_size <= 0 or dim <= 0:
            raise ValueError("vocab_size and dim must be > 0")
        if layout not in ("rows", "dims"):
            raise ValueError("layout must be 'rows' or 'dims'")
        if counts.shape != (vocab_size,):
            raise ValueError("counts must have shape (vocab_size,)")
        if extra_rows < 0:
            raise ValueError("extra_rows must be >= 0")
        if shared_negatives < 0:
            raise ValueError("shared_negatives must be >= 0")
        self.mesh = mesh
        self.vocab_size = int(vocab_size)
        self._seed = int(seed)  # graftlint: ignore[sync-point] host config scalar
        self.num_rows = int(vocab_size) + int(extra_rows)
        self.dim = int(dim)
        self.num_negatives = int(num_negatives)
        #: Shared-pool size S per step; 0 = per-pair draws (reference
        #: semantics). See ops.sgns.shared_sgns_grads for the estimator.
        self.shared_negatives = int(shared_negatives)
        self.unigram_power = float(unigram_power)
        self.unigram_table_size = unigram_table_size
        self._dtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
        # MXU operand dtype for the step's dense contractions (f32 accum
        # either way). Default f32 = exactness-tested reference numerics;
        # "bfloat16" is the MXU-native fast path (GLINT_W2V_MATMUL_DTYPE
        # env overrides when the ctor arg is unset).
        if compute_dtype is None:
            compute_dtype = os.environ.get(
                "GLINT_W2V_MATMUL_DTYPE", "float32"
            )
        self._compute_dtype = (
            jnp.bfloat16 if compute_dtype == "bfloat16" else jnp.float32
        )
        # Pallas row kernels for the sparse table traffic: opt-in per
        # engine or via GLINT_W2V_PALLAS=1; interpret mode off-TPU so the
        # same flag is testable on the CPU mesh.
        if use_pallas is None:
            use_pallas = os.environ.get("GLINT_W2V_PALLAS", "0") == "1"
        self._pallas_mode = 0
        if use_pallas:
            self._pallas_mode = 1 if jax.default_backend() == "tpu" else 2
        self.num_data = mesh.shape[DATA_AXIS]
        self.num_model = mesh.shape[MODEL_AXIS]
        self.layout = layout
        # Fused Pallas pair-step megakernel (ISSUE 11, ops/pallas_sgns):
        # rides the same pallas flag and replaces the composed pair-form
        # step body wherever every table row is shard-local — the rows
        # layout with an unsharded model axis (data parallelism is fine:
        # coefficients/h are all_gathered exactly like the composed
        # path). Model-sharded meshes keep the composed step (the fused
        # forward would need a mid-kernel logit psum). Escape hatch:
        # GLINT_W2V_PALLAS_FUSED=0 keeps the row kernels but not the
        # fused step.
        fused = (
            self._pallas_mode != 0
            and layout == "rows"
            and self.num_model == 1
            and os.environ.get("GLINT_W2V_PALLAS_FUSED", "1") == "1"
        )
        if fused and self.shared_negatives:
            from glint_word2vec_tpu.ops.pallas_sgns import (
                shared_pool_vmem_ok,
            )

            # The shared-pool forward pins the pool (storage + fp32) in
            # VMEM; an oversized pool falls back to the composed step.
            fused = shared_pool_vmem_ok(
                self.shared_negatives, self.dim, self._dtype
            )
        self._pallas_fused = bool(fused)
        if layout == "rows":
            self.padded_vocab = pad_to_multiple(self.num_rows, self.num_model)
            self.rows_per_shard = self.padded_vocab // self.num_model
            self.padded_dim = self.dim
            self.cols_per_shard = self.dim
        else:  # dims
            self.padded_vocab = self.num_rows  # no row padding needed
            self.rows_per_shard = self.num_rows
            self.padded_dim = pad_to_multiple(self.dim, self.num_model)
            self.cols_per_shard = self.padded_dim // self.num_model

        # Noise distribution over the *unpadded* vocab — draws are therefore
        # identical for every mesh shape (padding never enters sampling),
        # and padded rows can never be drawn as negatives.
        self._counts = np.asarray(counts, dtype=np.int64).copy()
        table = build_unigram_alias(
            self._counts, power=unigram_power, table_size=unigram_table_size
        )
        repl = NamedSharding(mesh, P())
        self._prob = jax.device_put(jnp.asarray(table.prob), repl)
        self._alias = jax.device_put(jnp.asarray(table.alias), repl)

        # Initialize tables directly sharded on-device (no host round-trip):
        # syn0 ~ U[-0.5/d, 0.5/d), syn1 = 0 (word2vec standard, ops/sgns.py).
        # Randoms are drawn for the unpadded rows/cols only, then
        # zero-padded, so initial values are layout- and mesh-shape-
        # invariant (a "dims" engine starts bitwise-equal to a "rows" one).
        # The init MUST trace with partitionable threefry: the legacy
        # (non-partitionable) lowering produces sharding-DEPENDENT random
        # values when GSPMD partitions the draw — on meshes with data > 1
        # and certain model-axis sizes the tables came up different from
        # every other mesh shape, breaking the seed -> identical-tables
        # contract (the two round-0 mesh-invariance test failures). Scoped
        # to this one jit so every other RNG stream (negatives, window
        # shrink) keeps its existing draws.
        tsh = self._table_sharding()
        V, Vp, d, dp = self.num_rows, self.padded_vocab, self.dim, self.padded_dim

        def _init(key):
            s0, s1 = sgns.init_tables(key, V, d, self._dtype)
            pad = ((0, Vp - V), (0, dp - d))
            return jnp.pad(s0, pad), jnp.pad(s1, pad)

        prev_partitionable = jax.config.jax_threefry_partitionable
        jax.config.update("jax_threefry_partitionable", True)
        try:
            self.syn0, self.syn1 = jax.jit(_init, out_shardings=(tsh, tsh))(
                jax.random.PRNGKey(seed)
            )
        finally:
            jax.config.update(
                "jax_threefry_partitionable", prev_partitionable
            )
        self._build_jitted_fns()

    def _table_sharding(self):
        return (
            table_sharding(self.mesh)
            if self.layout == "rows"
            else table_sharding_dims(self.mesh)
        )

    # ------------------------------------------------------------------
    # Jitted SPMD program construction
    # ------------------------------------------------------------------

    def _shard_map(self, f, in_specs, out_specs):
        try:
            return shard_map(
                f, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=False,
            )
        except TypeError:  # older jax spells the flag check_rep
            return shard_map(
                f, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
                check_rep=False,
            )

    def _build_jitted_fns(self) -> None:
        mesh = self.mesh
        Vs = self.rows_per_shard
        pm = self._pallas_mode
        n = self.num_negatives
        if self._pallas_fused:
            from glint_word2vec_tpu.ops import pallas_sgns
        else:
            pallas_sgns = None  # composed path never references it
        tspec = (
            P(MODEL_AXIS, None) if self.layout == "rows"
            else P(None, MODEL_AXIS)
        )
        rep = P()

        def fused_pair_body(syn0_l, syn1_l, prob, alias, centers,
                            contexts, mask, key, alpha):
            # Fused Pallas pair step (ISSUE 11): every table row is
            # shard-local (rows layout, num_model == 1), so the whole
            # update runs as ops/pallas_sgns kernels — gathers, dot,
            # sigmoid, and coefficient math in one VMEM-resident forward
            # pass, then id-sorted run-summing scatters with fp32
            # accumulation over the (fp32 or bf16) storage. Only the
            # data axis remains: the exchange ships the SAME compact
            # payload as the composed path (h, scalar coefficients,
            # int32 ids — the gPlus/gMinus wire format) plus the (P, d)
            # d_center rows the forward pass already materialized.
            Bl = centers.shape[0]
            drank = lax.axis_index(DATA_AXIS)
            interp = pm == 2
            a32 = alpha.astype(jnp.float32)
            cen_g = lax.all_gather(centers, DATA_AXIS, tiled=True)
            if self.shared_negatives:
                # ONE pool per step, identical on every rank (shared
                # key); the pool scoring and d_pool update run as dense
                # level-3 BLAS blocks inside the forward kernel.
                pool = sample_negatives(
                    key, prob, alias, (self.shared_negatives,)
                )
                fw = pallas_sgns.pair_forward_shared(
                    syn0_l, syn1_l, centers, contexts, mask, pool, a32,
                    n, interpret=interp,
                )
                cpos_g = lax.all_gather(fw.c_pos, DATA_AXIS, tiled=True)
                h_g = lax.all_gather(fw.h, DATA_AXIS, tiled=True)
                dcen_g = lax.all_gather(fw.d_center, DATA_AXIS, tiled=True)
                ctx_g = lax.all_gather(contexts, DATA_AXIS, tiled=True)
                # Pool contributions sum across data ranks; after the
                # psum the dense payload is identical everywhere.
                dpool_g = lax.psum(fw.d_pool, DATA_AXIS)
                P = cen_g.shape[0]
                syn1_l = pallas_sgns.scatter_add_rank1_hbm(
                    syn1_l, ctx_g, cpos_g, h_g,
                    jnp.arange(P, dtype=jnp.int32), interpret=interp,
                )
                syn1_l = pallas_sgns.scatter_add_rows_f32(
                    syn1_l, pool, dpool_g, interpret=interp
                )
            else:
                # Per-pair negatives, keyed by GLOBAL pair row — the
                # identical draw stream as the composed pair step.
                rows_g = drank * Bl + jnp.arange(Bl, dtype=jnp.int32)
                negs = sample_negatives_per_row(
                    key, prob, alias, rows_g, (1, n)
                )  # (Bl, 1, n)
                nmask = sgns.negative_mask(
                    negs, contexts[:, None], mask[:, None]
                )
                fw = pallas_sgns.pair_forward(
                    syn0_l, syn1_l, centers, contexts, mask,
                    negs[:, 0, :], nmask[:, 0, :], a32, interpret=interp,
                )
                cpos_g = lax.all_gather(fw.c_pos, DATA_AXIS, tiled=True)
                cneg_g = lax.all_gather(fw.c_neg, DATA_AXIS, tiled=True)
                h_g = lax.all_gather(fw.h, DATA_AXIS, tiled=True)
                dcen_g = lax.all_gather(fw.d_center, DATA_AXIS, tiled=True)
                ctx_g = lax.all_gather(contexts, DATA_AXIS, tiled=True)
                negs_g = lax.all_gather(
                    negs[:, 0, :], DATA_AXIS, tiled=True
                )
                P = cen_g.shape[0]
                rows_p = jnp.arange(P, dtype=jnp.int32)
                syn1_l = pallas_sgns.scatter_add_rank1_hbm(
                    syn1_l,
                    jnp.concatenate([ctx_g, negs_g.reshape(-1)]),
                    jnp.concatenate([cpos_g, cneg_g.reshape(-1)]),
                    h_g,
                    jnp.concatenate([rows_p, jnp.repeat(rows_p, n)]),
                    interpret=interp,
                )
            syn0_l = pallas_sgns.scatter_add_rows_f32(
                syn0_l, cen_g, dcen_g, interpret=interp
            )
            # Same global masked-mean as the composed body: the kernel
            # returns the SUM form directly.
            denom = mask.sum()
            loss = lax.psum(fw.loss_sum, DATA_AXIS) / jnp.maximum(
                lax.psum(denom, DATA_AXIS), 1.0
            )
            return syn0_l, syn1_l, loss

        def step_body_rows(syn0_l, syn1_l, prob, alias, centers, cmask,
                           contexts, mask, key, alpha):
            # Data-sharded inputs: centers/cmask (Bl, S), contexts/mask
            # (Bl, C). S = subword-group width; word-level training is the
            # S=1 specialization. The center representation is the masked
            # mean of its group's syn0 rows (fastText composition; for S=1
            # this is exactly the plain word vector).
            Bl, S = centers.shape
            C = contexts.shape[1]
            if self._pallas_fused and S == 1 and C == 1:
                # Dense pair form (the packed corpus scan / pair-step
                # callers): the fused Pallas megakernel path. S/C are
                # static python ints, so grid-shaped and subword-grouped
                # traces keep the composed body below.
                return fused_pair_body(
                    syn0_l, syn1_l, prob, alias, centers[:, 0],
                    contexts[:, 0], mask[:, 0], key, alpha,
                )
            start = lax.axis_index(MODEL_AXIS) * Vs
            drank = lax.axis_index(DATA_AXIS)

            h_rows = _pull_rows(syn0_l, centers.reshape(-1), start, Vs, pm)
            h_rows = h_rows.reshape(Bl, S, -1)
            cnt = jnp.maximum(cmask.sum(axis=1, keepdims=True), 1.0)  # (Bl,1)
            h = (h_rows * cmask[..., None]).sum(axis=1) / cnt
            u_pos = _pull_rows(syn1_l, contexts.reshape(-1), start, Vs, pm)
            u_pos = u_pos.reshape(Bl, C, -1)

            # The data-axis exchange ships ONLY h (B, d), scalar gradient
            # coefficients, and int32 indices — the TPU restatement of the
            # reference's defining ship-scalars property (gPlus/gMinus,
            # mllib:422-425). The O(B*C*(1+n)*d) rank-1 payloads are never
            # exchanged: every consuming shard re-forms coef x h outer
            # products locally, where XLA fuses them into the scatter-add.
            h_g = lax.all_gather(h, DATA_AXIS, tiled=True)  # (B, d)

            if self.shared_negatives:
                # Shared-pool mode: ONE pool of P negatives per step,
                # identical on every rank (drawn from the shared key — the
                # mesh-invariance contract needs no slicing here), scored
                # and updated by dense MXU matmuls instead of B*C*n sparse
                # row accesses (ops.sgns.shared_sgns_grads).
                pool = sample_negatives(
                    key, prob, alias, (self.shared_negatives,)
                )
                u_pool = _pull_rows(syn1_l, pool, start, Vs, pm)
                collide = sgns.pool_collision_mask(pool, contexts, mask)
                g = sgns.shared_sgns_grads(
                    h, u_pos, u_pool, mask, collide,
                    alpha.astype(jnp.float32), n,
                    compute_dtype=self._compute_dtype,
                )
                # The pool update sums contributions from every data rank;
                # after the psum it is identical everywhere, so each model
                # shard applies its owned slice exactly once per replica.
                d_pool = lax.psum(g.d_pool, DATA_AXIS)
                ids1 = lax.all_gather(
                    contexts.reshape(-1), DATA_AXIS, tiled=True
                )
                cpos_g = lax.all_gather(g.c_pos, DATA_AXIS, tiled=True)
                d_upos = cpos_g[..., None] * h_g[:, None, :]
                ids1_g = jnp.concatenate([ids1, pool])
                upd1_g = jnp.concatenate(
                    [d_upos.reshape(-1, d_upos.shape[-1]), d_pool]
                )
            else:
                # Per-pair mode (reference semantics): n fresh negatives
                # per (center, context) pair, keyed by GLOBAL row index so
                # draws are mesh-invariant while each rank samples only its
                # own Bl rows (ops.sampling.sample_negatives_per_row).
                rows_g = drank * Bl + jnp.arange(Bl, dtype=jnp.int32)
                negs = sample_negatives_per_row(
                    key, prob, alias, rows_g, (C, n)
                )
                u_neg = _pull_rows(syn1_l, negs.reshape(-1), start, Vs, pm)
                u_neg = u_neg.reshape(Bl, C, n, -1)
                nmask = sgns.negative_mask(negs, contexts, mask)
                g = sgns.sgns_grads(h, u_pos, u_neg, mask, nmask,
                                    alpha.astype(jnp.float32),
                                    compute_dtype=self._compute_dtype)

                ctx_g = lax.all_gather(contexts, DATA_AXIS, tiled=True)
                negs_g = lax.all_gather(negs, DATA_AXIS, tiled=True)
                cpos_g = lax.all_gather(g.c_pos, DATA_AXIS, tiled=True)
                cneg_g = lax.all_gather(g.c_neg, DATA_AXIS, tiled=True)
                ids1_g = jnp.concatenate(
                    [ctx_g.reshape(-1), negs_g.reshape(-1)]
                )
                # Fused Pallas scatter (payload formed in VMEM) when
                # eligible, else consumer-side outer products; ownership
                # masking for this rows layout via own_range.
                syn1_l, upd1_g = _apply_rank1_updates(
                    syn1_l, ids1_g, cpos_g, cneg_g, h_g, C, n, pm,
                    own_range=(start, Vs),
                )

            # The center gradient is distributed over the group's rows
            # (d mean / d row = 1/count): ship the (Bl, d) gradient + the
            # (Bl, S) group mask, expand to rows at the consumer.
            dcen_g = lax.all_gather(g.d_center / cnt, DATA_AXIS, tiled=True)
            cmask_g = lax.all_gather(cmask, DATA_AXIS, tiled=True)
            ids0_g = lax.all_gather(centers.reshape(-1), DATA_AXIS, tiled=True)
            upd0_g = (dcen_g[:, None, :] * cmask_g[..., None]).reshape(
                -1, dcen_g.shape[-1]
            )
            syn0_l = _scatter_rows(syn0_l, ids0_g, upd0_g, start, Vs, pm)
            if upd1_g is not None:
                syn1_l = _scatter_rows(syn1_l, ids1_g, upd1_g, start, Vs, pm)

            # Masked-mean loss over the global batch.
            denom = mask.sum()
            loss_sum = g.loss * jnp.maximum(denom, 1.0)
            loss = lax.psum(loss_sum, DATA_AXIS) / jnp.maximum(
                lax.psum(denom, DATA_AXIS), 1.0
            )
            return syn0_l, syn1_l, loss

        def step_body_dims(syn0_l, syn1_l, prob, alias, centers, cmask,
                           contexts, mask, key, alpha):
            # Column-sharded step (CIKM'16 partitioning, SURVEY.md §2.2):
            # tables are (V, dl) local column slices with EVERY row
            # resident, so gathers and scatter-adds are shard-local. The
            # only model-axis communication is the psum of scalar logit
            # partials — exactly the partial dot products the reference's
            # servers return from ``dotprod``. The data-axis exchange is
            # the same scalars+h contract as the rows layout, with h now
            # a (B, dl) column slice (1/n the bytes per chip).
            Bl, S = centers.shape
            C = contexts.shape[1]
            drank = lax.axis_index(DATA_AXIS)
            cd = self._compute_dtype

            h_rows = syn0_l[centers.reshape(-1)].astype(jnp.float32)
            h_rows = h_rows.reshape(Bl, S, -1)
            cnt = jnp.maximum(cmask.sum(axis=1, keepdims=True), 1.0)
            h = (h_rows * cmask[..., None]).sum(axis=1) / cnt  # (Bl, dl)
            u_pos = syn1_l[contexts.reshape(-1)].astype(jnp.float32)
            u_pos = u_pos.reshape(Bl, C, -1)

            h_g = lax.all_gather(h, DATA_AXIS, tiled=True)  # (B, dl)

            if self.shared_negatives:
                pool = sample_negatives(
                    key, prob, alias, (self.shared_negatives,)
                )
                u_pool = syn1_l[pool].astype(jnp.float32)  # (S, dl)
                collide = sgns.pool_collision_mask(pool, contexts, mask)
                f_pos = lax.psum(
                    jnp.einsum(
                        "bd,bcd->bc", h.astype(cd), u_pos.astype(cd),
                        preferred_element_type=jnp.float32,
                    ),
                    MODEL_AXIS,
                )
                f_pool = lax.psum(
                    jnp.dot(
                        h.astype(cd), u_pool.astype(cd).T,
                        preferred_element_type=jnp.float32,
                    ),
                    MODEL_AXIS,
                )
                co = sgns.shared_sgns_coefs(
                    f_pos, f_pool, mask, collide,
                    alpha.astype(jnp.float32), n,
                )
                d_center_l, d_pool_l = sgns.shared_sgns_updates(
                    co.c_pos, co.c_pool, h, u_pos, u_pool, cd
                )
                d_pool_g = lax.psum(d_pool_l, DATA_AXIS)  # (S, dl)
                ids1 = lax.all_gather(
                    contexts.reshape(-1), DATA_AXIS, tiled=True
                )
                cpos_g = lax.all_gather(co.c_pos, DATA_AXIS, tiled=True)
                d_upos = cpos_g[..., None] * h_g[:, None, :]
                ids1_g = jnp.concatenate([ids1, pool])
                upd1_g = jnp.concatenate(
                    [d_upos.reshape(-1, d_upos.shape[-1]), d_pool_g]
                )
                loss_local = co.loss
            else:
                rows_g = drank * Bl + jnp.arange(Bl, dtype=jnp.int32)
                negs = sample_negatives_per_row(
                    key, prob, alias, rows_g, (C, n)
                )
                u_neg = syn1_l[negs.reshape(-1)].astype(jnp.float32)
                u_neg = u_neg.reshape(Bl, C, n, -1)
                nmask = sgns.negative_mask(negs, contexts, mask)
                f_pos = lax.psum(
                    jnp.einsum(
                        "bd,bcd->bc", h.astype(cd), u_pos.astype(cd),
                        preferred_element_type=jnp.float32,
                    ),
                    MODEL_AXIS,
                )
                f_neg = lax.psum(
                    jnp.einsum(
                        "bd,bcnd->bcn", h.astype(cd), u_neg.astype(cd),
                        preferred_element_type=jnp.float32,
                    ),
                    MODEL_AXIS,
                )
                co = sgns.sgns_coefs(
                    f_pos, f_neg, mask, nmask, alpha.astype(jnp.float32)
                )
                d_center_l = sgns.sgns_d_center(
                    co.c_pos, co.c_neg, u_pos, u_neg, cd
                )
                ctx_g = lax.all_gather(contexts, DATA_AXIS, tiled=True)
                negs_g = lax.all_gather(negs, DATA_AXIS, tiled=True)
                cpos_g = lax.all_gather(co.c_pos, DATA_AXIS, tiled=True)
                cneg_g = lax.all_gather(co.c_neg, DATA_AXIS, tiled=True)
                ids1_g = jnp.concatenate(
                    [ctx_g.reshape(-1), negs_g.reshape(-1)]
                )
                # Every row is local under dims: no own_range masking.
                syn1_l, upd1_g = _apply_rank1_updates(
                    syn1_l, ids1_g, cpos_g, cneg_g, h_g, C, n, pm
                )
                loss_local = co.loss

            dcen_g = lax.all_gather(d_center_l / cnt, DATA_AXIS, tiled=True)
            cmask_g = lax.all_gather(cmask, DATA_AXIS, tiled=True)
            ids0_g = lax.all_gather(
                centers.reshape(-1), DATA_AXIS, tiled=True
            )
            upd0_g = (dcen_g[:, None, :] * cmask_g[..., None]).reshape(
                -1, dcen_g.shape[-1]
            )
            # Every row is local: plain scatter-adds, no ownership masks
            # (fp32 duplicate-row sums under bf16 storage, see
            # _bf16_safe_scatter_add).
            syn0_l = _bf16_safe_scatter_add(syn0_l, ids0_g, upd0_g)
            if upd1_g is not None:
                syn1_l = _bf16_safe_scatter_add(syn1_l, ids1_g, upd1_g)

            denom = mask.sum()
            loss_sum = loss_local * jnp.maximum(denom, 1.0)
            loss = lax.psum(loss_sum, DATA_AXIS) / jnp.maximum(
                lax.psum(denom, DATA_AXIS), 1.0
            )
            return syn0_l, syn1_l, loss

        step_body = (
            step_body_rows if self.layout == "rows" else step_body_dims
        )

        self._train_step = jax.jit(
            self._shard_map(
                step_body,
                in_specs=(tspec, tspec, rep, rep, P(DATA_AXIS, None),
                          P(DATA_AXIS, None), P(DATA_AXIS, None),
                          P(DATA_AXIS, None), rep, rep),
                out_specs=(tspec, tspec, rep),
            ),
            donate_argnums=(0, 1),
        )

        def local_train_scan(syn0_l, syn1_l, prob, alias, centers_k, cmask_k,
                             contexts_k, mask_k, base_key, step0, alphas_k):
            # K stacked minibatches executed by one on-device lax.scan —
            # one dispatch + one host->device transfer per K steps instead
            # of per step. Per-step keys are fold_in(base_key, step0 + i),
            # the same derivation the single-step caller uses, so a scanned
            # run and a step-at-a-time run of the same schedule draw
            # identical negatives.
            def body(carry, xs):
                s0, s1 = carry
                centers, cmask, contexts, mask, i, alpha = xs
                key = jax.random.fold_in(base_key, step0 + i)
                s0, s1, loss = step_body(
                    s0, s1, prob, alias, centers, cmask, contexts, mask,
                    key, alpha,
                )
                return (s0, s1), loss

            K = alphas_k.shape[0]
            (syn0_l, syn1_l), losses = lax.scan(
                body,
                (syn0_l, syn1_l),
                (centers_k, cmask_k, contexts_k, mask_k,
                 jnp.arange(K, dtype=jnp.uint32), alphas_k),
            )
            return syn0_l, syn1_l, losses

        # jit specializes on the leading scan length K.
        self._train_scan = jax.jit(
            self._shard_map(
                local_train_scan,
                in_specs=(tspec, tspec, rep, rep,
                          P(None, DATA_AXIS, None), P(None, DATA_AXIS, None),
                          P(None, DATA_AXIS, None), P(None, DATA_AXIS, None),
                          rep, rep, rep),
                out_specs=(tspec, tspec, rep),
            ),
            donate_argnums=(0, 1),
        )

        num_data = self.num_data
        self._corpus_scan_cache: dict = {}
        self._ones_mask_cache: dict = {}

        def make_corpus_scan(B: int, W: int):
            # Corpus-resident scan: batches are assembled ON DEVICE from
            # the uploaded flat corpus (ops/device_batching) — the only
            # per-dispatch host->device traffic is scalars. Step i of the
            # scan covers global center positions
            # [pstart + i*B, pstart + (i+1)*B); this rank materializes
            # only its Bl = B/num_data rows. Keys follow the exact
            # fold_in(base_key, step0 + i) schedule of local_train_scan,
            # so negatives match a host-batched run step for step.
            # ``n_valid`` (the corpus-end bound) is a TRACED scalar so
            # the subsampled path's per-epoch n_kept shares this one
            # compile with the full-corpus path.
            from glint_word2vec_tpu.ops.device_batching import (
                device_window_batch,
            )

            Bl = B // num_data

            def local_corpus_scan(syn0_l, syn1_l, prob, alias, ids, soffs,
                                  n_valid, pstart, base_key, step0,
                                  alphas_k):
                drank = lax.axis_index(DATA_AXIS)
                rows_l = (drank * Bl + jnp.arange(Bl)).astype(jnp.int32)

                def body(carry, xs):
                    s0, s1 = carry
                    i, alpha = xs
                    key = jax.random.fold_in(base_key, step0 + i)
                    positions = (
                        pstart + jnp.int32(i) * jnp.int32(B) + rows_l
                    )
                    centers, contexts, mask = device_window_batch(
                        ids, soffs, positions, rows_l, key, W,
                        n_valid=n_valid,
                    )
                    cmask = jnp.ones((Bl, 1), jnp.float32)
                    s0, s1, loss = step_body(
                        s0, s1, prob, alias, centers[:, None], cmask,
                        contexts, mask, key, alpha,
                    )
                    return (s0, s1), loss

                K = alphas_k.shape[0]
                (syn0_l, syn1_l), losses = lax.scan(
                    body,
                    (syn0_l, syn1_l),
                    (jnp.arange(K, dtype=jnp.uint32), alphas_k),
                )
                return syn0_l, syn1_l, losses

            return jax.jit(
                self._shard_map(
                    local_corpus_scan,
                    in_specs=(tspec, tspec, rep, rep, rep, rep,
                              rep, rep, rep, rep, rep),
                    out_specs=(tspec, tspec, rep),
                ),
                donate_argnums=(0, 1),
            )

        self._make_corpus_scan = make_corpus_scan
        self._packed_scan_cache: dict = {}

        def make_packed_corpus_scan(P: int, W: int, B_grid: int, S: int,
                                    K: int):
            # PACKED corpus-resident scan (ISSUE 4): instead of a (B, C)
            # context grid that is ~57% masked lanes, each step assembles
            # windows over an oversized candidate span of center
            # positions, prefix-sum-compacts the valid (center, context)
            # pairs into a DENSE (P,) pair list
            # (ops/device_batching.pack_window_pairs), and runs the
            # step body in its pair form — batch rows ARE pairs (C=1), so
            # no contraction lane is masked padding. The position counter
            # advances data-dependently by whole consumed positions and is
            # carried through the scan; the LR alpha is derived on device
            # from the traced consumed-position count via the same
            # pre-subsampling words_done rule the host uses
            # (device_words_done == corpus_words_done_compacted). The
            # assembly is computed replicated on every rank (it is a
            # deterministic function of replicated inputs — mesh-invariant
            # by construction); each data rank then slices its own
            # Pl = P/num_data pair rows, and negatives are keyed by GLOBAL
            # pair row exactly like every other path
            # (sample_negatives_per_row discipline). Window-shrink draws
            # reproduce the grid scan's position->draw mapping
            # (grid_window_shrink), so the packed stream trains the exact
            # same valid-pair multiset as the grid path at the same
            # (B_grid, key schedule) — the parity gate that keeps "grid"
            # the default until it holds.
            from glint_word2vec_tpu.ops.device_batching import (
                device_words_done,
                pack_window_pairs,
            )

            Pl = P // num_data

            def local_packed_scan(syn0_l, syn1_l, prob, alias, ids, soffs,
                                  orig_offs, n_valid, pstart, base_key,
                                  step0, grid_step0, step_size,
                                  inv_total_words, words_base):
                drank = lax.axis_index(DATA_AXIS)

                def body(carry, i):
                    s0, s1, pos = carry
                    key = jax.random.fold_in(base_key, step0 + i)
                    pc, px, pm, n_cons, n_pairs = pack_window_pairs(
                        ids, soffs, pos, base_key, grid_step0,
                        window=W, span=S, pair_batch=P, grid_batch=B_grid,
                        n_valid=n_valid,
                    )
                    pos_end = pos + n_cons
                    done = device_words_done(
                        orig_offs, soffs, pos_end, n_valid
                    )
                    wd = words_base + done.astype(jnp.float32)
                    alpha = jnp.maximum(
                        step_size * (1.0 - wd * inv_total_words),
                        step_size * 1e-4,
                    )
                    c_l = lax.dynamic_slice_in_dim(pc, drank * Pl, Pl)
                    x_l = lax.dynamic_slice_in_dim(px, drank * Pl, Pl)
                    m_l = lax.dynamic_slice_in_dim(pm, drank * Pl, Pl)
                    cmask = jnp.ones((Pl, 1), jnp.float32)
                    s0, s1, loss = step_body(
                        s0, s1, prob, alias, c_l[:, None], cmask,
                        x_l[:, None], m_l[:, None], key, alpha,
                    )
                    return (s0, s1, pos_end), (loss, n_pairs, pos_end, alpha)

                (syn0_l, syn1_l, _), ys = lax.scan(
                    body,
                    (syn0_l, syn1_l, pstart),
                    jnp.arange(K, dtype=jnp.uint32),
                )
                losses, pair_counts, pos_ends, alphas = ys
                return syn0_l, syn1_l, losses, pair_counts, pos_ends, alphas

            return jax.jit(
                self._shard_map(
                    local_packed_scan,
                    in_specs=(tspec, tspec) + (rep,) * 13,
                    out_specs=(tspec, tspec, rep, rep, rep, rep),
                ),
                donate_argnums=(0, 1),
            )

        self._make_packed_corpus_scan = make_packed_corpus_scan

        dims = self.layout == "dims"
        dcols = self.cols_per_shard
        dim_real = self.dim

        def shared_query_program(op, build):
            """Process-level program sharing (ISSUE 20): same-geometry
            engines reuse one jitted callable — and with it one XLA
            compile cache — because tables/norms/scalars are all traced
            arguments. The closure the memo retains captures only specs
            and host scalars, never device buffers."""
            key = self._query_memo_key(op)
            fn = _QUERY_MEMO.get(key)
            if fn is None:
                fn = _query_memo_put(key, build())
            return fn

        def local_pull(table_l, idx):
            if dims:
                rows = table_l[idx].astype(jnp.float32)  # (L, dl)
                full = lax.all_gather(
                    rows, MODEL_AXIS, tiled=True, axis=1
                )  # (L, padded_dim)
                return full[:, :dim_real]
            start = lax.axis_index(MODEL_AXIS) * Vs
            return _pull_rows(table_l, idx, start, Vs, pm)

        self._pull = shared_query_program("pull", lambda: jax.jit(
            self._shard_map(local_pull, in_specs=(tspec, rep), out_specs=rep)
        ))

        def local_pull_average(table_l, idx, m):
            # idx/m: (S, L) padded sentence word-indices + validity mask.
            S, L = idx.shape
            if dims:
                rows = table_l[idx.reshape(-1)].astype(jnp.float32)
                rows = rows.reshape(S, L, -1) * m[..., None]
                mean_l = rows.sum(axis=1) / jnp.maximum(
                    m.sum(axis=1)[:, None], 1.0
                )  # (S, dl): the server-side partial mean
                full = lax.all_gather(mean_l, MODEL_AXIS, tiled=True, axis=1)
                return full[:, :dim_real]
            start = lax.axis_index(MODEL_AXIS) * Vs
            rows = _pull_rows(table_l, idx.reshape(-1), start, Vs, pm)
            rows = rows.reshape(S, L, -1) * m[..., None]
            return rows.sum(axis=1) / jnp.maximum(
                m.sum(axis=1)[:, None], 1.0
            )

        self._pull_average = shared_query_program(
            "pull_average", lambda: jax.jit(
                self._shard_map(
                    local_pull_average, in_specs=(tspec, rep, rep),
                    out_specs=rep,
                )
            )
        )

        def local_norms(table_l):
            if dims:
                # Partial sum of squares over local columns, reduced over
                # the model axis; output replicated.
                sq = (table_l.astype(jnp.float32) ** 2).sum(axis=1)
                return jnp.sqrt(lax.psum(sq, MODEL_AXIS))
            # Shard-local, no communication: output stays model-sharded.
            return jnp.sqrt(
                (table_l.astype(jnp.float32) ** 2).sum(axis=1)
            )

        self._norms = shared_query_program("norms", lambda: jax.jit(
            self._shard_map(
                local_norms, in_specs=(tspec,),
                out_specs=rep if dims else P(MODEL_AXIS),
            )
        ))

        def _local_cols(v):
            # Slice the replicated padded query vector down to this
            # shard's column block.
            mrank = lax.axis_index(MODEL_AXIS)
            return lax.dynamic_slice_in_dim(v, mrank * dcols, dcols)

        def local_multiply(table_l, v):
            if dims:
                # Partial dot over local columns -> psum: exactly the
                # reference servers' partial-dot-product contract.
                return lax.psum(
                    table_l.astype(jnp.float32) @ _local_cols(v), MODEL_AXIS
                )
            # Distributed matvec: each shard scores its own rows (the TP
            # matvec noted in SURVEY.md §2.3); output model-sharded.
            return table_l.astype(jnp.float32) @ v

        self._multiply = shared_query_program("multiply", lambda: jax.jit(
            self._shard_map(
                local_multiply, in_specs=(tspec, rep),
                out_specs=rep if dims else P(MODEL_AXIS),
            )
        ))

        norms_spec = rep if dims else P(MODEL_AXIS)

        def _mask_terms(norms_l, start, n_queryable):
            # Cosine masking as one multiply + one add instead of a
            # division plus two (.., V)-wide boolean selects: inv is the
            # reciprocal norm (0 on masked rows), neg pins masked rows
            # at -inf. Zero-norm rows must never outrank a real word
            # with negative cosine (the reference's zero-norm guard at
            # mllib:603-609 only had to avoid a 0/0); likewise rows at or
            # past ``n_queryable`` (padding / subword buckets / spare
            # extra rows not yet assigned a streaming word): only real
            # words may surface from similarity search. ``n_queryable``
            # is a TRACED scalar — vocab_size + assigned extra rows —
            # so online vocab growth (streaming hot-swap, ISSUE 10)
            # widens the mask without recompiling any warmed top-k
            # program. Both vectors are (V,) so the per-score work is a
            # fused multiply-add — on the serving path this cut batch
            # top-k time ~30% (SERVING_BENCH).
            ok = (norms_l > 0) & (
                start + jnp.arange(norms_l.shape[0]) < n_queryable
            )
            inv = jnp.where(ok, 1.0 / jnp.where(norms_l > 0, norms_l, 1.0), 0.0)
            neg = jnp.where(ok, 0.0, -jnp.inf)
            return inv, neg

        def make_topk(k: int):
            def local_topk(table_l, v, norms_l, nq):
                if dims:
                    # Partial scores over local columns, psum'd to full
                    # cosine scores (replicated), then ranked. The psum
                    # moves V floats of scalars — never rows.
                    scores = lax.psum(
                        table_l.astype(jnp.float32) @ _local_cols(v),
                        MODEL_AXIS,
                    )  # (V,)
                    inv, neg = _mask_terms(norms_l, 0, nq)
                    val, idx = lax.top_k(
                        scores * inv + neg, min(k, scores.shape[0])
                    )
                    return val, idx
                # Cosine top-k without materializing all V scores on one
                # device: local top-k per shard, all_gather the M*k
                # candidates, merge. Replaces the reference's full-vocab
                # driver-side scan (mllib:601-617).
                start = lax.axis_index(MODEL_AXIS) * Vs
                kk = min(k, Vs)
                scores = table_l.astype(jnp.float32) @ v
                inv, neg = _mask_terms(norms_l, start, nq)
                val, idx = lax.top_k(scores * inv + neg, kk)
                cand_val = lax.all_gather(val, MODEL_AXIS, tiled=True)
                cand_idx = lax.all_gather(idx + start, MODEL_AXIS, tiled=True)
                mval, mpos = lax.top_k(cand_val, min(k, cand_val.shape[0]))
                return mval, cand_idx[mpos]

            return jax.jit(
                self._shard_map(
                    local_topk,
                    in_specs=(tspec, rep, norms_spec, rep),
                    out_specs=(rep, rep),
                )
            )

        def make_topk_batch(k: int):
            def local_topk_batch(table_l, q, norms_l, nq):
                # Scores are computed as (table @ q.T).T, not q @ table.T:
                # the tall-skinny orientation streams the row-major table
                # once (bandwidth-bound like the single-query matvec) —
                # 2x faster for small Q buckets on CPU, a wash at Q=16+.
                if dims:
                    # q arrives padded to (Q, padded_dim); each shard
                    # scores its column block, psum -> full scores. The
                    # public method chunks Q so (Q, V) stays bounded.
                    mrank = lax.axis_index(MODEL_AXIS)
                    q_l = lax.dynamic_slice_in_dim(
                        q, mrank * dcols, dcols, axis=1
                    )
                    scores = lax.psum(
                        (table_l.astype(jnp.float32) @ q_l.T).T, MODEL_AXIS
                    )  # (Q, V)
                    inv, neg = _mask_terms(norms_l, 0, nq)
                    val, idx = lax.top_k(
                        scores * inv[None, :] + neg[None, :],
                        min(k, scores.shape[1]),
                    )
                    return val, idx
                # q: (Q, d) replicated query batch. Same candidate-merge
                # scheme as the single-vector kernel, vectorized over Q —
                # one MXU matmul scores all queries against this shard.
                start = lax.axis_index(MODEL_AXIS) * Vs
                kk = min(k, Vs)
                scores = (table_l.astype(jnp.float32) @ q.T).T  # (Q, Vs)
                inv, neg = _mask_terms(norms_l, start, nq)
                val, idx = lax.top_k(
                    scores * inv[None, :] + neg[None, :], kk
                )  # (Q, kk)
                cand_val = lax.all_gather(
                    val, MODEL_AXIS, tiled=True, axis=1
                )
                cand_idx = lax.all_gather(
                    idx + start, MODEL_AXIS, tiled=True, axis=1
                )
                mval, mpos = lax.top_k(
                    cand_val, min(k, cand_val.shape[1])
                )
                return mval, jnp.take_along_axis(cand_idx, mpos, axis=1)

            return jax.jit(
                self._shard_map(
                    local_topk_batch,
                    in_specs=(tspec, rep, norms_spec, rep),
                    out_specs=(rep, rep),
                )
            )

        self._topk_cache: dict = {}
        self._topk_batch_cache: dict = {}
        # The per-k factories consult the process memo first: a
        # same-geometry engine's k-bucket family is the SAME jitted
        # callable (tables/norms/queryable are traced arguments), so a
        # second same-shape model inherits every warmed top-k program.
        self._make_topk = lambda k: shared_query_program(
            # graftlint: ignore[sync-point] k is a host int bucket key
            ("topk", int(k)), lambda: make_topk(int(k))
        )
        self._make_topk_batch = lambda k: shared_query_program(
            # graftlint: ignore[sync-point] k is a host int bucket key
            ("topk_batch", int(k)), lambda: make_topk_batch(int(k))
        )
        # Query-shape compile accounting: every distinct (op, shape
        # bucket) a query op dispatches is one XLA compile (jit
        # specializes on shape). The serving layer pads its dispatches
        # to power-of-two buckets, so post-warmup this set stops
        # growing — the /metrics zero-compile contract (ISSUE 2).
        self._query_shapes: set = set()
        self.query_compiles: int = 0
        #: First-seen shapes on THIS engine whose program was already
        #: compiled process-wide by a same-geometry engine (the shared
        #: warm family, ISSUE 20): a ``query_compiles`` tick that cost
        #: zero XLA work.
        self.shared_program_hits: int = 0
        # Lazy norms cache, invalidated by any table mutation — the engine-
        # side analogue of the reference's cached ``wordVecNorms``
        # (mllib:486). ``table_version`` ticks on the same mutations so
        # layers above (the serving result cache) can validate anything
        # derived from table values without holding device buffers.
        self._norms_cache = None
        self.table_version = 0
        #: Device-resident coarse index for approximate top-k (ISSUE
        #: 12): built via configure_ann()+ann_build(), flipped live by
        #: adopt_ann() — None keeps every query exact.
        self._ann = None
        self._ann_conf = None
        #: Spare extra rows claimed for runtime vocabulary growth
        #: (ISSUE 10 streaming): rows [vocab_size, vocab_size +
        #: extra_rows_assigned) hold words assigned online via
        #: :meth:`assign_extra_row` and ARE queryable (the top-k mask
        #: bound is the traced ``queryable_rows`` scalar, so growth
        #: never recompiles a warmed program). FastText bucket rows are
        #: NOT assigned this way and stay masked.
        self.extra_rows_assigned = 0
        # Non-blocking checkpoint machinery (ISSUE 5): the single
        # background writer (lazily created by save_async) and the
        # commit telemetry the heartbeat surfaces.
        self._ckpt_writer = None
        self._ckpt_last_commit: Optional[float] = None
        self._ckpt_last_write_s: Optional[float] = None
        self._ckpt_forced_sync = 0
        # Pre-dispatched next-epoch subsample-compact pass (ISSUE 5
        # prefetch overlap): (epoch_key host copy, ids_c, offsets_c,
        # n_kept) awaiting adoption by compact_corpus.
        self._compact_prefetch = None
        # Touched-row replica-exchange telemetry (ISSUE 15,
        # parallel/exchange.py): per-engine counters surfaced on the
        # heartbeat and summed into the gang rollup.
        self._exchange_stats = {
            "exchange_bytes_total": 0,
            "exchange_rows_total": 0,
            "exchange_overflow_total": 0,
            "exchange_syncs_total": 0,
            "exchange_dense_syncs_total": 0,
            "exchange_last_seconds": None,
            # ISSUE 16 wire-layer telemetry: payload bytes by wire
            # encoding (dense/spill/flush rounds count as fp32 — that
            # is what they ship), dispatch groups folded into rounds
            # by coalescing, checkpoint flush rounds, world=1 skipped
            # rounds, per-hop byte split for the two-level topology,
            # the live capacity gauge with its adaptation counters,
            # and the error-feedback residual high-water gauge.
            "exchange_bytes_wire_fp32_total": 0,
            "exchange_bytes_wire_bf16_total": 0,
            "exchange_bytes_wire_int8_total": 0,
            "exchange_groups_total": 0,
            "exchange_flushes_total": 0,
            "exchange_world1_skips_total": 0,
            "exchange_intra_bytes_total": 0,
            "exchange_inter_bytes_total": 0,
            "exchange_capacity": None,
            "exchange_capacity_grows_total": 0,
            "exchange_capacity_shrinks_total": 0,
            "exchange_residual_abs": 0.0,
        }
        # Per-shard checkpoint bookkeeping (ISSUE 15): which shard
        # files are dirty since the last committed save (None = all —
        # the safe default every generic table mutation restores; the
        # exchange apply narrows it to the rows a round touched), the
        # path those clean bits describe, and the skip/streaming
        # telemetry checkpoint_stats surfaces.
        self._shard_dirty = None
        self._shard_clean_path = None
        self._ckpt_shards_skipped = 0
        self._ckpt_shard_write_s: Optional[float] = None
        self._ckpt_shard_verify_s: Optional[float] = None
        self._ckpt_peak_block_bytes = 0
        self._stage_peak_block_bytes = 0
        # Replica save split (rank, world): under replica-exchange
        # training every rank holds the FULL reconciled table; the
        # sharded save then splits rows into ``world`` blocks and each
        # rank writes only its own — rank-parallel checkpoint I/O with
        # per-shard manifests, no gather anywhere. None = mesh-derived
        # shard files (the SPMD path).
        self._save_split = None

    # ------------------------------------------------------------------
    # Training
    # ------------------------------------------------------------------

    def train_step(self, centers, contexts, mask, key, alpha) -> float:
        """One synchronous SGNS minibatch update; returns the batch loss.

        The fused equivalent of one ``dotprod`` -> gradient-scale ->
        ``adjust`` round trip (mllib:421-425). Batch rows must be divisible
        by the data-axis size. Inputs may be host (numpy) or device-resident
        (jax) arrays; device arrays are used in place — no host bounce.
        """
        centers = _host_or_device(centers)
        B = centers.shape[0]
        # Same device-resident cached mask trick as train_steps: never
        # re-upload a constant per call (multi-host wants host arrays).
        if jax.process_count() > 1:
            gm = np.ones((B, 1), dtype=np.float32)
        else:
            if (B,) not in self._ones_mask_cache:
                self._ones_mask_cache[(B,)] = jnp.ones((B, 1), jnp.float32)
            gm = self._ones_mask_cache[(B,)]
        return self.train_step_grouped(
            centers[:, None], gm, contexts, mask, key, alpha,
        )

    def _device_batch(self, *arrays, data_axis: int):
        """Place batch arrays on the mesh. Single-process: plain
        ``jnp.asarray`` (a no-op for already-device-resident inputs; jit
        shards them). Multi-host: each process passes only ITS data-axis
        rows as HOST arrays; the global batch is assembled with every
        shard staying on the host that produced it
        (distributed.make_global_batch — the Spark partition-locality
        analogue, mllib:345)."""
        if jax.process_count() > 1:
            from glint_word2vec_tpu.parallel.distributed import (
                make_global_batch,
            )

            return make_global_batch(
                self.mesh, *(np.asarray(a) for a in arrays),
                data_axis=data_axis,
            )
        return tuple(jnp.asarray(a) for a in arrays)

    def train_step_grouped(
        self, center_groups, group_mask, contexts, mask, key, alpha
    ) -> float:
        """SGNS update with grouped centers: each center is the masked mean
        of its group's syn0 rows (fastText subword composition; the center
        gradient splits 1/count over the group's rows). Word-level training
        is the width-1 special case used by :meth:`train_step`."""
        cg, gm, cx, mk = self._device_batch(
            _host_or_device(center_groups),
            _host_or_device(group_mask, jnp.float32),
            _host_or_device(contexts),
            _host_or_device(mask, jnp.float32),
            data_axis=0,
        )
        B = cg.shape[0]
        if B % self.num_data:
            raise ValueError(
                f"batch size {B} not divisible by data axis {self.num_data}"
            )
        self.syn0, self.syn1, loss = self._train_step(
            self.syn0, self.syn1, self._prob, self._alias,
            cg, gm, cx, mk, key, jnp.float32(alpha),
        )
        self._tick_tables("train_step")
        return loss

    def train_steps(
        self, centers_k, contexts_k, mask_k, base_key, alphas, step0: int = 0
    ) -> jax.Array:
        """K minibatches in ONE device dispatch via an on-device ``lax.scan``.

        ``centers_k (K, B)``, ``contexts_k (K, B, C)``, ``mask_k (K, B, C)``,
        ``alphas (K,)``. The per-step PRNG key is
        ``fold_in(base_key, step0 + i)``, so this is step-for-step identical
        (same negatives, same updates) to K calls of :meth:`train_step` with
        that key schedule. Returns the (K,) per-step losses.

        This is the dispatch-amortized hot path: the reference pays two RPC
        round-trips per 50-position minibatch (mllib:421-429); the scanned
        step pays one host round-trip per K minibatches, with all K updates
        running back-to-back on device.
        """
        centers_k = _host_or_device(centers_k)
        K, B = centers_k.shape[0], centers_k.shape[1]
        # Device-resident all-ones group mask, cached per shape: building
        # it as host numpy per call re-uploaded ~32 KB/step of constant
        # data every dispatch, contaminating the "only scalars cross per
        # dispatch" property of the device-resident hot path.
        if jax.process_count() > 1:
            # Multi-host assembles global batches from HOST arrays
            # (make_global_batch); a device-resident constant would bounce
            # device->host per call there.
            gm = np.ones((K, B, 1), dtype=np.float32)
        else:
            # Keyed by shape so callers alternating between batch shapes
            # don't rebuild and re-upload the constant mask every call.
            if (K, B) not in self._ones_mask_cache:
                self._ones_mask_cache[(K, B)] = jnp.ones(
                    (K, B, 1), jnp.float32
                )
            gm = self._ones_mask_cache[(K, B)]
        return self.train_steps_grouped(
            centers_k[:, :, None], gm,
            contexts_k, mask_k, base_key, alphas, step0,
        )

    def train_steps_grouped(
        self, center_groups_k, group_mask_k, contexts_k, mask_k, base_key,
        alphas, step0: int = 0
    ) -> jax.Array:
        """Grouped-center (subword) variant of :meth:`train_steps`:
        ``center_groups_k (K, B, S)``, ``group_mask_k (K, B, S)``. Under
        multi-host each process passes its own data-axis slice of every
        step's batch (B here = local rows); the global batch is assembled
        across processes before dispatch."""
        cg, gm, cx, mk = self._device_batch(
            _host_or_device(center_groups_k),
            _host_or_device(group_mask_k, jnp.float32),
            _host_or_device(contexts_k),
            _host_or_device(mask_k, jnp.float32),
            data_axis=1,
        )
        B = cg.shape[1]
        if B % self.num_data:
            raise ValueError(
                f"batch size {B} not divisible by data axis {self.num_data}"
            )
        self.syn0, self.syn1, losses = self._train_scan(
            self.syn0, self.syn1, self._prob, self._alias,
            cg, gm, cx, mk,
            base_key, jnp.uint32(step0),
            jnp.asarray(alphas, dtype=jnp.float32),
        )
        self._tick_tables("train_steps")
        return losses

    # ------------------------------------------------------------------
    # Corpus-resident training (device-side batch assembly)
    # ------------------------------------------------------------------

    def upload_corpus(self, ids: np.ndarray, offsets: np.ndarray,
                      n_valid: Optional[int] = None) -> None:
        """Upload the flat encoded corpus (corpus/vocab.encode_file's
        ``(ids, offsets)``) to device HBM once. Subsequent
        :meth:`train_steps_corpus` dispatches assemble minibatches
        entirely on device (ops/device_batching) — per-dispatch
        host->device traffic drops to scalars. ~4 bytes/word of HBM
        replicated per device (~12 with the subsampled path's compacted
        buffers, see :meth:`compact_corpus`).

        ``n_valid`` bounds the live center positions to a PREFIX of the
        buffer: positions at or past it never train (they become
        zero-mask lanes inside the scan). The streaming trainer (ISSUE
        10) re-fills one fixed-capacity buffer per mini-epoch and passes
        the real fill here — the bound is a traced scalar in the
        compiled scan, so every round reuses the same warmed program
        regardless of how many words the stream delivered."""
        n = int(np.asarray(ids).shape[0])
        if n < 1 or n >= 2**31 or int(np.asarray(offsets)[-1]) != n:
            raise ValueError(
                "corpus must be non-empty with offsets[-1] == len(ids) "
                f"< 2**31 (got len(ids)={n})"
            )
        if n_valid is None:
            n_valid = n
        if not 0 <= int(n_valid) <= n:
            raise ValueError(
                f"n_valid ({n_valid}) must be in [0, len(ids)={n}]"
            )
        self._corpus = (
            jnp.asarray(ids, dtype=jnp.int32),
            jnp.asarray(offsets, dtype=jnp.int32),
        )
        self._corpus_n_valid = int(n_valid)
        self._corpus_compacted = None
        self._n_kept = None

    @property
    def corpus_positions(self) -> int:
        """Total center positions of the uploaded corpus (= its words)."""
        if getattr(self, "_corpus", None) is None:
            raise ValueError("no corpus uploaded (call upload_corpus first)")
        return int(self._corpus[0].shape[0])

    def set_keep_probs(self, keep_prob: np.ndarray) -> None:
        """Install the per-word keep-probability table driving on-device
        frequency subsampling (Vocabulary.device_keep_probabilities).
        Required before :meth:`compact_corpus`."""
        kp = np.asarray(keep_prob, dtype=np.float32)
        if kp.shape != (self.vocab_size,):
            raise ValueError(
                f"keep_prob must have shape ({self.vocab_size},), "
                f"got {kp.shape}"
            )
        self._keep_prob = jnp.asarray(kp)

    def compact_corpus(self, epoch_key) -> int:
        """Run one epoch's on-device subsample-and-compact pass
        (ops/device_batching.subsample_compact) over the uploaded corpus
        and make the compacted view the active corpus for subsequent
        :meth:`train_steps_corpus` dispatches. Returns ``n_kept`` — the
        single scalar the host reads back per epoch to size its step
        loop. The previous epoch's compacted buffers are freed first so
        HBM holds at most one compacted copy alongside the flat corpus.
        """
        if getattr(self, "_corpus", None) is None:
            raise ValueError("no corpus uploaded (call upload_corpus first)")
        if getattr(self, "_keep_prob", None) is None:
            raise ValueError(
                "no keep probabilities installed (call set_keep_probs first)"
            )
        if self._corpus_n_valid != int(self._corpus[0].shape[0]):
            # The device pass draws keep masks over the WHOLE static
            # buffer; a bounded prefix view would compact dead padding
            # tokens into the live stream. The streaming trainer
            # subsamples host-side while filling the buffer instead.
            raise ValueError(
                "on-device subsampling over an n_valid-bounded corpus "
                "view is unsupported (subsample host-side when filling "
                "the buffer)"
            )
        old = self._corpus_compacted
        self._corpus_compacted = None
        self._compacted_offsets_host = None
        if old is not None:
            for a in old:
                try:
                    a.delete()
                except Exception:
                    pass
        pre, self._compact_prefetch = self._compact_prefetch, None
        if pre is not None and np.array_equal(
            pre[0], np.asarray(epoch_key)
        ):
            # Adopt the pass prefetch_compact_corpus dispatched while the
            # previous epoch's tail group was still executing: same jitted
            # function, same key — bitwise-identical buffers, already (or
            # still becoming) computed on device.
            ids_c, offsets_c, n_kept = pre[1], pre[2], pre[3]
        else:
            if pre is not None:
                # Prefetched for a different key (e.g. an out-of-order
                # resume): discard, recompute fresh.
                for a in pre[1:3]:
                    try:
                        a.delete()
                    except Exception:
                        pass
            ids_c, offsets_c, n_kept = self._compact_dispatch(epoch_key)
        self._corpus_compacted = (ids_c, offsets_c)
        self._n_kept = int(n_kept)
        return self._n_kept

    def _compact_dispatch(self, epoch_key):
        """Dispatch (without blocking) one subsample-compact pass over
        the uploaded flat corpus; returns the lazy device triple."""
        if not hasattr(self, "_compact_fn"):
            from glint_word2vec_tpu.ops.device_batching import (
                subsample_compact,
            )

            self._compact_fn = jax.jit(subsample_compact)
        ids, offsets = self._corpus
        return self._compact_fn(ids, offsets, self._keep_prob, epoch_key)

    def prefetch_compact_corpus(self, epoch_key) -> None:
        """Dispatch the NEXT epoch's subsample-compact pass into fresh
        device buffers without adopting them — called by the fit loop
        while the current epoch's tail group is still executing, so the
        per-epoch compaction overlaps training instead of serializing
        the epoch boundary (ISSUE 5 prefetch overlap). The buffers are
        adopted by the next :meth:`compact_corpus` call with the same
        ``epoch_key`` (bitwise identical to computing them there); the
        currently-active compacted view is untouched until then. Costs
        one extra transient compacted buffer of HBM until adoption."""
        if getattr(self, "_corpus", None) is None:
            raise ValueError("no corpus uploaded (call upload_corpus first)")
        if getattr(self, "_keep_prob", None) is None:
            raise ValueError(
                "no keep probabilities installed (call set_keep_probs first)"
            )
        old, self._compact_prefetch = self._compact_prefetch, None
        if old is not None:
            for a in old[1:3]:
                try:
                    a.delete()
                except Exception:
                    pass
        key_h = np.asarray(epoch_key)
        ids_c, offsets_c, n_kept = self._compact_dispatch(epoch_key)
        self._compact_prefetch = (key_h, ids_c, offsets_c, n_kept)

    def compacted_offsets(self) -> np.ndarray:
        """Host copy of the active epoch's compacted sentence offsets —
        one (S+1,) readback per epoch, feeding the pre-subsampling
        words_done accounting (corpus_words_done_compacted)."""
        if getattr(self, "_corpus_compacted", None) is None:
            raise ValueError("no compacted corpus (call compact_corpus)")
        if getattr(self, "_compacted_offsets_host", None) is None:
            self._compacted_offsets_host = np.asarray(
                self._corpus_compacted[1]
            )
        return self._compacted_offsets_host

    def _scan_memo_key(self, kind: str, *shape_key):
        """Memo key for :data:`_SCAN_MEMO`: the mesh geometry (device
        ids + axis names) plus every engine attribute the scan
        closures capture at trace time — two engines agreeing on this
        key trace bitwise-identical programs (everything else is a
        traced argument)."""
        return (
            kind,
            tuple(d.id for d in self.mesh.devices.flat),
            self.mesh.axis_names,
            tuple(self.mesh.shape.items()),
            self.layout,
            str(self._dtype), str(self._compute_dtype),
            self._pallas_mode, self._pallas_fused,
            self.num_negatives, self.shared_negatives,
            self.rows_per_shard, self.cols_per_shard,
            self.padded_vocab, self.padded_dim,
            *shape_key,
        )

    def _query_memo_key(self, op):
        """Memo key for :data:`_QUERY_MEMO`: the mesh geometry plus
        ONLY the attributes the query closures capture — layout,
        storage dtype, shard geometry, pallas mode. Training attributes
        (negatives, compute dtype, fused mode) are excluded on purpose:
        they never reach a query program, so models that differ only in
        how they were trained still share the whole warm family."""
        return (
            "query", op,
            tuple(d.id for d in self.mesh.devices.flat),
            self.mesh.axis_names,
            tuple(self.mesh.shape.items()),
            self.layout,
            str(self._dtype),
            self._pallas_mode,
            self.rows_per_shard, self.cols_per_shard,
            self.padded_vocab, self.padded_dim, self.dim,
        )

    def train_steps_corpus(
        self, start_position: int, batch_size: int, window: int,
        base_key, alphas, step0: int = 0
    ) -> jax.Array:
        """K = len(alphas) scanned minibatches over the ACTIVE corpus
        view — the epoch's compacted buffers when :meth:`compact_corpus`
        has run (subsampled training; ``start_position`` is then a
        compacted-stream position), else the full uploaded corpus.
        Batch i covers positions [start + i*B, start + (i+1)*B);
        positions past the corpus end become zero-mask rows (the epoch
        tail). Returns the (K,) per-step losses. Key schedule matches
        :meth:`train_steps` exactly."""
        if getattr(self, "_corpus", None) is None:
            raise ValueError("no corpus uploaded (call upload_corpus first)")
        B, W = int(batch_size), int(window)
        if B % self.num_data:
            raise ValueError(
                f"batch size {B} not divisible by data axis {self.num_data}"
            )
        fn = self._corpus_scan_cache.get((B, W))
        if fn is None:
            mk = self._scan_memo_key("grid", B, W)
            fn = _SCAN_MEMO.get(mk)
            if fn is None:
                fn = _scan_memo_put(mk, self._make_corpus_scan(B, W))
            self._corpus_scan_cache[(B, W)] = fn
        if getattr(self, "_corpus_compacted", None) is not None:
            ids, soffs = self._corpus_compacted
            n_valid = self._n_kept
        else:
            ids, soffs = self._corpus
            n_valid = getattr(self, "_corpus_n_valid", ids.shape[0])
        self.syn0, self.syn1, losses = fn(
            self.syn0, self.syn1, self._prob, self._alias, ids, soffs,
            jnp.int32(n_valid), jnp.int32(start_position), base_key,
            jnp.uint32(step0), jnp.asarray(alphas, dtype=jnp.float32),
        )
        self._tick_tables("train_steps_corpus")
        return losses

    def train_steps_corpus_packed(
        self, start_position: int, pair_batch: int, window: int,
        grid_batch: int, base_key, n_steps: int, step0: int = 0,
        grid_step0: int = 0, *, step_size: float = 0.025,
        total_words: int = 1, words_base: int = 0,
        span: Optional[int] = None,
    ):
        """K = ``n_steps`` PACKED minibatches over the active corpus view
        — the dense-pair alternative to :meth:`train_steps_corpus`
        (``set_batch_packing("dense")`` routes here). Each step packs the
        next valid (center, context) pairs of the position stream into a
        dense ``pair_batch``-slot batch and applies the rank-1 SGNS
        update over pairs, so ~every dispatched contraction lane is a
        real pair (grid dispatches run ~0.43 live lanes at window 5).

        The consumed-position advance is data-dependent and carried
        through the scan; LR alphas are computed ON DEVICE from the
        traced advance with the host's exact pre-subsampling words_done
        rule, parameterized by ``step_size``, ``total_words`` (the LR
        denominator, ``num_iterations * train_words + 1``) and
        ``words_base`` (words credited before this epoch).

        ``grid_batch``/``grid_step0`` pin the window-shrink RNG stream to
        the grid scan's position->draw mapping (see
        ops/device_batching.grid_window_shrink): with the batch size and
        per-epoch step base a grid run would use, the packed run consumes
        the exact same valid-pair multiset per epoch. Negatives are keyed
        by global PAIR row under the ``fold_in(base_key, step0 + i)``
        schedule — mesh-invariant, but a different draw stream than the
        grid path's (like host-vs-device RNG divergence, documented).

        Returns ``(losses (K,), pair_counts (K,), pos_ends (K,),
        alphas (K,))`` — per-step loss, live pairs packed, consumed
        position after the step, and the device-computed alpha. The
        caller reads ``pos_ends[-1]`` to schedule the next dispatch
        (one scalar readback per K steps).
        """
        if getattr(self, "_corpus", None) is None:
            raise ValueError("no corpus uploaded (call upload_corpus first)")
        from glint_word2vec_tpu.corpus.batching import context_width

        P, W, B = int(pair_batch), int(window), int(grid_batch)
        C = context_width(W)
        if P % self.num_data:
            raise ValueError(
                f"pair batch {P} not divisible by data axis {self.num_data}"
            )
        if P < C:
            raise ValueError(
                f"pair_batch ({P}) must be >= context lanes ({C})"
            )
        if span is None:
            # Enough candidates that the cumulative valid-pair count
            # almost always reaches P (expected live lanes per position
            # is ~0.43*C at W=5, ~0.5*C at W=2): 3*P/C positions carry
            # ~1.3-1.5x P expected pairs, so underfill is confined to
            # the epoch tail.
            span = -(-3 * P // C)
        S, K = int(span), int(n_steps)
        fn = self._packed_scan_cache.get((P, W, B, S, K))
        if fn is None:
            mk = self._scan_memo_key("packed", P, W, B, S, K)
            fn = _SCAN_MEMO.get(mk)
            if fn is None:
                fn = _scan_memo_put(
                    mk, self._make_packed_corpus_scan(P, W, B, S, K)
                )
            self._packed_scan_cache[(P, W, B, S, K)] = fn
        if getattr(self, "_corpus_compacted", None) is not None:
            ids, soffs = self._corpus_compacted
            n_valid = self._n_kept
        else:
            ids, soffs = self._corpus
            n_valid = getattr(self, "_corpus_n_valid", ids.shape[0])
        (
            self.syn0, self.syn1, losses, pair_counts, pos_ends, alphas,
        ) = fn(
            self.syn0, self.syn1, self._prob, self._alias, ids, soffs,
            self._corpus[1], jnp.int32(n_valid),
            jnp.int32(start_position), base_key, jnp.uint32(step0),
            jnp.uint32(grid_step0), jnp.float32(step_size),
            jnp.float32(1.0 / float(total_words)),
            jnp.float32(words_base),
        )
        self._tick_tables("train_steps_corpus_packed")
        return losses, pair_counts, pos_ends, alphas

    # ------------------------------------------------------------------
    # Serving ops (the BigWord2VecMatrix query surface)
    # ------------------------------------------------------------------

    def _tick_tables(self, reason: str) -> None:
        """One table mutation: invalidate the norms cache, tick
        ``table_version`` (the token serving-layer caches validate
        against), and record the engine-level event (a no-op global read
        when no recorder is installed)."""
        self._norms_cache = None
        self.table_version += 1
        if reason != "exchange_adopt":
            # Any mutation whose touched-row set is unknown makes every
            # shard file dirty (the safe direction for the skip-clean
            # in-place save); exchange_adopt already narrowed the set.
            self._shard_dirty = None
        obs_events.emit(
            "table_mutation", reason=reason, version=self.table_version
        )

    # -- touched-row replica exchange (ISSUE 15, parallel/exchange.py) --

    def exchange_adopt(self, syn0, syn1, *, touched_ids=None) -> None:
        """Install the reconciled tables a replica-exchange round
        reconstructed (``base + sum of every rank's deltas``): two
        attribute flips and ONE ``table_version`` tick, exactly like
        :meth:`adopt_tables`. ``touched_ids`` (host int array, a sparse
        round's union of exchanged row ids) narrows the checkpoint
        dirty-shard set to the shard files covering those rows; None (a
        dense round) marks everything dirty."""
        self.syn0 = syn0
        self.syn1 = syn1
        self._mark_shards_dirty(touched_ids)
        self._tick_tables("exchange_adopt")

    def _mark_shards_dirty(self, touched_ids=None) -> None:
        """Fold one mutation's touched rows into the dirty-shard-file
        map: MERGE into the existing map, never narrow it — ``None``
        (everything dirty, the state every unknown mutation restores)
        stays ``None`` until a committed save re-establishes clean
        bits. Column-sharded (dims) layouts always go all-dirty: every
        column block spans every row."""
        if touched_ids is None:
            self._shard_dirty = None
            return
        if self._shard_dirty is None:
            return  # already all-dirty; a narrower mark must not undo it
        axis, per_shard, real_extent = self._shard_geometry()
        if axis != "rows":
            self._shard_dirty = None
            return
        starts = np.unique(
            # graftlint: ignore[sync-point] touched_ids is a host id array
            (np.asarray(touched_ids, dtype=np.int64) // per_shard)
            * per_shard
        )
        for start in starts:
            if 0 <= start < real_extent:
                for name in ("syn0", "syn1"):
                    self._shard_dirty[f"{name}.r{start:012d}.npy"] = True

    def _shard_is_dirty(self, fname: str, path: str) -> bool:
        """Whether an in-place save to ``path`` must rewrite ``fname``:
        True unless the last committed save that cleaned the bits wrote
        to this same path and nothing has dirtied the shard since
        (unknown shard names default to dirty — the safe direction)."""
        if self._shard_clean_path != path or self._shard_dirty is None:
            return True
        return bool(self._shard_dirty.get(fname, True))

    def _mark_shards_clean(self, path: str, fnames) -> None:
        """Record that ``path`` now holds current bytes for ``fnames``
        (called after the save's commit point)."""
        if self._shard_clean_path != path or self._shard_dirty is None:
            self._shard_dirty = {}
            self._shard_clean_path = path
        for f in fnames:
            self._shard_dirty[f] = False

    def _note_exchange(self, *, bytes_sent: int, rows: int,
                       overflow: bool, dense: bool,
                       seconds: float, wire: str = "fp32",
                       groups: int = 1, flush: bool = False,
                       world1_skip: bool = False, intra_bytes: int = 0,
                       capacity: Optional[int] = None,
                       cap_event: Optional[str] = None,
                       residual_abs: float = 0.0) -> None:
        st = self._exchange_stats
        st["exchange_bytes_total"] += int(bytes_sent)  # graftlint: ignore[sync-point] host stat
        st["exchange_rows_total"] += int(rows)  # graftlint: ignore[sync-point] host stat
        st["exchange_overflow_total"] += int(bool(overflow))
        st["exchange_syncs_total"] += 1
        st["exchange_dense_syncs_total"] += int(bool(dense))
        st["exchange_last_seconds"] = round(float(seconds), 6)  # graftlint: ignore[sync-point] host stat
        wire_key = "exchange_bytes_wire_%s_total" % (
            wire if wire in ("fp32", "bf16", "int8") else "fp32"
        )
        st[wire_key] += int(bytes_sent)  # graftlint: ignore[sync-point] host stat
        st["exchange_groups_total"] += int(groups)  # graftlint: ignore[sync-point] host stat
        st["exchange_flushes_total"] += int(bool(flush))
        st["exchange_world1_skips_total"] += int(bool(world1_skip))
        st["exchange_intra_bytes_total"] += int(intra_bytes)  # graftlint: ignore[sync-point] host stat
        inter = max(int(bytes_sent) - int(intra_bytes), 0)  # graftlint: ignore[sync-point] host stat
        st["exchange_inter_bytes_total"] += inter  # graftlint: ignore[sync-point] host stat
        if capacity is not None:
            st["exchange_capacity"] = int(capacity)  # graftlint: ignore[sync-point] host stat
        st["exchange_capacity_grows_total"] += int(cap_event == "grow")
        st["exchange_capacity_shrinks_total"] += int(cap_event == "shrink")
        st["exchange_residual_abs"] = float(residual_abs)  # graftlint: ignore[sync-point] host stat

    def exchange_stats(self) -> dict:
        """Replica-exchange telemetry for the heartbeat (zeros until a
        :class:`parallel.exchange.ReplicaExchanger` runs a round)."""
        return dict(self._exchange_stats)

    def _count_query_shape(self, *key) -> None:
        """Record one query-op dispatch shape; a first-seen shape is one
        jit compile (jit specializes per shape). Callers hold the query
        lock on the serving path; elsewhere races only over-count."""
        if key not in self._query_shapes:
            self._query_shapes.add(key)
            self.query_compiles += 1
            # Process-level accounting (ISSUE 20): if a same-geometry
            # engine already dispatched this (op, shape), the shared
            # program memo means no XLA compile actually ran — the
            # per-engine counter keeps its first-seen-here semantics,
            # the process counter measures real compile work.
            pkey = self._query_memo_key("shape") + key
            shared = pkey in _QUERY_SHAPES_SEEN
            if shared:
                self.shared_program_hits += 1
            else:
                _QUERY_SHAPES_SEEN.add(pkey)
                _QUERY_PROGRAM_BUILDS[0] += 1
            obs_events.emit(
                "query_compile", op=str(key[0]), shape=list(key[1:]),
                total=self.query_compiles, shared=shared,
            )

    def _k_bucket(self, k: int) -> int:
        """Round a top-k request up to its compile bucket (see
        TOPK_MIN_K_BUCKET)."""
        return min(max(next_pow2(k), TOPK_MIN_K_BUCKET), self.padded_vocab)

    def _q_bucket(self, n: int) -> int:
        """Round a batch top-k row count up to its compile bucket (see
        TOPK_MIN_Q_BUCKET)."""
        return 1 if n <= 1 else max(next_pow2(n), TOPK_MIN_Q_BUCKET)

    def pull(self, indices) -> jax.Array:
        """Gather syn0 rows by global index (Glint ``pull``, mllib:514)."""
        idx = jnp.asarray(indices, dtype=jnp.int32)
        self._count_query_shape("pull", int(idx.shape[0]))
        return self._pull(self.syn0, idx)

    def pull_average(self, sentence_indices, mask) -> jax.Array:
        """Mean of syn0 rows per padded index-set row (Glint ``pullAverage``,
        ml:453): sentence embedding computed device-side; only S*d floats
        ever leave the device. All-masked rows yield zero vectors (the
        reference's empty-sentence average)."""
        idx = jnp.asarray(sentence_indices, dtype=jnp.int32)
        self._count_query_shape(
            "pull_average", int(idx.shape[0]), int(idx.shape[1])
        )
        return self._pull_average(
            self.syn0, idx, jnp.asarray(mask, dtype=jnp.float32)
        )

    def _row_writer(self):
        """Lazily-built jitted row-block writer shared by
        :meth:`write_rows` and the extra-row assignment path: one
        compiled program per block shape, start row traced."""
        if not hasattr(self, "_write_rows_fn"):
            self._write_rows_fn = jax.jit(
                lambda table, block, s: jax.lax.dynamic_update_slice(
                    table, block.astype(table.dtype), (s, 0)
                ),
                out_shardings=self._table_sharding(),
                donate_argnums=(0,),
            )
        return self._write_rows_fn

    def write_rows(self, start_row: int, rows: jax.Array) -> None:
        """Overwrite ``rows.shape[0]`` consecutive syn0 rows starting at
        ``start_row``, entirely on device (used to assemble derived tables,
        e.g. composed subword vectors, without a host round-trip). The
        start index is a traced argument, so chunked writers compile once
        per chunk shape."""
        fn = self._row_writer()
        pad = self.padded_dim - self.dim
        if pad:
            rows = jnp.pad(rows, ((0, 0), (0, pad)))
        self.syn0 = fn(self.syn0, rows, jnp.int32(start_row))
        self._tick_tables("write_rows")
        self._ann_touch_rows(range(start_row, start_row + rows.shape[0]))

    # ------------------------------------------------------------------
    # Runtime vocabulary growth (ISSUE 10 streaming)
    # ------------------------------------------------------------------

    @property
    def extra_rows_total(self) -> int:
        """Spare non-vocabulary rows reserved at construction."""
        return self.num_rows - self.vocab_size

    @property
    def extra_rows_free(self) -> int:
        """Spare rows still available to :meth:`assign_extra_row`."""
        return self.extra_rows_total - self.extra_rows_assigned

    @property
    def queryable_rows(self) -> int:
        """Rows the similarity ops may surface: the base vocab plus
        every assigned extra row. This bound enters the warmed top-k
        programs as a TRACED scalar, so growing (or freeing) rows never
        costs a compile — the streaming hot-swap contract (ISSUE 10)."""
        return self.vocab_size + self.extra_rows_assigned

    def _extra_row_init(self, start: int, m: int) -> jax.Array:
        """Fresh syn0 init for rows ``[start, start+m)``: the word2vec
        ``U[-0.5/d, 0.5/d)`` draw, keyed per GLOBAL row by the engine
        seed — so batched and single assignment produce identical
        values and repeated runs draw identically. Compiled once per
        block size ``m``; ``start`` is traced."""
        if not hasattr(self, "_extra_init_fn"):
            d = self.dim
            base = jax.random.PRNGKey(self._seed)

            def _block(start, rel):
                keys = jax.vmap(
                    lambda r: jax.random.fold_in(base, (1 << 30) + r)
                )(start + rel)
                blk = jax.vmap(
                    lambda k: jax.random.uniform(
                        k, (self.padded_dim,), jnp.float32,
                        minval=-0.5 / d, maxval=0.5 / d,
                    )
                )(keys)
                if self.padded_dim > d:
                    blk = blk.at[:, d:].set(0.0)
                return blk

            self._extra_init_fn = jax.jit(_block)
        return self._extra_init_fn(
            jnp.int32(start), jnp.arange(m, dtype=jnp.int32)
        )

    def assign_extra_rows(self, words: Sequence[Optional[str]]) -> List[int]:
        """Claim ``len(words)`` consecutive spare extra rows in one
        batched mutation: the promotion-burst path (a vocabulary shift
        can promote thousands of words between two mini-epochs, and
        per-word writes would issue thousands of serialized single-row
        dispatches). The block is written in power-of-two chunks, so a
        lifetime of arbitrary burst sizes compiles at most
        ``log2(extra_rows_total)`` distinct block shapes, and the whole
        burst costs ONE ``table_version`` tick.

        Each claimed syn0 row gets the word2vec ``U[-0.5/d, 0.5/d)``
        init keyed by the engine seed + its GLOBAL row (identical to n
        single assignments — the draw does not depend on the batch it
        arrived in) and the syn1 row is zeroed, so a
        freed-and-recycled row never leaks its previous word's trained
        values. Returns the claimed GLOBAL row indices — always the
        next ``len(words)`` rows after ``queryable_rows``, so the
        caller's grown word list stays aligned with the table by
        construction. ``words`` feed the obs event only — the engine
        stays word-agnostic; the vocabulary layer owns the mapping."""
        words = list(words)
        n = len(words)
        if n == 0:
            return []
        if n > self.extra_rows_free:
            raise ValueError(
                f"no spare extra rows left for {n} word(s) "
                f"({self.extra_rows_assigned}/{self.extra_rows_total} "
                "assigned); construct the engine with more extra_rows "
                "headroom"
            )
        start = self.vocab_size + self.extra_rows_assigned
        fn = self._row_writer()
        s, left = start, n
        while left:
            m = 1 << (left.bit_length() - 1)
            self.syn0 = fn(
                self.syn0, self._extra_row_init(s, m), jnp.int32(s)
            )
            self.syn1 = fn(
                self.syn1, jnp.zeros((m, self.padded_dim), jnp.float32),
                jnp.int32(s),
            )
            s += m
            left -= m
        self.extra_rows_assigned += n
        self._tick_tables("assign_extra_row")
        self._ann_touch_rows(range(start, start + n))
        obs_events.emit(
            "extra_rows_assigned", start=start, n=n,
            assigned=self.extra_rows_assigned, words=words[:8],
        )
        return list(range(start, start + n))

    def assign_extra_row(self, word: Optional[str] = None) -> int:
        """Claim the next spare extra row for a word that entered the
        vocabulary at runtime (ISGNS online vocab growth). Returns the
        claimed GLOBAL row index. Single-word form of
        :meth:`assign_extra_rows` — identical init, one
        ``table_version`` tick per call."""
        return self.assign_extra_rows([word])[0]

    def free_extra_rows(self, n: Optional[int] = None) -> int:
        """Release the last ``n`` assigned extra rows (default: all),
        zeroing both table rows so a later reassignment can never leak
        the previous word's vectors. Returns the number freed. Ticks
        ``table_version`` — the queryable bound shrank, so any cached
        top-k that surfaced a freed row must drop."""
        if n is None:
            n = self.extra_rows_assigned
        n = int(n)  # graftlint: ignore[sync-point] host argument, not a device value
        if n < 0 or n > self.extra_rows_assigned:
            raise ValueError(
                f"cannot free {n} extra rows "
                f"({self.extra_rows_assigned} assigned)"
            )
        if n == 0:
            return 0
        start = self.vocab_size + self.extra_rows_assigned - n
        fn = self._row_writer()
        zeros = jnp.zeros((n, self.padded_dim), jnp.float32)
        self.syn0 = fn(self.syn0, zeros, jnp.int32(start))
        self.syn1 = fn(self.syn1, zeros, jnp.int32(start))
        self.extra_rows_assigned -= n
        self._tick_tables("free_extra_rows")
        if self._ann is not None:
            from glint_word2vec_tpu.ops import ann as _ann_mod

            _ann_mod.remove_rows(
                self._ann, self.syn0, range(start, start + n)
            )
            self._ann.table_version = self.table_version
        obs_events.emit(
            "extra_rows_freed", freed=n, assigned=self.extra_rows_assigned,
        )
        return n

    def _ann_touch_rows(self, rows) -> None:
        """Incrementally re-bucket rows whose values just changed into
        the live coarse index (streaming promotions / row writes):
        ONLY the touched rows move — the ISSUE 12 incremental
        re-assignment contract. A no-op without an adopted index; the
        index version advances with the table so staleness gauges stay
        honest."""
        if self._ann is None:
            return
        from glint_word2vec_tpu.ops import ann as _ann_mod

        _ann_mod.update_rows(self._ann, self.syn0, self.norms(), rows)
        self._ann.table_version = self.table_version

    def set_noise_counts(self, counts: np.ndarray) -> None:
        """Install updated per-word corpus counts and rebuild the
        negative-sampling alias table from them — the ISGNS adaptive
        unigram distribution (arXiv:1704.03956): a long-lived streaming
        trainer re-derives the noise distribution from the counts it
        has actually observed, on a cadence, instead of freezing the
        bootstrap distribution forever.

        Shapes are invariant (``prob``/``alias`` stay ``(vocab_size,)``
        arrays), so every compiled train program keeps running warm —
        the refresh is two replicated device_puts. Spare extra rows are
        never negative-sampled (the table spans the base vocab only, as
        for fastText buckets); checkpoints carry the updated counts."""
        # graftlint: ignore[sync-point] counts arrive as a host numpy array
        c = np.asarray(counts, dtype=np.int64)
        if c.shape != (self.vocab_size,):
            raise ValueError(
                f"counts must have shape ({self.vocab_size},), got {c.shape}"
            )
        if c.sum() <= 0:
            raise ValueError("counts must sum to > 0")
        table = build_unigram_alias(
            c, power=self.unigram_power, table_size=self.unigram_table_size
        )
        self._counts = c.copy()
        repl = NamedSharding(self.mesh, P())
        self._prob = jax.device_put(jnp.asarray(table.prob), repl)
        self._alias = jax.device_put(jnp.asarray(table.alias), repl)
        obs_events.emit(
            # graftlint: ignore[sync-point] c is the host counts array
            "noise_counts_updated", train_words=int(c.sum()),
        )

    def norms(self) -> jax.Array:
        """Per-row Euclidean norms of syn0, computed shard-local (Glint
        ``norms``, mllib:486), cached until the next table mutation.
        Returns the padded-row-count array. With ``extra_rows`` > 0 the
        bucket rows [vocab_size, num_rows) have nonzero norms — only
        rows past ``num_rows`` (padding) are guaranteed zero; query ops
        exclude non-vocab rows by index, not by norm."""
        if self._norms_cache is None:
            self._norms_cache = self._norms(self.syn0)
        return self._norms_cache

    def _pad_query(self, v: np.ndarray) -> jnp.ndarray:
        """Pad a (d,) or (Q, d) query to padded_dim for the dims layout
        (zero columns contribute zero to every partial dot product)."""
        pad = self.padded_dim - self.dim
        if pad:
            widths = [(0, 0)] * (v.ndim - 1) + [(0, pad)]
            v = np.pad(v, widths)
        return jnp.asarray(v)

    def multiply(self, vec) -> jax.Array:
        """Distributed matvec syn0 @ vec (Glint ``multiply``, mllib:598)."""
        v = np.asarray(vec, dtype=np.float32)
        if v.shape != (self.dim,):
            raise ValueError(f"vec must have shape ({self.dim},)")
        return self._multiply(self.syn0, self._pad_query(v))

    def top_k_cosine(self, vec, k: int) -> Tuple[np.ndarray, np.ndarray]:
        """On-device distributed top-k by cosine similarity against syn0.

        Returns (similarities, indices), padded rows excluded by their zero
        norm. The query is normalized here (the reference normalizes with
        BLAS snrm2/sscal before ``multiply``, mllib:593-595)."""
        if not 0 < k <= self.padded_vocab:
            raise ValueError(f"k must be in [1, {self.padded_vocab}]")
        v = np.asarray(vec, dtype=np.float32)
        nrm = float(np.linalg.norm(v))
        if nrm > 0:
            v = v / nrm
        # One compiled program per k-BUCKET, not per k: fetch the
        # bucket's top-k (a sorted superset) and truncate. Exact — the
        # global top-k is the prefix of the global top-k_bucket.
        k_b = self._k_bucket(k)
        if k_b not in self._topk_cache:
            self._topk_cache[k_b] = self._make_topk(k_b)
        self._count_query_shape("topk", k_b)
        val, idx = self._topk_cache[k_b](
            self.syn0, self._pad_query(v), self.norms(),
            jnp.int32(self.queryable_rows),
        )
        return np.asarray(val)[:k], np.asarray(idx)[:k]

    def top_k_cosine_batch(
        self, vecs, k: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Batched :meth:`top_k_cosine`: (Q, d) queries -> ((Q, k) sims,
        (Q, k) indices) in one distributed dispatch. The batch analogue of
        the reference's findSynonyms(Array) delegation loop
        (ml:375-420), scored as one sharded matmul per call."""
        if not 0 < k <= self.padded_vocab:
            raise ValueError(f"k must be in [1, {self.padded_vocab}]")
        q = np.asarray(vecs, dtype=np.float32)
        if q.ndim != 2 or q.shape[1] != self.dim:
            raise ValueError(f"vecs must have shape (Q, {self.dim})")
        nrm = np.linalg.norm(q, axis=1, keepdims=True)
        q = q / np.where(nrm > 0, nrm, 1.0)
        kk = min(k, self.padded_vocab)
        if q.shape[0] == 0:
            empty = np.zeros((0, kk))
            return empty.astype(np.float32), empty.astype(np.int64)
        k_b = self._k_bucket(k)
        if k_b not in self._topk_batch_cache:
            self._topk_batch_cache[k_b] = self._make_topk_batch(k_b)
        fn = self._topk_batch_cache[k_b]
        # Dims layout materializes full (Q, V) scores per shard; chunk Q
        # to a ~256 MB score-matrix budget so the intermediate stays
        # bounded at any vocab size (10M rows -> 6-query chunks).
        if self.layout == "dims":
            chunk = max(1, int(256e6 // (4 * self.padded_vocab)))
        else:
            chunk = q.shape[0]
        vals, idxs = [], []
        for s in range(0, q.shape[0], chunk):
            qc = q[s : s + chunk]
            n = qc.shape[0]
            # Pad Q up to its bucket (power of two, floored at
            # TOPK_MIN_Q_BUCKET) so concurrency jitter (every distinct
            # coalesced batch size) maps onto a small compiled family.
            # Zero-vector padding rows score 0 for real words and are
            # sliced off; they can never perturb a real row's top-k
            # (each query row ranks independently).
            q_b = self._q_bucket(n)
            if q_b != n:
                qc = np.concatenate(
                    [qc, np.zeros((q_b - n, qc.shape[1]), np.float32)]
                )
            self._count_query_shape("topk_batch", q_b, k_b)
            val, idx = fn(
                self.syn0, self._pad_query(qc), self.norms(),
                jnp.int32(self.queryable_rows),
            )
            vals.append(np.asarray(val)[:n, :kk])
            idxs.append(np.asarray(idx)[:n, :kk])
        return np.concatenate(vals), np.concatenate(idxs)

    # ------------------------------------------------------------------
    # Approximate top-k (device-resident ANN index, ISSUE 12)
    # ------------------------------------------------------------------

    def configure_ann(
        self,
        *,
        clusters: int = -1,
        nprobe: int = 8,
        iters: int = 6,
        sample: int = 65536,
    ) -> dict:
        """Fix the coarse-index geometry for this engine. ``clusters``
        -1 picks ``ops.ann.auto_clusters`` (≈ next_pow2(√rows) — the
        O(√V·d) operating point); the member-slot count follows from
        the engine's FULL row capacity, so streaming growth and every
        later rebuild share one compiled shape family. Returns the
        resolved geometry."""
        from glint_word2vec_tpu.ops import ann as _ann

        clusters = int(clusters)  # graftlint: ignore[sync-point] host config scalar
        nprobe = int(nprobe)  # graftlint: ignore[sync-point] host config scalar
        iters = int(iters)  # graftlint: ignore[sync-point] host config scalar
        sample = int(sample)  # graftlint: ignore[sync-point] host config scalar
        C = clusters if clusters > 0 else _ann.auto_clusters(self.num_rows)
        self._ann_conf = {
            "clusters": C,
            "slots": _ann.member_slots(self.num_rows, C),
            "nprobe": max(1, min(nprobe, C)),
            "iters": max(1, iters),
            "sample": max(1, sample),
        }
        return dict(self._ann_conf)

    @property
    def ann_index(self):
        """The adopted live index, or None."""
        return getattr(self, "_ann", None)

    def ann_build(self, syn0=None, norms=None, queryable=None):
        """Build a coarse index (k-means centroids + packed member
        layout) from ``syn0`` — the LIVE table by default, or a STAGED
        generation's (pass its arrays) so a hot-swap can prepare the
        index entirely off the request path. Returns the index WITHOUT
        adopting it; flip it live with :meth:`adopt_ann` (the serving
        swap does both under one device-lock hold). Requires
        :meth:`configure_ann` first."""
        from glint_word2vec_tpu.ops import ann as _ann

        conf = getattr(self, "_ann_conf", None)
        if conf is None:
            raise RuntimeError("call configure_ann() before ann_build()")
        if syn0 is None:
            syn0 = self.syn0
            norms = self.norms()
            queryable = self.queryable_rows
        elif norms is None:
            norms = self._norms(syn0)
        if queryable is None:
            queryable = self.queryable_rows
        queryable = int(queryable)  # graftlint: ignore[sync-point] host row-count scalar
        return _ann.build(
            syn0,
            norms,
            queryable,
            clusters=conf["clusters"],
            iters=conf["iters"],
            sample=conf["sample"],
            seed=self._seed,
            table_version=self.table_version,
            num_rows=self.num_rows,
            sharding=NamedSharding(self.mesh, P()),
        )

    def adopt_ann(self, index) -> None:
        """Flip the live coarse index: one attribute assignment — the
        serving hot-swap pairs it with :meth:`adopt_tables` under the
        same device-lock hold so tables and index always flip together.
        ``None`` disables the approximate path."""
        self._ann = index
        if index is not None:
            index.table_version = self.table_version

    def ann_stats(self) -> dict:
        """Index telemetry for the serving ``index_*`` family; safe to
        call with no index (reports disabled)."""
        idx = self.ann_index
        if idx is None:
            return {"enabled": False}
        st = idx.stats()
        st["enabled"] = True
        st["nprobe"] = self._ann_conf["nprobe"]
        st["table_versions_behind"] = max(
            0, self.table_version - idx.table_version
        )
        return st

    def ann_top_k_batch(
        self, vecs, k: int, nprobe: Optional[int] = None, *, index=None,
        queryable=None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Approximate :meth:`top_k_cosine_batch` through the coarse
        index: coarse centroid scores pick ``nprobe`` clusters per
        query, exact masked rerank inside their padded member-row
        blocks. Same bucketing contract as the exact path (Q padded to
        its power-of-two bucket capped at ``ANN_MAX_Q``, k rounded to
        its bucket and truncated), so serving concurrency jitter rides
        one small warmed family. The search reads ONLY the index (the
        member blocks are a copy of the index's source table), so
        ``index``/``queryable`` overrides run staged-generation recall
        checks on the very same compiled programs the live path uses."""
        from glint_word2vec_tpu.ops import ann as _ann

        idx = index if index is not None else self.ann_index
        if idx is None:
            raise RuntimeError("no ANN index adopted (ann_build/adopt_ann)")
        if queryable is None:
            queryable = self.queryable_rows
        if nprobe is None:
            nprobe = self._ann_conf["nprobe"]
        nprobe = max(1, min(int(nprobe), idx.clusters))
        if not 0 < k <= self.padded_vocab:
            raise ValueError(f"k must be in [1, {self.padded_vocab}]")
        if k > nprobe * idx.slots:
            # The probed slots cannot hold k candidates — a silent
            # truncation would diverge from the exact path with no
            # signal. Callers (the model layer) route oversized k to
            # the exact path instead.
            raise ValueError(
                f"k={k} exceeds the index's probe capacity "
                f"({nprobe} probes x {idx.slots} slots); raise nprobe "
                "or use the exact path"
            )
        q = np.asarray(vecs, dtype=np.float32)
        if q.ndim != 2 or q.shape[1] != self.dim:
            raise ValueError(f"vecs must have shape (Q, {self.dim})")
        nrm = np.linalg.norm(q, axis=1, keepdims=True)
        q = q / np.where(nrm > 0, nrm, 1.0)
        kk = min(k, self.padded_vocab)
        if q.shape[0] == 0:
            empty = np.zeros((0, kk))
            return empty.astype(np.float32), empty.astype(np.int64)
        k_b = min(self._k_bucket(k), nprobe * idx.slots)  # bucket pad only
        vals, idxs = [], []
        for s in range(0, q.shape[0], ANN_MAX_Q):
            qc = q[s : s + ANN_MAX_Q]
            n = qc.shape[0]
            q_b = min(self._q_bucket(n), ANN_MAX_Q)
            if q_b != n:
                qc = np.concatenate(
                    [qc, np.zeros((q_b - n, qc.shape[1]), np.float32)]
                )
            fn = _ann._search_fn(
                q_b, k_b, nprobe, idx.clusters, idx.slots, idx.dim
            )
            self._count_query_shape("ann_topk", q_b, k_b, nprobe)
            val, ids = fn(
                idx.member_rows, idx.centroids, idx.members,
                idx.member_invn, self._pad_query(qc),
                jnp.int32(queryable),
            )
            vals.append(np.asarray(val)[:n, :kk])
            idxs.append(np.asarray(ids)[:n, :kk])
        return np.concatenate(vals), np.concatenate(idxs)

    def warmup_ann(self, q_buckets=(1, 8, ANN_MAX_Q),
                   k_buckets=(TOPK_MIN_K_BUCKET,),
                   nprobes=()) -> int:
        """Compile the approximate dispatch family — coarse score +
        bucketed rerank for every (Q bucket, k bucket, nprobe), plus
        the incremental-assignment program promotions ride — so the
        serving warmup covers the ANN path too and
        ``post_warmup_compiles`` stays 0 (ISSUE 12 satellite). Requires
        an adopted index."""
        from glint_word2vec_tpu.ops import ann as _ann

        idx = self.ann_index
        if idx is None:
            raise RuntimeError("adopt an index before warmup_ann()")
        before = self.query_compiles
        # Buckets arrive as host int tuples from the serving warmup.
        nps = sorted(
            {max(1, min(p, idx.clusters))
             for p in (*nprobes, self._ann_conf["nprobe"])}
        )
        d = self.dim
        with obs_events.span("engine_warmup_ann"):
            for p in nps:
                for q in sorted(
                    {min(self._q_bucket(q), ANN_MAX_Q)
                     for q in q_buckets}
                ):
                    for k in sorted(
                        {self._k_bucket(k) for k in k_buckets}
                    ):
                        self.ann_top_k_batch(
                            np.zeros((q, d), np.float32), k, p
                        )
            # The promotion path's fixed-chunk assignment program.
            _ann._score_fn(
                _ann.INCREMENTAL_BLOCK, idx.clusters, idx.dim
            )(
                self.syn0, self.norms(),
                jnp.zeros(_ann.INCREMENTAL_BLOCK, jnp.int32),
                idx.centroids,
            )
        n = self.query_compiles - before
        obs_events.emit("warmup_ann_done", shapes_compiled=n)
        return n

    def ann_recall_at_k(
        self, k: int = 10, sample: int = 64, nprobe: Optional[int] = None,
        *, index=None, syn0=None, norms=None, queryable=None,
        q_chunk: int = 64,
    ) -> float:
        """Measured recall@k of the approximate path against the exact
        path on the SAME tables (live by default; pass a staged
        generation's arrays to gate a hot-swap before adopting it).
        Queries are ``sample`` deterministic table rows; for each, the
        exact and approximate top-(k+1) sets are compared with the
        query row itself excluded — the serving ``/synonyms``
        semantics. Both sides ride the already-warmed bucketed
        programs (``q_chunk`` should be the serving max_batch), so a
        post-warmup recall check never compiles."""
        idx = index if index is not None else self.ann_index
        if idx is None:
            raise RuntimeError("no ANN index adopted")
        if syn0 is None:
            syn0 = self.syn0
            norms = self.norms()
            queryable = self.queryable_rows
        elif norms is None:
            norms = self._norms(syn0)
        if queryable is None:
            queryable = self.queryable_rows
        queryable = int(queryable)
        rng = np.random.default_rng(self._seed)
        n_q = min(int(sample), queryable)
        if n_q == 0:
            return 1.0
        qids = rng.choice(queryable, n_q, replace=False).astype(np.int32)
        qvecs = np.asarray(
            syn0[jnp.asarray(qids)].astype(jnp.float32)
        )[:, : self.dim]
        live = np.linalg.norm(qvecs, axis=1) > 0
        if not live.any():
            return 1.0
        qids, qvecs = qids[live], qvecs[live]
        k_b = self._k_bucket(k + 1)
        if k_b not in self._topk_batch_cache:
            self._topk_batch_cache[k_b] = self._make_topk_batch(k_b)
        exact_fn = self._topk_batch_cache[k_b]
        hits = 0
        total = 0
        for s in range(0, qids.shape[0], q_chunk):
            qc = qvecs[s : s + q_chunk]
            ic = qids[s : s + q_chunk]
            n = qc.shape[0]
            nrm = np.linalg.norm(qc, axis=1, keepdims=True)
            qn = qc / np.where(nrm > 0, nrm, 1.0)
            q_b = self._q_bucket(n)
            qp = qn
            if q_b != n:
                qp = np.concatenate(
                    [qn, np.zeros((q_b - n, qn.shape[1]), np.float32)]
                )
            self._count_query_shape("topk_batch", q_b, k_b)
            ex_val, ex_idx = exact_fn(
                syn0, self._pad_query(qp), norms, jnp.int32(queryable)
            )
            ex_val = np.asarray(ex_val)[:n]
            ex_idx = np.asarray(ex_idx)[:n]
            ap_val, ap_idx = self.ann_top_k_batch(
                qc, k + 1, nprobe, index=idx, queryable=queryable,
            )
            for row in range(n):
                # -inf entries are masked filler (padding rows, empty
                # member slots) surfacing only when fewer than k+1 rows
                # are queryable — they are NOT results on either side.
                ex = [
                    int(i) for i, v in zip(ex_idx[row], ex_val[row])
                    if np.isfinite(v) and int(i) != int(ic[row])
                ]
                ap = {
                    int(i) for i, v in zip(ap_idx[row], ap_val[row])
                    if np.isfinite(v) and int(i) != int(ic[row])
                }
                want = ex[:k]
                hits += len(set(want) & ap)
                total += len(want)
        return hits / max(1, total)

    def warmup(
        self,
        q_buckets=(1, 2, 4, 8, 16, 32, 64),
        k_buckets=(TOPK_MIN_K_BUCKET,),
        *,
        sentence_lens=(),
        sentence_rows=(1,),
    ) -> int:
        """Compile the query-op shape family up front so no real request
        ever pays a jit compile (the serving warmup entry point, ISSUE 2).

        Exercises ``pull`` and ``top_k_cosine_batch`` for every Q bucket,
        ``top_k_cosine`` for every k bucket, and — when ``sentence_lens``
        is given — ``pull_average`` for the (rows, len) sentence grid.
        Buckets are quantized exactly as the query ops quantize real
        requests, so a warmed bucket can never re-compile. Returns the
        number of shapes this call compiled (0 = already warm)."""
        before = self.query_compiles
        with obs_events.span("engine_warmup"):
            d = self.dim
            ks = sorted({self._k_bucket(int(k)) for k in k_buckets})
            for k in ks:
                self.top_k_cosine(np.zeros(d, np.float32), k)
            for q in sorted({next_pow2(int(q)) for q in q_buckets}):
                self.pull(np.zeros(q, np.int32))
            for q in sorted({self._q_bucket(int(q)) for q in q_buckets}):
                zq = np.zeros((q, d), np.float32)
                for k in ks:
                    self.top_k_cosine_batch(zq, k)
            for s in sorted({next_pow2(int(s)) for s in sentence_rows}):
                for L in sorted({next_pow2(int(L)) for L in sentence_lens}):
                    self.pull_average(
                        np.zeros((s, L), np.int32),
                        np.zeros((s, L), np.float32),
                    )
        n = self.query_compiles - before
        obs_events.emit("warmup_done", shapes_compiled=n)
        return n

    # ------------------------------------------------------------------
    # Persistence / lifecycle
    # ------------------------------------------------------------------

    def save(self, path: str, mode: str = "sharded") -> None:
        """Write both matrices + engine metadata (Glint ``matrix.save``,
        mllib:494 — each server flushing its shard to HDFS becomes each
        mesh slice flushing its row block). Blocks until committed.

        ``mode="sharded"`` (default) writes one ``.npy`` per owned model-axis
        row block — no host ever materializes a full table (the save-side
        analogue of killing the 8 GB broadcast ceiling, README.md:71-73),
        and under multi-host each process writes only its addressable
        shards. ``mode="single"`` writes one full-table file (handy for
        small models / interop). Both re-load onto any mesh shape.

        Crash safety (single-process): a fresh ``path`` is written as a
        temp directory and committed with one atomic rename — a kill
        mid-write leaves only an unreferenced ``*.tmp-*`` directory; an
        existing ``path`` is updated per-file via temp + ``os.replace``
        with the ``engine.json`` manifest written last. Multi-host keeps
        the legacy in-place protocol (every process writes disjoint
        shard files; the fit loop's barrier + ``train_state.json`` flip
        is the commit point there).
        """
        if jax.process_count() > 1:
            return self._save_multihost(path, mode)
        # Blocking path: views of the live tables are safe to serialize
        # directly — no donating dispatch can run until this returns —
        # so skip the deep copy (and its transient 2x host memory). In
        # sharded mode the blocks are LAZY (ISSUE 15 shard streaming):
        # each is copied to host, written, hashed into its sidecar
        # manifest, and dropped before the next one materializes — peak
        # host memory is one shard, never one table.
        files, meta = self._snapshot_host(
            self.syn0, self.syn1, mode, deep_copy=False,
            lazy=(mode == "sharded"),
        )
        self._write_snapshot(path, files, meta,
                             table_version=self.table_version)
        if mode == "sharded":
            # Only the blocks THIS engine serialized become clean —
            # under a replica save split the manifest names every
            # rank's blocks, but this rank vouches only for its own.
            shard_set = {
                b["file"] for t in meta["shards"].values() for b in t
            }
            self._mark_shards_clean(path, [
                fname for fname, _ in files if fname in shard_set
            ])

    # -- non-blocking checkpointing (ISSUE 5) ---------------------------

    def async_saves_enabled(self) -> bool:
        """Whether :meth:`save_async` will actually run non-blocking:
        single-process only (multi-host saves need the cross-process
        barrier before the state flip) and not escape-hatched by
        ``GLINT_SYNC_CKPT=1`` (README "Checkpointing")."""
        return (
            jax.process_count() == 1
            and os.environ.get("GLINT_SYNC_CKPT", "0") != "1"
        )

    def save_async(self, path: str, mode: str = "sharded",
                   on_commit=None) -> bool:
        """Non-blocking :meth:`save`: snapshot the (donation-cycled)
        tables to host memory — the device->host copy is the ONLY work
        on the calling thread — then hand serialization + atomic commit
        to the single background writer thread (utils/async_ckpt.py).
        At most one snapshot is in flight — a second request blocks for
        the first (counted in ``async_save_waits``). ``on_commit`` runs
        on the writer thread strictly AFTER the snapshot directory is
        committed (the fit loops flip ``train_state.json`` there), so a
        crash mid-write can never dangle the manifest. Falls back to a
        blocking save (returning False) under multi-host or
        ``GLINT_SYNC_CKPT=1``."""
        if not self.async_saves_enabled():
            self.save(path, mode)
            self._ckpt_forced_sync += 1
            if on_commit is not None:
                on_commit()
            return False
        if self._ckpt_writer is None:
            from glint_word2vec_tpu.utils.async_ckpt import (
                AsyncSnapshotWriter,
            )

            self._ckpt_writer = AsyncSnapshotWriter()
        writer = self._ckpt_writer
        # Block for any in-flight snapshot BEFORE materializing this one
        # (counted as back-pressure): transient host memory stays
        # bounded to one extra table pair.
        writer.wait_for_slot()
        files, meta = self._snapshot_host(self.syn0, self.syn1, mode)
        tv = self.table_version

        def job():
            with obs_events.span("ckpt_write", ckpt=path):
                self._write_snapshot(path, files, meta, table_version=tv)
                if on_commit is not None:
                    on_commit()

        writer.submit(job, label=path)
        return True

    def wait_pending_saves(self, *, reraise: bool = True,
                           timeout=None) -> None:
        """Barrier: block until no async save is in flight. The fit
        loops run it at fit exit (and implicitly before every state
        flip, since commits are ordered through the single writer);
        ``reraise=False`` is the exception-path variant that must not
        mask the original failure. ``timeout`` (seconds) raises
        ``utils.async_ckpt.SnapshotWriterHung`` naming the stuck job
        instead of hanging fit exit forever on a dead filesystem."""
        if self._ckpt_writer is not None:
            self._ckpt_writer.wait(reraise=reraise, timeout=timeout)

    def checkpoint_stats(self) -> dict:
        """Checkpoint telemetry for the heartbeat / serving snapshots:
        ``pending_async_saves`` (0/1), ``async_save_waits`` (blocked
        second requests — checkpoint back-pressure),
        ``checkpoint_write_seconds`` (last write job wall time),
        ``last_checkpoint_age_seconds`` (since the last commit, sync or
        async; None before any), ``forced_sync_saves``."""
        w = self._ckpt_writer
        last_write = self._ckpt_last_write_s
        last_commit = self._ckpt_last_commit
        ws = w.stats() if w is not None else {}
        if ws.get("last_write_seconds") is not None:
            last_write = ws["last_write_seconds"]
        if ws.get("last_commit_time") is not None:
            last_commit = max(last_commit or 0.0, ws["last_commit_time"])
        return {
            "pending_async_saves": int(ws.get("pending", 0)),
            "async_save_waits": int(ws.get("blocked_waits", 0)),
            "checkpoint_write_seconds": (
                round(last_write, 4) if last_write is not None else None
            ),
            "last_checkpoint_age_seconds": (
                round(time.time() - last_commit, 2)
                if last_commit else None
            ),
            "forced_sync_saves": self._ckpt_forced_sync,
            # Shard-streaming checkpoint telemetry (ISSUE 15): seconds
            # spent writing/verifying table shard blocks in the most
            # recent save/stage, in-place shards skipped as clean, and
            # the save path's peak concurrently-live host block bytes
            # (the bounded-by-one-shard contract, tests assert it).
            "checkpoint_shard_write_seconds": self._ckpt_shard_write_s,
            "checkpoint_shard_verify_seconds": self._ckpt_shard_verify_s,
            "checkpoint_shards_skipped": int(self._ckpt_shards_skipped),  # graftlint: ignore[sync-point] host counter
            "checkpoint_peak_block_bytes": int(  # graftlint: ignore[sync-point] host counter
                self._ckpt_peak_block_bytes
            ),
        }

    def _snapshot_host(self, syn0, syn1, mode: str, *,
                       deep_copy: bool = True, lazy: bool = False):
        """Blocking device->host snapshot of the given table pair:
        returns ``(files, meta)`` where ``files`` is a list of
        ``(filename, ndarray)`` blocks and ``meta`` the ``engine.json``
        manifest dict. With ``deep_copy`` (the async path) every block
        is a DEEP host copy — the live tables may be donated to the next
        dispatch the moment the caller resumes, and a zero-copy
        CPU-backend view of a donated buffer would read garbage; the
        copies run on a small thread pool (numpy releases the GIL for
        the memcpy) and their latency is the async checkpoint pause.
        ``deep_copy=False`` (the blocking save, which serializes before
        returning) keeps the views and skips the extra table-pair of
        transient host memory. ``lazy`` (blocking sharded saves only)
        defers each block to a zero-arg callable the writer materializes
        one at a time — the shard-streaming path whose peak host memory
        is ONE block (ISSUE 15); incompatible with ``deep_copy`` (an
        async snapshot must copy before the tables are donated)."""
        files = []
        if lazy and mode == "sharded" and not deep_copy:
            # Same ownership iteration as the materialized path (this
            # matters under a replica save split: each rank serializes
            # ONLY its own row block), just deferred: each producer
            # copies its one block at write time.
            shard_files = self._shard_manifest()
            for name, table in (("syn0", syn0), ("syn1", syn1)):
                for fname, produce in self._iter_owned_block_producers(
                    name, table
                ):
                    files.append([
                        fname,
                        lambda p=produce: np.asarray(
                            p(), dtype=np.float32
                        ),
                    ])
        elif mode == "sharded":
            shard_files = self._shard_manifest()
            for name, table in (("syn0", syn0), ("syn1", syn1)):
                for fname, block in self._iter_owned_blocks(name, table):
                    files.append([fname, block])
        elif mode == "single":
            for name, table in (("syn0", syn0), ("syn1", syn1)):
                files.append([
                    f"{name}.npy",
                    np.asarray(table)[: self.num_rows, : self.dim],
                ])
        else:
            raise ValueError("mode must be 'sharded' or 'single'")
        if deep_copy:
            # Deep-copy every block in parallel: np.asarray above may be
            # a zero-copy view of the live device buffer on the CPU
            # backend.
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                max_workers=min(max(len(files), 1), 8),
                thread_name_prefix="glint-snap",
            ) as pool:
                for entry, copied in zip(
                    files,
                    pool.map(
                        lambda e: np.array(e[1], dtype=np.float32), files
                    ),
                ):
                    entry[1] = copied
        else:
            # Cast-only (no copy for f32 tables): the blocking caller
            # serializes before any donating dispatch can run. Lazy
            # blocks cast inside their own producer.
            for entry in files:
                if not callable(entry[1]):
                    entry[1] = np.asarray(entry[1], dtype=np.float32)
        files = [tuple(e) for e in files]
        files.append(
            ("counts.npy", np.asarray(self._counts_unpadded(), np.int64))
        )
        meta = self._save_meta(mode)
        if mode == "sharded":
            meta["shards"] = shard_files
        return files, meta

    def set_save_split(self, rank: int, world: int) -> None:
        """Configure the replica save split (ISSUE 15): sharded saves
        slice the (replicated) tables into ``world`` row blocks and this
        engine writes only block ``rank`` — N replica ranks checkpoint
        one table in parallel, each copying/hashing 1/N of it. Rows
        layout only (column blocks span every row, so a row-replica
        split has nothing to divide). ``world == 1`` clears the split."""
        if world <= 1:
            self._save_split = None
            return
        if self.layout != "rows":
            raise ValueError("save split requires the rows layout")
        if not 0 <= rank < world:
            raise ValueError(f"rank {rank} not in [0, {world})")
        self._save_split = (int(rank), int(world))  # graftlint: ignore[sync-point] host config
        self._shard_dirty = None  # file geometry changed: all dirty

    def _shard_geometry(self):
        """(axis, per_shard, real_extent) of the sharded-save layout —
        the one place the manifest and the block producers agree on.
        Under a replica save split the block size comes from the split
        world, not the mesh model axis (every rank addresses every
        row)."""
        axis = "rows" if self.layout == "rows" else "cols"
        if self._save_split is not None and axis == "rows":
            _, world = self._save_split
            return axis, max(1, -(-self.padded_vocab // world)), self.num_rows
        per_shard = (
            self.rows_per_shard if axis == "rows" else self.cols_per_shard
        )
        real_extent = self.num_rows if axis == "rows" else self.dim
        return axis, per_shard, real_extent

    def _shard_manifest(self) -> dict:
        """Deterministic (mesh-geometry-only) shard-file manifest shared
        by the single-process snapshot and the multi-host in-place save
        — identical producers, so checkpoints from either path re-load
        interchangeably."""
        axis, per_shard, real_extent = self._shard_geometry()
        n_blocks = (
            self._save_split[1]
            if self._save_split is not None and axis == "rows"
            else self.num_model
        )
        shard_files = {"syn0": [], "syn1": []}
        for name in ("syn0", "syn1"):
            for k in range(n_blocks):
                start = k * per_shard
                stop = min(start + per_shard, real_extent)
                if start >= stop:
                    continue  # pure-padding block
                shard_files[name].append({
                    "file": f"{name}.{axis[0]}{start:012d}.npy",
                    "start": start, "stop": stop, "axis": axis,
                })
        return shard_files

    def _iter_owned_block_producers(self, name: str, table):
        """Yield ``(fname, producer)`` for every shard block this
        process owns — ``producer()`` materializes the host copy, so a
        caller can decide per shard whether to pay it (the skip-clean
        path never does). Ownership: replica 0 of each mesh-addressed
        block once, or — under a replica save split
        (:meth:`set_save_split`, tables replicated across ranks) — the
        rank's own row block, device-sliced so no producer ever copies
        more than one block."""
        axis, per_shard, real_extent = self._shard_geometry()
        if self._save_split is not None and axis == "rows":
            rank, world = self._save_split
            start = rank * per_shard
            stop = min(start + per_shard, real_extent)
            if start < stop:
                yield (
                    f"{name}.r{start:012d}.npy",
                    lambda: np.asarray(table[start:stop, : self.dim]),
                )
            return
        ix = 0 if axis == "rows" else 1
        for shard in table.addressable_shards:
            if shard.replica_id != 0:
                continue
            start = shard.index[ix].start or 0
            if start >= real_extent:
                continue
            stop = min(start + per_shard, real_extent)

            def produce(shard=shard, start=start, stop=stop):
                data = np.asarray(shard.data)
                if axis == "rows":
                    return data[: stop - start]
                return data[: self.num_rows, : stop - start]

            yield f"{name}.{axis[0]}{start:012d}.npy", produce

    def _iter_owned_blocks(self, name: str, table):
        """Materialized form of :meth:`_iter_owned_block_producers`:
        yields ``(fname, block)``. Blocks may be zero-copy views of the
        device buffers — callers that outlive the next donating
        dispatch must deep-copy."""
        for fname, produce in self._iter_owned_block_producers(
            name, table
        ):
            yield fname, produce()

    def _save_meta(self, mode: str) -> dict:
        return {
            "format": mode,
            "layout": self.layout,
            "vocab_size": self.vocab_size,
            "dim": self.dim,
            "num_negatives": self.num_negatives,
            "unigram_power": self.unigram_power,
            "unigram_table_size": self.unigram_table_size,
            "extra_rows": self.num_rows - self.vocab_size,
            "extra_rows_assigned": self.extra_rows_assigned,
            "dtype": (
                "bfloat16" if self._dtype == jnp.bfloat16 else "float32"
            ),
            "shared_negatives": self.shared_negatives,
        }

    def _write_snapshot(self, path: str, files, meta: dict,
                        table_version=None) -> None:
        """Serialize a host snapshot to disk with a crash-safe commit.

        Fresh ``path`` (every checkpoint dir): everything lands in a
        sibling temp directory first — each file fsync'd, so the rename
        can never commit a checkpoint whose bytes are still only in the
        page cache (a power loss after the rename must not roll the
        DATA back) — plus a ``manifest.json`` (per-file sha256 + sizes +
        ``table_version``, utils/integrity.py) so the committed
        directory is verifiable end to end — then ONE atomic rename
        makes the whole snapshot appear, followed by a parent-directory
        fsync to make the rename itself durable. A kill at any earlier
        point leaves only an unreferenced ``*.tmp-*`` directory (pruned
        by the next state flip). ``GLINT_CKPT_NO_FSYNC=1`` skips the
        fsyncs (fast local scratch / tests). Existing ``path``
        (re-saving a model dir in place): each file goes through temp +
        ``os.replace`` with ``engine.json`` after the data files and the
        integrity manifest last, so no file is ever truncated."""
        from glint_word2vec_tpu.utils import faults, integrity

        t0 = time.time()
        fsync = os.environ.get("GLINT_CKPT_NO_FSYNC", "0") != "1"
        # Table shard files get per-shard sidecar manifests (ISSUE 15)
        # and may arrive as LAZY zero-arg producers: materialize one,
        # write it, hash it, drop it — the shard-streaming memory bound
        # checkpoint_stats reports as ckpt_peak_block_bytes.
        shard_set = {
            b["file"] for t in (meta.get("shards") or {}).values()
            for b in t
        }
        eager_bytes = sum(
            a.nbytes for _, a in files if not callable(a)
        )
        peak = eager_bytes
        t_shards = 0.0

        def _emit(dirpath, fname, arr) -> None:
            nonlocal peak, t_shards
            ts = time.time()
            with open(os.path.join(dirpath, fname), "wb") as f:
                np.save(f, arr)
                if fsync:
                    f.flush()
                    os.fsync(f.fileno())
            if fname in shard_set:
                integrity.write_shard_manifest(
                    dirpath, fname,
                    integrity.build_shard_manifest(
                        dirpath, fname, table_version
                    ),
                    fsync=fsync,
                )
                faults.fire("ckpt.shard_commit")
                t_shards += time.time() - ts

        if not os.path.exists(path):
            tmp = f"{path}.tmp-{os.getpid()}"
            if os.path.exists(tmp):
                import shutil

                shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp)
            for fname, arr in files:
                if callable(arr):
                    arr = arr()
                    peak = max(peak, eager_bytes + arr.nbytes)
                _emit(tmp, fname, arr)
                del arr
            with open(os.path.join(tmp, "engine.json"), "w") as f:
                json.dump(meta, f)
                if fsync:
                    f.flush()
                    os.fsync(f.fileno())
            integrity.write_manifest(
                tmp,
                integrity.build_manifest(
                    tmp,
                    [
                        fname for fname, _ in files
                        if fname not in shard_set
                    ] + ["engine.json"],
                    table_version,
                    table_dtype=meta.get("dtype"),
                ) | (
                    {"version": 2, "shard_files": sorted(shard_set)}
                    if shard_set else {}
                ),
                fsync=fsync,
            )
            if fsync:
                # The dirents too, not just the file data: fsync(file)
                # alone need not persist the entry in its directory.
                self._fsync_dir(tmp)
            faults.fire("ckpt.pre_rename")
            self._commit_snapshot_dir(tmp, path)
            faults.fire("ckpt.post_rename")
            if fsync:
                self._fsync_dir(os.path.dirname(os.path.abspath(path)))
        else:
            # In-place update (model re-save over an existing dir, or
            # re-writing an orphaned checkpoint dir after a crash):
            # per-file temp + replace — every file is always either the
            # old or the new complete version — with the same fsync
            # durability as the fresh-dir path, and the engine.json
            # manifest last.
            def _put(fname, writer_fn):
                tmp_f = os.path.join(path, f"{fname}.tmp.{os.getpid()}")
                with open(tmp_f, "wb") as f:
                    writer_fn(f)
                    if fsync:
                        f.flush()
                        os.fsync(f.fileno())
                os.replace(tmp_f, os.path.join(path, fname))

            for fname, arr in files:
                # Skip-clean fast path (ISSUE 15 satellite): an
                # in-place re-save never copies or rewrites a shard the
                # last committed save to this path already holds —
                # ranks whose shards are all clean pay zero host-copy
                # time on the caller thread.
                if (
                    fname in shard_set
                    and not self._shard_is_dirty(fname, path)
                    and os.path.exists(os.path.join(path, fname))
                    and os.path.exists(os.path.join(
                        path, fname + integrity.SHARD_MANIFEST_SUFFIX
                    ))
                ):
                    self._ckpt_shards_skipped += 1
                    continue
                if callable(arr):
                    arr = arr()
                    peak = max(peak, eager_bytes + arr.nbytes)
                ts = time.time()
                _put(fname, lambda f, a=arr: np.save(f, a))
                if fname in shard_set:
                    integrity.write_shard_manifest(
                        path, fname,
                        integrity.build_shard_manifest(
                            path, fname, table_version
                        ),
                        fsync=fsync,
                    )
                    faults.fire("ckpt.shard_commit")
                    t_shards += time.time() - ts
                del arr
            _put(
                "engine.json",
                lambda f: f.write(json.dumps(meta).encode()),
            )
            integrity.write_manifest(
                path,
                integrity.build_manifest(
                    path,
                    [
                        fname for fname, _ in files
                        if fname not in shard_set
                    ] + ["engine.json"],
                    table_version,
                    table_dtype=meta.get("dtype"),
                ) | (
                    {"version": 2, "shard_files": sorted(shard_set)}
                    if shard_set else {}
                ),
                fsync=fsync,
            )
            if fsync:
                self._fsync_dir(os.path.abspath(path))
        self._ckpt_last_write_s = time.time() - t0
        self._ckpt_last_commit = time.time()
        self._ckpt_shard_write_s = round(t_shards, 6)
        self._ckpt_peak_block_bytes = int(peak)  # graftlint: ignore[sync-point] host counter

    @staticmethod
    def _fsync_dir(dirpath: str) -> None:
        """Make renames inside ``dirpath`` durable; best-effort (some
        filesystems refuse directory fsync)."""
        try:
            dfd = os.open(dirpath, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass

    @staticmethod
    def _commit_snapshot_dir(tmp: str, path: str) -> None:
        """THE commit point of a fresh-directory snapshot: one atomic
        rename. Kept as its own (monkeypatchable) seam so the
        crash-mid-checkpoint test can kill the writer between temp-write
        and rename and assert the previous checkpoint survives."""
        os.rename(tmp, path)

    def _save_multihost(self, path: str, mode: str = "sharded") -> None:
        """In-place save for multi-host runs: every process writes its
        own shard files into ``path`` — mesh-addressed blocks on the
        SPMD path, the rank's row block under a replica save split
        (:meth:`set_save_split`) — each with its per-shard sidecar
        manifest (ISSUE 15: integrity without any rank ever seeing the
        whole table); process 0 writes counts + the version-2 top-level
        manifest. Commit/crash-safety is the caller's barrier +
        ``train_state.json`` flip. Clean shards (unchanged since the
        last committed save to this same path) are skipped entirely —
        no host copy, no write (``shards_skipped``)."""
        from glint_word2vec_tpu.utils import faults, integrity

        t0 = time.time()
        os.makedirs(path, exist_ok=True)
        shard_files = {"syn0": [], "syn1": []}
        written = []
        t_shards = 0.0
        peak = 0
        if mode == "sharded":
            # The manifest is deterministic from mesh geometry (identical on
            # every process); files are written only by a process that can
            # address the block, each block by exactly one process. Blocks
            # are row ranges under the rows layout and column ranges under
            # the dims layout ("axis" in each manifest entry; absent =
            # rows, for round-2 checkpoints).
            shard_files = self._shard_manifest()
            for name, table in (("syn0", self.syn0), ("syn1", self.syn1)):
                for fname, produce in self._iter_owned_block_producers(
                    name, table
                ):
                    if (
                        not self._shard_is_dirty(fname, path)
                        and os.path.exists(os.path.join(path, fname))
                        and os.path.exists(os.path.join(
                            path,
                            fname + integrity.SHARD_MANIFEST_SUFFIX,
                        ))
                    ):
                        self._ckpt_shards_skipped += 1
                        written.append(fname)
                        continue
                    ts = time.time()
                    block = np.asarray(produce(), dtype=np.float32)
                    peak = max(peak, block.nbytes)
                    atomic_write_npy(os.path.join(path, fname), block)
                    del block
                    integrity.write_shard_manifest(
                        path, fname,
                        integrity.build_shard_manifest(
                            path, fname, self.table_version
                        ),
                    )
                    faults.fire("ckpt.shard_commit")
                    t_shards += time.time() - ts
                    written.append(fname)
        else:
            if mode != "single":
                raise ValueError("mode must be 'sharded' or 'single'")
            if jax.process_index() == 0:
                syn0 = np.asarray(self.syn0, dtype=np.float32)[
                    : self.num_rows, : self.dim
                ]
                syn1 = np.asarray(self.syn1, dtype=np.float32)[
                    : self.num_rows, : self.dim
                ]
                atomic_write_npy(os.path.join(path, "syn0.npy"), syn0)
                atomic_write_npy(os.path.join(path, "syn1.npy"), syn1)
        if jax.process_index() == 0:
            counts = np.asarray(self._counts_unpadded(), dtype=np.int64)
            atomic_write_npy(os.path.join(path, "counts.npy"), counts)
        meta = self._save_meta(mode)
        if mode == "sharded":
            meta["shards"] = shard_files
        # Multi-host: every process wrote disjoint shard files; exactly one
        # writes the manifest (it is deterministic from mesh geometry).
        # Per-file atomic (temp + replace, engine.json last) so a worker
        # killed mid-save into a previously-committed dir can never leave
        # a torn .npy behind — the in-place twin of the fresh-dir
        # temp+rename commit.
        if jax.process_index() == 0:
            atomic_write_json(os.path.join(path, "engine.json"), meta)
            # Version-2 integrity manifest (ISSUE 15): shard files are
            # named here but hashed by their OWN writers into sidecar
            # manifests, so the multi-host path is finally verifiable —
            # no single writer ever needed to see every shard. Process
            # 0 hashes only the small files it wrote itself. The
            # caller's barrier orders this before any state flip that
            # would make the directory authoritative.
            if mode == "sharded":
                all_shards = sorted(
                    b["file"] for t in shard_files.values() for b in t
                )
                integrity.write_manifest(
                    path,
                    integrity.build_manifest(
                        path, ["counts.npy", "engine.json"],
                        self.table_version,
                        table_dtype=meta.get("dtype"),
                    ) | {"version": 2, "shard_files": all_shards},
                )
            else:
                # Single-file multi-host saves stay manifest-less (one
                # writer, but the shard protocol does not apply); drop
                # any stale manifest a previous save left behind.
                try:
                    os.remove(os.path.join(path, "manifest.json"))
                except OSError:
                    pass
        self._ckpt_last_write_s = time.time() - t0
        self._ckpt_last_commit = time.time()
        self._ckpt_shard_write_s = round(t_shards, 6)
        self._ckpt_peak_block_bytes = int(peak)  # graftlint: ignore[sync-point] host counter
        if mode == "sharded":
            self._mark_shards_clean(path, written)

    def _counts_unpadded(self) -> np.ndarray:
        # Recover counts from the alias table is lossy; engines keep them.
        return self._counts

    @classmethod
    def load(cls, path: str, mesh, **overrides) -> "EmbeddingEngine":
        """Rebuild an engine from :meth:`save` output onto any mesh shape —
        the analogue of re-homing a saved model onto a different PS cluster
        (mllib:696-725, ml:584-586). The source and target mesh shapes are
        independent: sharded files are re-sliced to whatever row blocks the
        new mesh owns, streamed via mmap (no full-table host copy)."""
        with open(os.path.join(path, "engine.json")) as f:
            meta = json.load(f)
        counts = np.load(os.path.join(path, "counts.npy"))
        eng = cls(
            mesh,
            meta["vocab_size"],
            meta["dim"],
            counts,
            layout=overrides.get("layout", meta.get("layout", "rows")),
            num_negatives=overrides.get("num_negatives", meta["num_negatives"]),
            unigram_power=overrides.get(
                "unigram_power", meta.get("unigram_power", 0.75)
            ),
            unigram_table_size=overrides.get(
                "unigram_table_size", meta.get("unigram_table_size")
            ),
            dtype=overrides.get("dtype", meta["dtype"]),
            extra_rows=meta.get("extra_rows", 0),
            shared_negatives=overrides.get(
                "shared_negatives", meta.get("shared_negatives", 0)
            ),
        )
        eng.load_tables(path)
        return eng

    def load_tables(self, path: str, *, verify: bool = True) -> None:
        """Install table values from a :meth:`save` directory (either
        format) into this engine, re-sharding to its mesh. Each device
        shard is assembled independently from the overlapping source row
        blocks (mmap-sliced), so peak host memory is one shard, not one
        table.

        ``verify`` (default on) checks the directory against its
        ``manifest.json`` first — sizes + sha256 of every file — and
        raises ``utils.integrity.CheckpointCorruptError`` on mismatch
        or a partial directory, so bit rot can never load silently.
        Legacy directories with no manifest load unverified;
        ``GLINT_CKPT_NO_VERIFY=1`` downgrades to size-only checks.

        Implemented as :meth:`stage_tables` (disk reads + device
        transfers, safe to run concurrently with live query dispatches)
        followed by :meth:`adopt_tables` (the attribute flip + version
        tick). The serving hot-swap path (ISSUE 10) calls the two
        halves itself so a new table generation loads entirely OFF the
        request path and the flip happens under the device lock."""
        self.adopt_tables(self.stage_tables(path, verify=verify))

    def stage_tables(self, path: str, *, verify: bool = True):
        """Read a :meth:`save` directory and build the re-sharded device
        arrays WITHOUT touching the engine's live state: no attribute is
        assigned, no version ticked, and in-flight dispatches against
        the current tables are unaffected. Returns an opaque staged
        payload for :meth:`adopt_tables`. Raises exactly as
        :meth:`load_tables` (geometry mismatch, integrity failure)."""
        if verify:
            from glint_word2vec_tpu.utils import integrity

            tv0 = time.time()
            integrity.verify_snapshot_dir(path)
            # Shard verify cost is the dominant share on big tables
            # (per-shard sidecar hashing, ISSUE 15) — surfaced on the
            # heartbeat next to the write-side twin.
            self._ckpt_shard_verify_s = round(time.time() - tv0, 6)
        with open(os.path.join(path, "engine.json")) as f:
            meta = json.load(f)
        if (meta["vocab_size"], meta.get("extra_rows", 0)) != (
            self.vocab_size, self.num_rows - self.vocab_size
        ) or meta["dim"] != self.dim:
            raise ValueError(
                f"checkpoint at {path} has geometry "
                f"(V={meta['vocab_size']}, extra={meta.get('extra_rows', 0)}, "
                f"d={meta['dim']}), engine has (V={self.vocab_size}, "
                f"extra={self.num_rows - self.vocab_size}, d={self.dim})"
            )
        fmt = meta.get("format", "single")
        tsh = self._table_sharding()
        staged = {"meta": meta}
        for name in ("syn0", "syn1"):
            # Source blocks as (row range, col range, data), covering any
            # mix of row-block (rows layout), col-block (dims layout), or
            # whole-table files — so checkpoints re-home across BOTH mesh
            # shapes and layouts.
            if fmt == "sharded":
                blocks = []
                for b in meta["shards"][name]:
                    data = np.load(
                        os.path.join(path, b["file"]), mmap_mode="r"
                    )
                    if b.get("axis", "rows") == "rows":
                        blocks.append(
                            ((b["start"], b["stop"]), (0, data.shape[1]), data)
                        )
                    else:
                        blocks.append(
                            ((0, data.shape[0]), (b["start"], b["stop"]), data)
                        )
            else:
                arr = np.load(os.path.join(path, f"{name}.npy"), mmap_mode="r")
                blocks = [((0, arr.shape[0]), (0, arr.shape[1]), arr)]

            def assemble(index, _blocks=blocks):
                row_sl, col_sl = index[0], index[1]
                r0 = row_sl.start or 0
                r1 = (
                    row_sl.stop if row_sl.stop is not None
                    else self.padded_vocab
                )
                c0 = col_sl.start or 0
                c1 = (
                    col_sl.stop if col_sl.stop is not None
                    else self.padded_dim
                )
                out = np.zeros((r1 - r0, c1 - c0), np.float32)
                for (br0, br1), (bc0, bc1), data in _blocks:
                    rlo, rhi = max(r0, br0), min(r1, br1)
                    clo, chi = max(c0, bc0), min(c1, bc1)
                    if rlo < rhi and clo < chi:
                        out[rlo - r0 : rhi - r0, clo - c0 : chi - c0] = data[
                            rlo - br0 : rhi - br0, clo - bc0 : chi - bc0
                        ]
                # Restore-side memory bound (ISSUE 15): each device
                # shard assembles from mmap slices into exactly one
                # shard-sized host buffer — the peak the shard-streaming
                # restore test asserts against.
                self._stage_peak_block_bytes = max(
                    self._stage_peak_block_bytes, out.nbytes
                )
                return out.astype(self._dtype)

            staged[name] = jax.make_array_from_callback(
                (self.padded_vocab, self.padded_dim), tsh, assemble
            )
        return staged

    def adopt_tables(self, staged) -> None:
        """Flip the live tables to a :meth:`stage_tables` payload: two
        attribute assignments, the assigned-extra-row count from the
        snapshot's manifest, and ONE ``table_version`` tick (norms
        cache + serving result caches drop). Microseconds — the whole
        point of the split is that this is all the serving hot-swap
        holds the device lock for."""
        self.syn0 = staged["syn0"]
        self.syn1 = staged["syn1"]
        # graftlint: ignore[sync-point] meta is the parsed engine.json dict
        self.extra_rows_assigned = int(
            staged["meta"].get("extra_rows_assigned", 0)
        )
        self._tick_tables("load_tables")

    def set_tables(self, syn0: np.ndarray, syn1: np.ndarray) -> None:
        """Install host table values (unpadded, all num_rows rows),
        re-padding and re-sharding."""
        if syn0.shape != (self.num_rows, self.dim):
            raise ValueError("syn0 shape mismatch")
        if syn1.shape != (self.num_rows, self.dim):
            raise ValueError("syn1 shape mismatch")
        pad = (
            (0, self.padded_vocab - self.num_rows),
            (0, self.padded_dim - self.dim),
        )
        tsh = self._table_sharding()
        full0 = np.pad(syn0, pad).astype(np.float32)
        full1 = np.pad(syn1, pad).astype(np.float32)
        self.syn0 = jax.device_put(jnp.asarray(full0, dtype=self._dtype), tsh)
        self.syn1 = jax.device_put(jnp.asarray(full1, dtype=self._dtype), tsh)
        self._tick_tables("set_tables")

    def resident_bytes(self) -> int:
        """Device bytes the live tables (+ adopted ANN index) hold —
        the per-model cost the serving catalog's memory budget accounts
        (ISSUE 20). Zero after :meth:`release_tables`."""
        n = 0
        for a in (self.syn0, self.syn1):
            if a is not None:
                # graftlint: ignore[sync-point] .size is array metadata
                n += int(a.size) * a.dtype.itemsize
        idx = self._ann
        if idx is not None:
            for name in ("centroids", "members", "member_invn",
                         "member_rows"):
                a = getattr(idx, name, None)
                if a is not None and hasattr(a, "size"):
                    # graftlint: ignore[sync-point] .size is metadata
                    n += int(a.size) * a.dtype.itemsize
        return n

    @property
    def tables_resident(self) -> bool:
        """Whether the tables currently occupy device memory (False
        between :meth:`release_tables` and the next adopt/stage-in)."""
        return self.syn0 is not None

    def release_tables(self) -> None:
        """Stage-out: free the table (+ ANN index) device buffers
        WITHOUT destroying the engine — compiled programs, vocabulary
        geometry, and checkpoint machinery all survive, so a later
        :meth:`stage_tables` + :meth:`adopt_tables` round trip makes
        the engine serve again with zero new compiles. Querying while
        released fails (callers gate on :attr:`tables_resident`);
        unlike :meth:`destroy` the corpus/training buffers (if any)
        are left alone."""
        self.wait_pending_saves(reraise=False)
        for a in (self.syn0, self.syn1):
            try:
                a.delete()
            except Exception:
                pass
        self.syn0 = self.syn1 = None
        self._ann = None
        self._tick_tables("release_tables")

    def destroy(self) -> None:
        """Free device memory (Glint ``matrix.destroy``, mllib:665).
        Drains any in-flight async save first (its snapshot copies are
        separate buffers, but a half-written checkpoint helps nobody)."""
        self.wait_pending_saves(reraise=False)
        corpus = getattr(self, "_corpus", None) or ()
        compacted = getattr(self, "_corpus_compacted", None) or ()
        keep_prob = getattr(self, "_keep_prob", None)
        extras = (keep_prob,) if keep_prob is not None else ()
        pre = getattr(self, "_compact_prefetch", None)
        prefetched = pre[1:3] if pre is not None else ()
        self._compact_prefetch = None
        for a in (
            self.syn0, self.syn1, self._prob, self._alias,
            *corpus, *compacted, *extras, *prefetched,
        ):
            try:
                a.delete()
            except Exception:
                pass
        self.syn0 = self.syn1 = self._prob = self._alias = None
        self._corpus = None
        self._corpus_compacted = None
        self._keep_prob = None
        self._ann = None
        self._tick_tables("destroy")

    @property
    def cols(self) -> int:
        """Column count == vector size (Glint ``matrix.cols``, mllib:473)."""
        return self.dim
