"""Parallel layer: device mesh construction and the sharded embedding engine."""
