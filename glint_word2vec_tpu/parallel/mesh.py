"""Device-mesh construction for the sharded embedding engine.

The reference's deployment geometry — ``numPartitions`` Spark workers x
``numParameterServers`` Glint servers (README.md:45-57, mllib:354-362) — maps
onto a 2-D TPU mesh:

  axis "data"  (size = num_partitions analogue): batch rows are sharded here;
               each slice processes its share of every minibatch.
  axis "model" (size = numParameterServers analogue): the vocab rows of both
               embedding tables are sharded here; each slice owns
               1/num_shards of syn0 and syn1 (README.md:69).

Collectives ride ICI: a psum over "model" replaces the client<->server
pull RPCs; an all_gather over "data" replaces the async push of gradient
scalars (SURVEY.md §2.3 comm-backend row).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    num_data: Optional[int] = None,
    num_model: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ("data", "model") mesh over the available devices.

    Defaults: all devices on the model axis (pure vocab sharding — the
    topology closest to the reference's PS cluster) unless sizes are given.
    When both sizes are given, the first ``num_data * num_model`` devices
    are used (so a small mesh can run on a larger host, mirroring the
    reference's freedom to run fewer parameter servers than executors).
    """
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if num_data is None and num_model is None:
        num_data, num_model = 1, n
    elif num_data is None:
        if n % num_model:
            raise ValueError(f"{n} devices not divisible by num_model={num_model}")
        num_data = n // num_model
    elif num_model is None:
        if n % num_data:
            raise ValueError(f"{n} devices not divisible by num_data={num_data}")
        num_model = n // num_data
    if num_data * num_model > n:
        raise ValueError(
            f"mesh {num_data}x{num_model} needs more than the {n} available devices"
        )
    grid = np.asarray(devs[: num_data * num_model]).reshape(num_data, num_model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def table_sharding(mesh: Mesh) -> NamedSharding:
    """Vocab-row sharding for syn0/syn1: rows split over "model", dim
    replicated — each model slice is one 'parameter server'."""
    return NamedSharding(mesh, P(MODEL_AXIS, None))


def table_sharding_dims(mesh: Mesh) -> NamedSharding:
    """Dim/column sharding for syn0/syn1: every shard holds ALL vocab rows
    x 1/n of the embedding dimensions — the CIKM'16 partitioning the
    reference's parameter servers use (SURVEY.md §2.2 sharding note:
    servers compute *partial* dot products the client sums). Model-axis
    traffic becomes scalar logit partials instead of full rows."""
    return NamedSharding(mesh, P(None, MODEL_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Minibatch rows split over "data", replicated over "model"."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(n: int, m: int) -> int:
    """Smallest multiple of m that is >= n."""
    return ((n + m - 1) // m) * m
