"""Multi-host distributed backend: DCN bring-up, global meshes, host-local
batch feeding.

Reference mapping (SURVEY.md §2.2, §3.5): the Glint fork's cluster substrate
is an Akka-remoting actor system spanning a Spark app — a master on the
driver, servers on executors, workers connecting by host
(``Client.getHostConfig(parameterServerHost)``, mllib:358-360), launched
either inside the training app (``Client.runWithWord2VecMatrixOnSpark``,
mllib:355) or as a standalone cluster app (``glint.Main``, README.md:52-57).
The TPU-native restatement has no server processes at all:

  * cluster bring-up   -> :func:`initialize` (JAX distributed runtime over
    DCN: one coordinator, N host processes, each owning its local chips)
  * PS/worker topology -> :func:`make_global_mesh` (("data", "model") mesh
    over ALL processes' devices; ICI inside a slice, DCN across slices)
  * Spark partition feeding its executor -> :func:`process_batch_slice` +
    :func:`make_global_batch` (each host materializes only its data-axis
    rows; ``jax.make_array_from_process_local_data`` assembles the global
    batch without any host ever holding it all)
  * separate-cluster mode / host override at load -> meshes are
    reconstructable on any topology; checkpoints re-home freely
    (engine.load, mllib:696-725 analogue)

Single-process use is the degenerate case throughout: every helper works
unchanged (and is unit-tested) with ``process_count == 1``.
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence, Tuple

import numpy as np

from glint_word2vec_tpu.parallel.mesh import DATA_AXIS, make_mesh

logger = logging.getLogger(__name__)


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
) -> None:
    """Bring up the JAX distributed runtime (DCN coordination layer).

    The analogue of starting/joining the Glint cluster: where the reference
    spawns a master + parameter servers and connects by host:port
    (mllib:354-360; separate-glint.conf ports), TPU pods coordinate through
    one bootstrap service. With no arguments, TPU pod environments
    auto-discover topology (the "integrated" deployment, README.md:45-50);
    explicit arguments are the "separate cluster" analogue (README.md:52-57)
    for GPU/CPU multi-host or custom launchers.

    Call once per host process, before any other JAX API. No-op if the
    distributed runtime is already initialized.
    """
    import os

    import jax

    if _is_initialized(jax):  # already up
        logger.info("jax.distributed already initialized; skipping")
        return
    # Multi-process CPU runs (the supervisor's gang mode on dev boxes /
    # CI) need an explicit cross-host collectives backend: without it
    # jaxlib raises "Multiprocess computations aren't implemented on
    # the CPU backend" at the first psum. Opt into gloo when the run is
    # pinned to CPU and the operator hasn't chosen an implementation
    # (older jax versions without the option just skip this).
    platforms = (
        jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS", "")
    )
    if (
        "cpu" in (platforms or "")
        and "JAX_CPU_COLLECTIVES_IMPLEMENTATION" not in os.environ
    ):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # pragma: no cover - jax without the option
            pass
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = list(local_device_ids)
    jax.distributed.initialize(**kwargs)
    logger.info(
        "distributed runtime up: process %d/%d, %d local / %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )


def _is_initialized(jax) -> bool:
    """Best-effort "is the distributed runtime already up?" check, using the
    public API where this JAX version has one and falling back to the
    private global state otherwise (the private attribute may move across
    releases; the fallback failing open just means jax.distributed.initialize
    itself reports the duplicate initialization)."""
    is_init = getattr(jax.distributed, "is_initialized", None)
    if callable(is_init):
        try:
            return bool(is_init())
        except Exception:  # pragma: no cover - defensive
            pass
    try:
        state = getattr(jax._src.distributed, "global_state", None)
        return state is not None and state.client is not None
    except Exception:  # pragma: no cover - defensive
        return False


def make_global_mesh(
    num_data: Optional[int] = None, num_model: Optional[int] = None
):
    """("data", "model") mesh over ALL hosts' devices.

    Layout policy: the device grid is built from the global device list in
    process-major order, so with ``num_data >= process_count`` each host's
    chips form whole data-axis rows — the model axis (the hot psum/all_gather
    paths, engine._pull_rows/_scatter_rows) stays inside one host's slice and
    rides ICI, while the data axis alone crosses DCN. That is the same
    locality split the reference gets from server-side compute: heavy traffic
    stays server-local; only batch-level exchange crosses the network
    (SURVEY.md §2.3 comm-backend row).
    """
    import jax

    return make_mesh(num_data, num_model, devices=jax.devices())


def process_batch_slice(mesh, process_index: Optional[int] = None,
                        process_count: Optional[int] = None) -> Tuple[float, float]:
    """This host's fraction [lo, hi) of the global batch's data-axis rows.

    The feeding contract mirrors Spark's partition->executor locality
    (repartition(numPartitions) at mllib:345): each host's corpus reader
    produces only the rows its local devices will consume. Returns fractions
    so callers can slice any global batch size.
    """
    import jax

    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    return pi / pc, (pi + 1) / pc


def make_global_batch(mesh, *host_arrays: np.ndarray, data_axis: int = 0):
    """Assemble global device arrays from per-host batch slices.

    Each process passes its own rows (``global_rows / process_count`` each,
    along ``data_axis``); the result is a tuple of global ``jax.Array``s
    sharded over "data" on that axis, with every shard living on the host
    that produced it — no cross-host copy of batch data, exactly like a
    Spark partition never leaving its executor until the (index-only) PS
    traffic. Use ``data_axis=1`` for the stacked (K, B, ...) groups fed to
    ``EmbeddingEngine.train_steps``. Works unchanged for one process.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    out = []
    for a in host_arrays:
        dims = [None] * a.ndim
        dims[data_axis] = DATA_AXIS
        spec = P(*dims)
        out.append(
            jax.make_array_from_process_local_data(
                NamedSharding(mesh, spec), np.asarray(a)
            )
        )
    return tuple(out)


def shard_sentences_for_process(
    sentences, process_index: Optional[int] = None,
    process_count: Optional[int] = None,
):
    """Partition a sentence list across host processes (round-robin).

    The analogue of ``repartition(numPartitions)`` placing RDD partitions on
    executors (mllib:345): each host trains on its own corpus slice. Round-
    robin (not contiguous blocks) so document-ordered corpora spread topical
    clusters evenly across hosts within every epoch. Every process receives
    the SAME number of sentences (the remainder ``len % process_count`` is
    dropped): multi-host SPMD training requires every process to dispatch
    the same step count, or the program deadlocks at the first collective
    one host doesn't reach. Equal sentence counts make per-host step counts
    *near*-equal; the feeding loop must still equalize exactly (pad the
    short hosts' final groups with zero-mask batches, as fit() already does
    for epoch tails) before dispatching.
    """
    import jax

    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    per = len(sentences) // pc
    return [sentences[i * pc + pi] for i in range(per)]


def shard_flat_for_process(
    ids: np.ndarray,
    offsets: np.ndarray,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Flat-encoding (ids, offsets) variant of
    :func:`shard_sentences_for_process`: same round-robin split, same
    drop-the-remainder equal-count contract, without materializing
    per-sentence Python objects (the streaming fit_file path)."""
    import jax

    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    n = len(offsets) - 1
    per = n // pc
    picks = np.arange(per) * pc + pi
    lens = np.diff(offsets)
    my_lens = lens[picks]
    out_offsets = np.zeros(per + 1, dtype=np.int64)
    np.cumsum(my_lens, out=out_offsets[1:])
    total = int(my_lens.sum())
    # Vectorized shard copy (this is the streaming path built for corpora
    # with tens of millions of sentences — a per-sentence Python loop here
    # would dominate every fit_file start): source index of each output
    # word = its sentence's source start + its position within the sentence.
    src_start = np.repeat(offsets[picks], my_lens)
    pos_in_sent = np.arange(total, dtype=np.int64) - np.repeat(
        out_offsets[:-1], my_lens
    )
    out_ids = np.ascontiguousarray(ids[src_start + pos_in_sent], dtype=np.int32)
    return out_ids, out_offsets


def shard_span(
    n_items: int, process_index: int, process_count: int
) -> Tuple[int, int]:
    """Contiguous, balanced ``[start, end)`` span for one rank over
    ``n_items`` — the bulk-transform input split
    (``glint_word2vec_tpu.batch``). Unlike
    :func:`shard_flat_for_process` (round-robin, drop-the-remainder:
    gradient-path semantics where equal per-rank counts matter more
    than coverage), this covers EVERY item exactly once: the bulk
    transform's contract is one output row per input line, so nothing
    may be dropped. The first ``n_items % process_count`` ranks take
    one extra item; spans are a pure function of the three arguments,
    so every rank (and every resume) derives the same split with no
    coordination."""
    if process_count < 1:
        raise ValueError("process_count must be >= 1")
    if not 0 <= process_index < process_count:
        raise ValueError(
            f"process_index {process_index} out of range for "
            f"{process_count} processes"
        )
    if n_items < 0:
        raise ValueError("n_items must be >= 0")
    q, r = divmod(n_items, process_count)
    start = process_index * q + min(process_index, r)
    return start, start + q + (1 if process_index < r else 0)


def shard_flat_locality(
    ids: np.ndarray,
    offsets: np.ndarray,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Locality-aware replica sharding (ISSUE 16, arXiv:1909.03359):
    cluster each replica's sentences by their RAREST token so per-rank
    touched-row sets concentrate, shrinking the touched-row unions
    that size every exchange buffer (and letting the adaptive capacity
    walk down further).

    Vocabulary ids are frequency-ordered (0 = most frequent), so a
    sentence's max token id is its rarest word — the tail rows only
    that sentence's shard will touch. Sentences sort by that key
    (stable, so equal-key sentences keep corpus order) and split into
    ``process_count`` CONTIGUOUS runs balanced by cumulative word
    count: every replica sees the same deterministic assignment
    (computed redundantly from the full corpus on every rank — same
    contract as the round-robin sharder), head-word rows stay shared
    (they appear everywhere) while tail rows concentrate on one rank.
    Ranks can differ by up to one sentence in word count — the
    lockstep filler protocol absorbs the skew, exactly as it does for
    the round-robin remainder."""
    import jax

    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    n = len(offsets) - 1
    if n == 0 or pc == 1:
        return (
            np.ascontiguousarray(ids, dtype=np.int32),
            np.asarray(offsets, dtype=np.int64),  # graftlint: ignore[sync-point] host corpus array
        )
    lens = np.diff(offsets)
    nonempty = lens > 0
    # Rarest-token key per sentence: segment max over the flat ids
    # (reduceat needs in-range starts; empty segments return a
    # neighbor's value and are masked to -1, sorting first and landing
    # harmlessly in rank 0's run).
    seg_max = np.zeros(n, dtype=np.int64)
    if len(ids):
        starts = np.minimum(offsets[:-1], len(ids) - 1)
        seg_max = np.maximum.reduceat(ids.astype(np.int64), starts)
    keys = np.where(nonempty, seg_max, -1)
    order = np.argsort(keys, kind="stable")
    # Contiguous word-count-balanced runs over the sorted order: rank r
    # takes sentences whose cumulative word count lands in
    # (r * total/pc, (r+1) * total/pc].
    sorted_lens = lens[order]
    cum = np.cumsum(sorted_lens)
    total = int(cum[-1]) if n else 0  # graftlint: ignore[sync-point] host numpy scalar
    bounds = (total * (np.arange(pc + 1))) // pc
    # Sentence s goes to the rank whose (lo, hi] word-window contains
    # its cumulative end — searchsorted on the shared boundary grid.
    assign = np.searchsorted(bounds[1:-1], cum, side="left")
    picks = order[assign == pi]
    picks.sort()  # keep corpus order within the shard (RNG streams)
    my_lens = lens[picks]
    per = len(picks)
    out_offsets = np.zeros(per + 1, dtype=np.int64)
    np.cumsum(my_lens, out=out_offsets[1:])
    tot = int(my_lens.sum())  # graftlint: ignore[sync-point] host numpy scalar
    src_start = np.repeat(offsets[picks], my_lens)
    pos_in_sent = np.arange(tot, dtype=np.int64) - np.repeat(
        out_offsets[:-1], my_lens
    )
    out_ids = np.ascontiguousarray(
        ids[src_start + pos_in_sent], dtype=np.int32
    )
    return out_ids, out_offsets


def allgather_host(arr: np.ndarray) -> np.ndarray:
    """Host-level allgather of one fixed-shape numpy array: returns
    ``(process_count, *shape)`` with rank order preserved. The wire of
    the replica-exchange protocol (parallel/exchange.py): gloo between
    CPU gang processes, DCN across pod hosts, via
    ``multihost_utils.process_allgather`` — each distinct buffer shape
    compiles exactly one collective, so the exchange's fixed-capacity
    padded buffers keep this compile-once. Single-process returns
    ``arr[None]`` without touching the collective machinery."""
    import jax

    a = np.asarray(arr)
    if jax.process_count() == 1:
        return a[None]
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(a))


def per_process_word_counts(
    sentence_lengths: np.ndarray, process_count: int
) -> np.ndarray:
    """Word count each process's shard will hold under the round-robin
    split — computable on EVERY host with no communication (each host sees
    the full corpus; only its own slice is materialized). The max of these
    fixes the per-epoch step count every process must dispatch (SPMD
    lockstep: a host short on batches pads zero-mask steps up to it)."""
    lens = np.asarray(sentence_lengths, dtype=np.int64)
    pc = int(process_count)
    per = len(lens) // pc
    return np.array(
        [int(lens[pi : per * pc : pc].sum()) for pi in range(pc)],
        dtype=np.int64,
    )
