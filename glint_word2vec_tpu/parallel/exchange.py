"""Sparse touched-row delta exchange between data-parallel replicas.

ROADMAP item 4 (pod-scale training): the SPMD mesh path ships batch
payloads inside every jitted step, which is right for chips on one ICI
fabric — but across hosts on DCN (or across gang processes on gloo) the
win is to let each replica train privately on its own corpus shard and
reconcile on a cadence. SGNS touches O(batch * (1 + C + n)) rows per
step out of V, so reconciliation that ships whole tables (the classic
dense allreduce) pays O(V * d) per sync regardless of how little a
dispatch group actually trained. Following Ji et al. (arXiv:1604.04661)
and the partitioned-embedding work (arXiv:1909.03359), this module makes
the wire cost proportional to *touched rows* instead:

  * each replica snapshots its tables at group start (a jitted
    device-side copy — the train scans donate the live buffers, so the
    base costs one extra table pair of HBM, halved by bf16 storage);
  * after the dispatch group, a jitted harvest diffs current vs base,
    dedupes touched rows BY CONSTRUCTION (one row = one delta, the
    table-diff restatement of the sorted-run-sum dedupe in
    ``engine._dup_sum_f32``), and compacts their ids into a
    FIXED-CAPACITY padded buffer via the same prefix-sum scatter trick
    as ``ops/device_batching.subsample_compact`` — every traced shape is
    constant, so the whole protocol compiles once and stays
    ``fit_stream``-compatible;
  * replicas allgather a tiny header, then the padded (ids, deltas)
    buffers — ``capacity * (4 + 4d)`` bytes per table instead of
    ``V * d * 4``;
  * every replica reconstructs ``base + delta_0 + delta_1 + ...`` in
    rank order, so all replicas leave the sync with value-identical
    tables, and the sparse schedule reproduces the dense schedule's
    tables exactly (the parity gates in tests/test_exchange.py).

Overflow spill: a group that touches more rows than ``capacity`` raises
the header's overflow flag and THAT round falls back to shipping the
dense per-rank delta (correctness never depends on the capacity guess);
``exchange_overflow_total`` counts the spills so operators can size
capacity from telemetry. ``GLINT_DENSE_EXCHANGE=1`` forces the dense
path outright (the escape hatch and the parity baseline).

Transports: :class:`ProcessTransport` rides
``jax.experimental.multihost_utils.process_allgather`` (gloo on CPU
gangs, DCN on pods); :class:`NullTransport` is the 1-replica degenerate
case; :func:`sync_group` drives N in-process engines through the same
decide/apply helpers (the weak-scaling harness and the parity tests).
"""

from __future__ import annotations

import os
import time
from typing import List, Optional, Sequence

import numpy as np

from glint_word2vec_tpu.utils import faults, next_pow2

#: Wire dtype of delta payloads (accumulation dtype, not storage dtype:
#: deltas of bf16 tables still travel and sum in fp32 so the
#: reconstruction rounds each row total once — same contract as
#: ``engine._bf16_safe_scatter_add``).
_WIRE_DTYPE = np.float32

#: Header layout (int64): [live, done, n0, ovf0, n1, ovf1].
HEADER_LEN = 6


def default_capacity(engine, pair_batch: int, steps_per_call: int) -> int:
    """Capacity heuristic: bound the rows one dispatch group can touch
    — ``steps_per_call * pair_batch`` pairs, each touching one center,
    one context, and ``num_negatives`` noise rows — rounded up to a
    power of two and clamped to the table. Dedup makes the true count
    far smaller on zipfian corpora; overflow spills keep a bad guess
    safe, not wrong. ``GLINT_EXCHANGE_CAPACITY`` overrides."""
    env = os.environ.get("GLINT_EXCHANGE_CAPACITY")
    if env:
        return max(1, min(int(env), engine.num_rows))
    touched = pair_batch * steps_per_call * (2 + engine.num_negatives)
    return min(next_pow2(max(256, touched)), engine.num_rows)


def _build_harvest_fn(engine, capacity: int):
    """Jitted (cur0, cur1, base0, base1) -> per-table
    ``(ids, deltas, n, overflow)`` harvest for one replica. Touched =
    any component of the fp32 delta is nonzero; ids compact into the
    ``capacity`` buffer by prefix-sum scatter (slot ``capacity`` is the
    shared dump slot for overflow/untouched writes)."""
    import jax
    import jax.numpy as jnp

    cap = int(capacity)  # graftlint: ignore[sync-point] host config scalar
    num_rows = engine.num_rows
    dim = engine.dim

    def one(cur, base):
        delta = cur.astype(jnp.float32) - base.astype(jnp.float32)
        rows = jnp.arange(delta.shape[0], dtype=jnp.int32)
        touched = jnp.any(delta != 0.0, axis=1) & (rows < num_rows)
        n = touched.sum().astype(jnp.int32)
        pos = jnp.cumsum(touched.astype(jnp.int32)) - 1
        slot = jnp.where(touched & (pos < cap), pos, cap)
        ids = jnp.zeros(cap + 1, jnp.int32).at[slot].set(rows)[:cap]
        valid = jnp.arange(cap, dtype=jnp.int32) < jnp.minimum(n, cap)
        ids = jnp.where(valid, ids, 0)
        deltas = jnp.where(valid[:, None], delta[ids, :dim], 0.0)
        return ids, deltas, n, n > cap

    def harvest(cur0, cur1, base0, base1):
        return one(cur0, base0), one(cur1, base1)

    return jax.jit(harvest)


def _build_dense_fn(engine):
    """Jitted (cur, base) -> fp32 delta sliced to the real
    (num_rows, dim) extent — the spill/dense-mode payload."""
    import jax
    import jax.numpy as jnp

    num_rows, dim = engine.num_rows, engine.dim

    def dense(cur, base):
        d = cur.astype(jnp.float32) - base.astype(jnp.float32)
        return d[:num_rows, :dim]

    return jax.jit(dense)


def _build_apply_sparse_fn(engine, capacity: int, world: int):
    """Jitted reconstruction ``base + sum_r delta_r`` from R stacked
    sparse payloads, applied rank by rank (ids unique within a rank, so
    every scatter is deterministic and each replica computes the
    identical float sum in the identical order)."""
    import jax
    import jax.numpy as jnp

    dim = engine.dim
    tsh = engine._table_sharding()

    def one(base, ids_r, deltas_r):
        acc = base.astype(jnp.float32)
        for r in range(world):
            upd = jnp.zeros(
                (capacity, base.shape[1]), jnp.float32
            ).at[:, :dim].set(deltas_r[r])
            acc = acc.at[ids_r[r]].add(upd)
        return acc.astype(base.dtype)

    def apply(base0, base1, ids0, d0, ids1, d1):
        return one(base0, ids0, d0), one(base1, ids1, d1)

    return jax.jit(apply, out_shardings=(tsh, tsh))


def _build_snapshot_fn(engine):
    """Jitted device-side table copy for the reconciliation base. A
    bare reference is NOT a snapshot here: the train scans donate the
    table buffers, so the pre-group arrays would be freed by the first
    dispatch. One extra table pair of HBM while an exchange group is in
    flight (bf16 storage halves it)."""
    import jax
    import jax.numpy as jnp

    tsh = engine._table_sharding()

    def snap(a, b):
        return jnp.copy(a), jnp.copy(b)

    return jax.jit(snap, out_shardings=(tsh, tsh))


def _build_apply_dense_fn(engine, world: int):
    """Dense twin of the sparse apply: sequential per-rank full-delta
    adds in rank order — per-row float schedule identical to the sparse
    scatter path (an untouched rank contributes exact +0.0)."""
    import jax
    import jax.numpy as jnp

    num_rows, dim = engine.num_rows, engine.dim
    tsh = engine._table_sharding()

    def one(base, deltas_r):
        acc = base.astype(jnp.float32)
        for r in range(world):
            pad = jnp.zeros(base.shape, jnp.float32)
            pad = pad.at[:num_rows, :dim].set(deltas_r[r])
            acc = acc + pad
        return acc.astype(base.dtype)

    def apply(base0, base1, d0, d1):
        return one(base0, d0), one(base1, d1)

    return jax.jit(apply, out_shardings=(tsh, tsh))


class NullTransport:
    """1-replica transport: allgather returns the local payload alone.
    Keeps the exchange protocol exercisable (and its telemetry live) in
    single-process fits and unit tests."""

    rank = 0
    world = 1

    def allgather(self, arr: np.ndarray) -> np.ndarray:
        return np.asarray(arr)[None]


class ProcessTransport:
    """Cross-process transport over the JAX distributed runtime
    (``distributed.allgather_host``): gloo between CPU gang processes,
    DCN across pod hosts. Every payload shape is fixed by construction,
    so each distinct buffer compiles one collective."""

    def __init__(self):
        import jax

        self.rank = jax.process_index()
        self.world = jax.process_count()

    def allgather(self, arr: np.ndarray) -> np.ndarray:
        from glint_word2vec_tpu.parallel.distributed import (
            allgather_host,
        )

        return allgather_host(arr)


class ReplicaExchanger:
    """Drives the touched-row delta exchange for ONE replica engine.

    Lifecycle: ``begin()`` snapshots the table refs; the fit loop runs
    one dispatch group; ``sync(live=..., done=...)`` harvests, swaps
    deltas with the peer replicas through ``transport``, reconstructs
    the reconciled tables on every replica, and re-snapshots. Returns
    True while any replica still has work (the lockstep loop condition:
    a drained replica keeps calling ``sync(live=False)`` with empty
    payloads until the whole gang reports done, so no collective is
    ever left waiting).
    """

    def __init__(self, engine, *, mode: str = "sparse",
                 capacity: Optional[int] = None,
                 transport=None, pair_batch: int = 1024,
                 steps_per_call: int = 16):
        if mode not in ("sparse", "dense"):
            raise ValueError("exchange mode must be 'sparse' or 'dense'")
        self.engine = engine
        self.transport = transport if transport is not None else NullTransport()
        if os.environ.get("GLINT_DENSE_EXCHANGE", "0") == "1":
            mode = "dense"  # operator escape hatch
        self.mode = mode
        # graftlint: ignore[sync-point] host config scalar
        self.capacity = int(
            capacity if capacity
            else default_capacity(engine, pair_batch, steps_per_call)
        )
        self._fns = {}
        self._base = None
        # Snapshot NOW: the base must predate the first dispatch group,
        # or that group's deltas silently vanish from the exchange.
        self.begin()

    # -- device programs (compiled once per engine/capacity) -----------

    def _fn(self, kind: str, builder, *args):
        key = (kind, *args)
        fn = self._fns.get(key)
        if fn is None:
            fn = self._fns[key] = builder(self.engine, *args)
        return fn

    def begin(self) -> None:
        """Snapshot the reconciliation base: a jitted device-side copy
        of both tables (the train scans donate the live buffers, so a
        reference would dangle after the first dispatch)."""
        fn = self._fn("snapshot", _build_snapshot_fn)
        self._base = fn(self.engine.syn0, self.engine.syn1)

    def harvest(self):
        """Run the jitted touched-row harvest for this replica and
        bring the fixed-capacity buffers to host (the one device->host
        sync of the exchange; the transport needs host arrays).
        Returns ``(header_body, payload)`` where payload is
        ``(ids0, d0, ids1, d1)`` host arrays."""
        fn = self._fn("harvest", _build_harvest_fn, self.capacity)
        (i0, d0, n0, o0), (i1, d1, n1, o1) = fn(
            self.engine.syn0, self.engine.syn1, *self._base
        )
        payload = (
            np.asarray(i0), np.asarray(d0), np.asarray(i1), np.asarray(d1),
        )
        return (
            int(n0), int(np.asarray(o0)), int(n1), int(np.asarray(o1)),
        ), payload

    def _dense_delta(self):
        """Host fp32 per-rank deltas for a dense/spill round — full
        (num_rows, dim) per table. Part of the harvest seam: the dense
        wire payload is by definition a host copy of the table diff."""
        fn = self._fn("dense", _build_dense_fn)
        return (
            np.asarray(fn(self.engine.syn0, self._base[0])),
            np.asarray(fn(self.engine.syn1, self._base[1])),
        )

    def _empty_sparse(self):
        cap, d = self.capacity, self.engine.dim
        return (
            np.zeros(cap, np.int32), np.zeros((cap, d), _WIRE_DTYPE),
            np.zeros(cap, np.int32), np.zeros((cap, d), _WIRE_DTYPE),
        )

    def _empty_dense(self):
        v, d = self.engine.num_rows, self.engine.dim
        z = np.zeros((v, d), _WIRE_DTYPE)
        return z, z

    # -- the protocol ---------------------------------------------------

    def sync(self, *, live: bool = True, done: bool = False) -> bool:
        """One exchange round. ``live``: this replica dispatched a group
        since the last sync (False = empty payload, lockstep filler).
        ``done``: this replica has no further groups this epoch. Returns
        True while ANY replica is not done (keep looping)."""
        eng, tr = self.engine, self.transport
        t0 = time.time()
        header = np.zeros(HEADER_LEN, np.int64)
        header[0], header[1] = int(live), int(done)
        payload = None
        if live:
            (n0, o0, n1, o1), payload = self.harvest()
            header[2:] = (n0, o0, n1, o1)
        faults.fire("exchange.pre_send")
        headers = tr.allgather(header)
        dense_round = decide_dense(self.mode, headers)
        sent = headers.nbytes // max(tr.world, 1)
        touched_ids = None
        if dense_round:
            d0, d1 = (
                self._dense_delta() if live else self._empty_dense()
            )
            deltas0 = tr.allgather(d0)
            deltas1 = tr.allgather(d1)
            sent += d0.nbytes + d1.nbytes
            fn = self._fn(
                "apply_dense", _build_apply_dense_fn, tr.world
            )
            syn0, syn1 = fn(*self._base, deltas0, deltas1)
        else:
            if payload is None:
                payload = self._empty_sparse()
            i0, d0, i1, d1 = payload
            ids0, ds0 = tr.allgather(i0), tr.allgather(d0)
            ids1, ds1 = tr.allgather(i1), tr.allgather(d1)
            sent += i0.nbytes + d0.nbytes + i1.nbytes + d1.nbytes
            fn = self._fn(
                "apply_sparse", _build_apply_sparse_fn,
                self.capacity, tr.world,
            )
            syn0, syn1 = fn(*self._base, ids0, ds0, ids1, ds1)
            touched_ids = np.unique(
                np.concatenate([ids0.ravel(), ids1.ravel()])
            )
        eng.exchange_adopt(syn0, syn1, touched_ids=touched_ids)
        self.begin()
        eng._note_exchange(
            bytes_sent=int(sent),
            rows=int(header[2] + header[4]),
            overflow=bool(header[3] or header[5]),
            dense=bool(dense_round),
            seconds=time.time() - t0,
        )
        return not bool(headers[:, 1].all())


def decide_dense(mode: str, headers: np.ndarray) -> bool:
    """Spill rule shared by the transported and in-process drivers: a
    round is dense when the configured mode says so, the escape hatch
    forces it, or ANY replica overflowed its capacity buffer."""
    if os.environ.get("GLINT_DENSE_EXCHANGE", "0") == "1":
        return True
    return mode == "dense" or bool((headers[:, 3] | headers[:, 5]).any())


def sync_group(exchangers: Sequence[ReplicaExchanger], *,
               live: Optional[List[bool]] = None) -> dict:
    """In-process N-replica exchange round: harvest every replica,
    decide sparse vs dense with the same spill rule, reconstruct every
    replica's tables in the same rank order — the single-process driver
    the weak-scaling harness and the parity tests run replicas through
    (each replica is its own engine; the "wire" is process memory, but
    payload bytes are counted exactly as the transported protocol
    ships them)."""
    world = len(exchangers)
    if live is None:
        live = [True] * world
    headers = np.zeros((world, HEADER_LEN), np.int64)
    payloads = []
    for r, ex in enumerate(exchangers):
        headers[r, 0] = int(live[r])
        if live[r]:
            (n0, o0, n1, o1), p = ex.harvest()
            headers[r, 2:] = (n0, o0, n1, o1)
            payloads.append(p)
        else:
            payloads.append(None)
    faults.fire("exchange.pre_send")
    mode = exchangers[0].mode
    dense_round = decide_dense(mode, headers)
    cap = exchangers[0].capacity
    if dense_round:
        deltas = [
            ex._dense_delta() if live[r] else ex._empty_dense()
            for r, ex in enumerate(exchangers)
        ]
        d0 = np.stack([d[0] for d in deltas])
        d1 = np.stack([d[1] for d in deltas])
        per_rank = d0[0].nbytes + d1[0].nbytes
        args = (d0, d1)
    else:
        ps = [
            p if p is not None else ex._empty_sparse()
            for p, ex in zip(payloads, exchangers)
        ]
        ids0 = np.stack([p[0] for p in ps])
        ds0 = np.stack([p[1] for p in ps])
        ids1 = np.stack([p[2] for p in ps])
        ds1 = np.stack([p[3] for p in ps])
        per_rank = ids0[0].nbytes + ds0[0].nbytes \
            + ids1[0].nbytes + ds1[0].nbytes
        args = (ids0, ds0, ids1, ds1)
    touched_ids = (
        None if dense_round
        else np.unique(np.concatenate([args[0].ravel(), args[2].ravel()]))
    )
    for r, ex in enumerate(exchangers):
        t0 = time.time()
        if dense_round:
            fn = ex._fn("apply_dense", _build_apply_dense_fn, world)
        else:
            fn = ex._fn(
                "apply_sparse", _build_apply_sparse_fn, cap, world
            )
        syn0, syn1 = fn(*ex._base, *args)
        ex.engine.exchange_adopt(syn0, syn1, touched_ids=touched_ids)
        ex.begin()
        ex.engine._note_exchange(
            bytes_sent=int(per_rank + headers[r].nbytes),
            rows=int(headers[r, 2] + headers[r, 4]),
            overflow=bool(headers[r, 3] or headers[r, 5]),
            dense=bool(dense_round),
            seconds=time.time() - t0,
        )
    return {
        "dense": bool(dense_round),
        "bytes_per_rank": int(per_rank),
        "rows": [int(headers[r, 2] + headers[r, 4]) for r in range(world)],
    }
