"""Sparse touched-row delta exchange between data-parallel replicas.

ROADMAP item 4 (pod-scale training): the SPMD mesh path ships batch
payloads inside every jitted step, which is right for chips on one ICI
fabric — but across hosts on DCN (or across gang processes on gloo) the
win is to let each replica train privately on its own corpus shard and
reconcile on a cadence. SGNS touches O(batch * (1 + C + n)) rows per
step out of V, so reconciliation that ships whole tables (the classic
dense allreduce) pays O(V * d) per sync regardless of how little a
dispatch group actually trained. Following Ji et al. (arXiv:1604.04661)
and the partitioned-embedding work (arXiv:1909.03359), this module makes
the wire cost proportional to *touched rows* instead, and (ISSUE 16)
layers three independently-gated optimizations on that wire:

  * each replica snapshots its tables at group start (a jitted
    device-side copy — the train scans donate the live buffers, so the
    base costs one extra table pair of HBM, halved by bf16 storage);
  * after the dispatch group, a jitted diff+encode harvest dedupes
    touched rows BY CONSTRUCTION (one row = one delta, the table-diff
    restatement of the sorted-run-sum dedupe in ``engine._dup_sum_f32``)
    and compacts their ids into a FIXED-CAPACITY padded buffer via the
    same prefix-sum scatter trick as
    ``ops/device_batching.subsample_compact`` — every traced shape is
    constant, so the whole protocol compiles once and stays
    ``fit_stream``-compatible;
  * replicas allgather a tiny header, then the padded payload buffers;
  * every replica reconstructs ``base + delta_0 + delta_1 + ...`` in
    rank order with fp32 accumulation at the landing site (the PR 11
    discipline — bf16 tables round each row total once), so all
    replicas leave the sync with value-identical tables.

The ISSUE 16 wire layers (each with a parity escape hatch):

**Quantized deltas** (``wire="fp32"|"bf16"|"int8"``): bf16 halves the
payload by rounding each delta component once (decoded back to fp32
before accumulation); int8 ships a per-row symmetric maxabs scale plus
1-byte lanes and carries the quantization residual locally in an
error-feedback buffer — the residual folds into the next round's sent
rows, so the per-replica update *stream* stays unbiased even though
individual rounds are lossy. Every replica decodes the identical
``q * scale`` values, so replicas remain value-identical under any wire.
Residual carry is adopted only on rounds that actually shipped the
quantized payload (spill rounds ship exact fp32 deltas and leave the
carry untouched). ``flush()`` ships pending deltas *plus* the carry as
exact fp32 and zeroes the carry — the checkpoint hook that keeps
mid-run resume bitwise for a given (wire, R) config.

**Round coalescing** (``every=R``): ``group_end()`` counts dispatch
groups and runs a wire round only every R-th call — the base snapshot
simply stays put, so R groups of updates accumulate into one diff with
row dedup for free (zipf hot rows repeatedly touched in a window cost
one wire row). Drained replicas keep calling ``group_end(live=False,
done=True)``; every call advances the window, so boundary rounds stay
count-aligned across ranks and the lockstep collective never skews.

**Two-level topology-aware sync** (``topology="twolevel"``): Ji et
al. split the reconciliation across the bandwidth cliff — exact fp32
sparse payloads cross only the fast intra-node hop, node members fold
them into one node-level delta (deduped across the node's touched-row
union), and only node *leaders* ship the quantized node payload over
the slow inter-node hop (non-leaders contribute all-zero buffers whose
scatter adds an exact +0.0). Per-hop byte counters split
intra-node from inter-node traffic; over a flat gloo gang both hops
ride the same wire, so the split is a *model* of pod topology (real
deployments ride ICI for level 1) — documented caveat, see README.

**Adaptive capacity**: headers already carry each rank's true touched
counts, so every rank deterministically tracks the global high-water
mark over a rolling window and shrinks ``capacity`` (with 2x headroom
hysteresis) or grows it after an overflow spill — identical decisions
on identical headers, no extra wire. ``GLINT_EXCHANGE_CAPACITY`` (or an
explicit capacity) pins it.

**world=1 short-circuit**: a single replica reconciling with itself is
a no-op — ``sync`` skips the harvest and the wire entirely and records
``bytes=0`` (the MULTICHIP_BENCH world-1 artifact where sparse
"exceeded" dense). ``GLINT_EXCHANGE_FORCE_WIRE=1`` restores the old
loopback behavior for protocol unit tests.

Overflow spill: a group that touches more rows than ``capacity`` raises
the header's overflow flag and THAT round falls back to shipping the
dense per-rank delta (correctness never depends on the capacity guess);
``exchange_overflow_total`` counts the spills so operators can size
capacity from telemetry. ``GLINT_DENSE_EXCHANGE=1`` forces the dense
path outright (the escape hatch and the parity baseline).

Transports: :class:`ProcessTransport` rides
``jax.experimental.multihost_utils.process_allgather`` (gloo on CPU
gangs, DCN on pods); :class:`NullTransport` is the 1-replica degenerate
case; :func:`sync_group` drives N in-process engines through the same
decide/encode/apply helpers (the weak-scaling harness and the parity
tests).
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import List, Optional, Sequence

import numpy as np

from glint_word2vec_tpu.utils import faults, next_pow2

#: Wire dtype of exact (fp32-wire / dense / flush) delta payloads:
#: accumulation dtype, not storage dtype — deltas of bf16 tables still
#: travel and sum in fp32 so the reconstruction rounds each row total
#: once (same contract as ``engine._bf16_safe_scatter_add``).
_WIRE_DTYPE = np.float32

#: Supported sparse payload encodings (``--exchange-wire``).
WIRE_FORMATS = ("fp32", "bf16", "int8")

#: Header layout (int64): [live, done, n0, ovf0, n1, ovf1].
HEADER_LEN = 6

#: Adaptive capacity: boundary rounds of high-water history required
#: before a shrink is considered, and the smallest capacity adaptation
#: will ever pick (the same floor ``default_capacity`` uses).
CAPACITY_WINDOW = 16
CAPACITY_FLOOR = 256


def _wire_np_dtype(wire: str):
    """Host numpy dtype of the sparse payload lanes for one wire."""
    if wire == "bf16":
        import ml_dtypes  # ships with jax

        return np.dtype(ml_dtypes.bfloat16)
    if wire == "int8":
        return np.dtype(np.int8)
    return np.dtype(_WIRE_DTYPE)


def wire_row_bytes(wire: str, dim: int) -> int:
    """Wire cost of ONE sparse touched row: 4-byte id + payload lanes
    (+ the per-row fp32 scale for int8). The bench surface and the
    README variant matrix quote these."""
    if wire == "bf16":
        return 4 + 2 * dim
    if wire == "int8":
        return 4 + dim + 4
    return 4 + 4 * dim


def default_capacity(engine, pair_batch: int, steps_per_call: int) -> int:
    """Capacity heuristic: bound the rows one dispatch group can touch
    — ``steps_per_call * pair_batch`` pairs, each touching one center,
    one context, and ``num_negatives`` noise rows — rounded up to a
    power of two and clamped to the table. Dedup makes the true count
    far smaller on zipfian corpora; overflow spills keep a bad guess
    safe, not wrong, and the adaptive shrink walks it down toward the
    observed high-water mark. ``GLINT_EXCHANGE_CAPACITY`` overrides
    (and pins — no adaptation)."""
    env = os.environ.get("GLINT_EXCHANGE_CAPACITY")
    if env:
        return max(1, min(int(env), engine.num_rows))
    touched = pair_batch * steps_per_call * (2 + engine.num_negatives)
    return min(next_pow2(max(CAPACITY_FLOOR, touched)), engine.num_rows)


def _build_diff_fn(engine):
    """Jitted (cur, base) -> full-shape fp32 delta. Split out of the
    old monolithic harvest so the flat path, the two-level node
    accumulator, and every wire encoder share one diff program."""
    import jax
    import jax.numpy as jnp

    def diff(cur, base):
        return cur.astype(jnp.float32) - base.astype(jnp.float32)

    return jax.jit(diff)


def _build_encode_fn(engine, capacity: int, wire: str, flush: bool):
    """Jitted (delta, carry) -> ``(ids, payload, scales, n, overflow,
    new_carry, resid_abs)`` sparse encoder for one table.

    Touched = any component of the fp32 delta is nonzero (flush rounds
    also count rows with pending carry); ids compact into the
    ``capacity`` buffer by prefix-sum scatter (slot ``capacity`` is the
    shared dump slot for overflow/untouched writes).

    Wire behaviors:
      * fp32 — exact payload; carry passes through untouched.
      * bf16 — payload rounded to bfloat16 once (decoded to fp32 at the
        landing site); no error feedback (half-ULP of bf16).
      * int8 — error feedback: the pending carry folds into each SENT
        row, the sum quantizes to (int8 q, per-row fp32 maxabs scale),
        and ``new_carry`` holds exactly ``full - q*scale`` for sent
        rows (dump-slot scatter: unsent rows keep their carry, invalid
        slots write zeros to the dump row). The caller adopts
        ``new_carry`` only if the round actually ships this payload.
      * flush=True — exact fp32 payload of delta + carry with
        ``new_carry = 0``: the checkpoint flush that drains the error
        feedback state through the wire.

    ``carry`` has shape ``(num_rows + 1, dim)`` — the extra row is the
    scatter dump slot."""
    import jax
    import jax.numpy as jnp

    cap = int(capacity)  # graftlint: ignore[sync-point] host config scalar
    num_rows = engine.num_rows
    dim = engine.dim

    def encode(delta, carry):
        rows = jnp.arange(delta.shape[0], dtype=jnp.int32)
        if flush:
            eff = delta.at[:num_rows, :dim].add(carry[:num_rows])
            touched = jnp.any(eff != 0.0, axis=1) & (rows < num_rows)
        else:
            eff = delta
            touched = jnp.any(delta != 0.0, axis=1) & (rows < num_rows)
        n = touched.sum().astype(jnp.int32)
        pos = jnp.cumsum(touched.astype(jnp.int32)) - 1
        slot = jnp.where(touched & (pos < cap), pos, cap)
        ids = jnp.zeros(cap + 1, jnp.int32).at[slot].set(rows)[:cap]
        valid = jnp.arange(cap, dtype=jnp.int32) < jnp.minimum(n, cap)
        ids = jnp.where(valid, ids, 0)
        if flush:
            payload = jnp.where(valid[:, None], eff[ids, :dim], 0.0)
            scales = jnp.zeros(cap, jnp.float32)
            new_carry = jnp.zeros_like(carry)
            resid = jnp.float32(0.0)
        elif wire == "int8":
            full = delta[ids, :dim] + carry[ids]
            full = jnp.where(valid[:, None], full, 0.0)
            scale = jnp.max(jnp.abs(full), axis=1) / 127.0
            safe = jnp.where(scale > 0.0, scale, 1.0)
            q = jnp.clip(
                jnp.round(full / safe[:, None]), -127.0, 127.0
            ).astype(jnp.int8)
            deq = q.astype(jnp.float32) * scale[:, None]
            resid_rows = jnp.where(valid[:, None], full - deq, 0.0)
            dump = jnp.where(valid, ids, num_rows)
            new_carry = carry.at[dump].set(resid_rows)
            payload = q
            scales = scale
            resid = jnp.max(jnp.abs(new_carry))
        elif wire == "bf16":
            full = jnp.where(valid[:, None], delta[ids, :dim], 0.0)
            payload = full.astype(jnp.bfloat16)
            scales = jnp.zeros(cap, jnp.float32)
            new_carry = carry
            resid = jnp.max(jnp.abs(carry))
        else:  # fp32
            payload = jnp.where(valid[:, None], delta[ids, :dim], 0.0)
            scales = jnp.zeros(cap, jnp.float32)
            new_carry = carry
            resid = jnp.max(jnp.abs(carry))
        return ids, payload, scales, n, n > cap, new_carry, resid

    return jax.jit(encode)


def _build_dense_fn(engine):
    """Jitted (cur, base) -> fp32 delta sliced to the real
    (num_rows, dim) extent — the spill/dense-mode payload."""
    import jax
    import jax.numpy as jnp

    num_rows, dim = engine.num_rows, engine.dim

    def dense(cur, base):
        d = cur.astype(jnp.float32) - base.astype(jnp.float32)
        return d[:num_rows, :dim]

    return jax.jit(dense)


def _build_dense_carry_fn(engine):
    """Dense payload with the error-feedback carry folded in — the
    flush round's spill form (an exact superset of ``_build_dense_fn``:
    callers pass a zero carry to get the plain dense delta)."""
    import jax
    import jax.numpy as jnp

    num_rows, dim = engine.num_rows, engine.dim

    def dense(cur, base, carry):
        d = cur.astype(jnp.float32) - base.astype(jnp.float32)
        return d[:num_rows, :dim] + carry[:num_rows]

    return jax.jit(dense)


def _build_node_accum_fn(engine, capacity: int, members: tuple):
    """Jitted level-1 fold: scatter-add the exact fp32 sparse payloads
    of this rank's NODE MEMBERS (static tuple) into one dense node
    delta. Every member runs the identical program over the identical
    gathered buffers in the identical rank order, so all members hold
    the identical node delta (and hence the identical level-2 encoding
    and carry) without any extra coordination."""
    import jax
    import jax.numpy as jnp

    num_rows, dim = engine.num_rows, engine.dim

    def accum(ids_r, deltas_r):
        acc = jnp.zeros((num_rows, dim), jnp.float32)
        for r in members:
            acc = acc.at[ids_r[r]].add(deltas_r[r].astype(jnp.float32))
        return acc

    return jax.jit(accum)


def _build_apply_sparse_fn(engine, capacity: int, world: int, wire: str):
    """Jitted reconstruction ``base + sum_r decode(payload_r)`` from R
    stacked sparse payloads, applied rank by rank (ids unique within a
    rank, so every scatter is deterministic and each replica computes
    the identical float sum in the identical order). Decoding happens
    HERE, at the landing site, so accumulation is always fp32 no matter
    the wire (int8 lanes scale by their per-row fp32 maxabs scale; bf16
    lanes widen)."""
    import jax
    import jax.numpy as jnp

    dim = engine.dim
    tsh = engine._table_sharding()

    def one(base, ids_r, payload_r, scales_r):
        acc = base.astype(jnp.float32)
        for r in range(world):
            if wire == "int8":
                dec = payload_r[r].astype(jnp.float32) \
                    * scales_r[r][:, None]
            else:
                dec = payload_r[r].astype(jnp.float32)
            upd = jnp.zeros(
                (capacity, base.shape[1]), jnp.float32
            ).at[:, :dim].set(dec)
            acc = acc.at[ids_r[r]].add(upd)
        return acc.astype(base.dtype)

    def apply(base0, base1, ids0, p0, s0, ids1, p1, s1):
        return one(base0, ids0, p0, s0), one(base1, ids1, p1, s1)

    return jax.jit(apply, out_shardings=(tsh, tsh))


def _build_snapshot_fn(engine):
    """Jitted device-side table copy for the reconciliation base. A
    bare reference is NOT a snapshot here: the train scans donate the
    table buffers, so the pre-group arrays would be freed by the first
    dispatch. One extra table pair of HBM while an exchange group is in
    flight (bf16 storage halves it)."""
    import jax
    import jax.numpy as jnp

    tsh = engine._table_sharding()

    def snap(a, b):
        return jnp.copy(a), jnp.copy(b)

    return jax.jit(snap, out_shardings=(tsh, tsh))


def _build_apply_dense_fn(engine, world: int):
    """Dense twin of the sparse apply: sequential per-rank full-delta
    adds in rank order — per-row float schedule identical to the sparse
    scatter path (an untouched rank contributes exact +0.0)."""
    import jax
    import jax.numpy as jnp

    num_rows, dim = engine.num_rows, engine.dim
    tsh = engine._table_sharding()

    def one(base, deltas_r):
        acc = base.astype(jnp.float32)
        for r in range(world):
            pad = jnp.zeros(base.shape, jnp.float32)
            pad = pad.at[:num_rows, :dim].set(deltas_r[r])
            acc = acc + pad
        return acc.astype(base.dtype)

    def apply(base0, base1, d0, d1):
        return one(base0, d0), one(base1, d1)

    return jax.jit(apply, out_shardings=(tsh, tsh))


class NullTransport:
    """1-replica transport: allgather returns the local payload alone.
    Keeps the exchange protocol exercisable (and its telemetry live) in
    single-process fits and unit tests (with
    ``GLINT_EXCHANGE_FORCE_WIRE=1`` now that world=1 short-circuits)."""

    rank = 0
    world = 1

    def allgather(self, arr: np.ndarray) -> np.ndarray:
        return np.asarray(arr)[None]


class ProcessTransport:
    """Cross-process transport over the JAX distributed runtime
    (``distributed.allgather_host``): gloo between CPU gang processes,
    DCN across pod hosts. Every payload shape is fixed by construction,
    so each distinct buffer compiles one collective. bf16 payloads ride
    the wire as uint16 views — bit-identical lanes, and the collective
    only ever sees dtypes every backend supports."""

    def __init__(self):
        import jax

        self.rank = jax.process_index()
        self.world = jax.process_count()

    def allgather(self, arr: np.ndarray) -> np.ndarray:
        from glint_word2vec_tpu.parallel.distributed import (
            allgather_host,
        )

        bf16 = _wire_np_dtype("bf16")
        if arr.dtype == bf16:
            out = allgather_host(np.ascontiguousarray(arr).view(np.uint16))
            return out.view(bf16)
        return allgather_host(arr)


class ReplicaExchanger:
    """Drives the touched-row delta exchange for ONE replica engine.

    Lifecycle: ``begin()`` snapshots the table refs; the fit loop runs
    one dispatch group and calls ``group_end(live=..., done=...)``,
    which runs a wire round (``sync``) every ``every``-th call —
    harvest, swap encoded deltas with the peer replicas through
    ``transport``, reconstruct the reconciled tables on every replica,
    re-snapshot. Both return True while any replica still has work (the
    lockstep loop condition: a drained replica keeps calling
    ``group_end(live=False, done=True)`` with empty payloads until the
    whole gang reports done, so no collective is ever left waiting).
    ``flush()`` drains the error-feedback carry before a checkpoint;
    ``epoch_reset()`` rearms the window/done latches between epochs.
    """

    def __init__(self, engine, *, mode: str = "sparse",
                 capacity: Optional[int] = None,
                 transport=None, pair_batch: int = 1024,
                 steps_per_call: int = 16, wire: str = "fp32",
                 every: int = 1, topology: str = "flat",
                 node_size: Optional[int] = None):
        if mode not in ("sparse", "dense"):
            raise ValueError("exchange mode must be 'sparse' or 'dense'")
        if wire not in WIRE_FORMATS:
            raise ValueError(
                "exchange wire must be one of %s" % (WIRE_FORMATS,)
            )
        if int(every) < 1:  # graftlint: ignore[sync-point] host config scalar
            raise ValueError("exchange every must be >= 1")
        if topology not in ("flat", "twolevel"):
            raise ValueError("exchange topology must be flat|twolevel")
        self.engine = engine
        self.transport = transport if transport is not None else NullTransport()
        if os.environ.get("GLINT_DENSE_EXCHANGE", "0") == "1":
            mode = "dense"  # operator escape hatch
        self.mode = mode
        # Dense mode always ships exact fp32 full deltas; the wire
        # encoders only shape sparse rounds.
        self.wire = wire if mode == "sparse" else "fp32"
        self.every = int(every)  # graftlint: ignore[sync-point] host config scalar
        self.topology = topology if mode == "sparse" else "flat"
        env_ns = os.environ.get("GLINT_RANKS_PER_NODE")
        ns = int(node_size) if node_size else (int(env_ns) if env_ns else 0)  # graftlint: ignore[sync-point] host config scalar
        #: ranks per node for the two-level topology; 0/None = the whole
        #: gang is one node (single-host default: one leader speaks on
        #: the modeled slow hop).
        self.node_size = ns if ns > 0 else None
        #: capacity is PINNED (no adaptation) when the operator chose it
        #: — explicit param or the env override.
        self.capacity_pinned = bool(capacity) or bool(
            os.environ.get("GLINT_EXCHANGE_CAPACITY")
        )
        # graftlint: ignore[sync-point] host config scalar
        self.capacity = int(
            capacity if capacity
            else default_capacity(engine, pair_batch, steps_per_call)
        )
        self._hw = deque(maxlen=CAPACITY_WINDOW)
        self._fns = {}
        self._base = None
        self._carry = None          # lazy (carry0, carry1) device pair
        self._pending_carry = None  # encoder output awaiting adoption
        self._resid_abs = 0.0
        self._window = 0
        self._live_pending = False
        self._done_pending = False
        self._gang_live = True
        #: world=1 short-circuit (ISSUE 16 satellite): one replica
        #: reconciling with itself is a no-op — skip the wire, report
        #: bytes=0. Env restores the loopback wire for protocol tests.
        self.short_circuit = (
            self.transport.world == 1
            and os.environ.get("GLINT_EXCHANGE_FORCE_WIRE", "0") != "1"
        )
        # Snapshot NOW: the base must predate the first dispatch group,
        # or that group's deltas silently vanish from the exchange.
        # (Kept even under the short-circuit: sync_group() drives
        # NullTransport exchangers through the real protocol.)
        self.begin()

    # -- device programs (compiled once per engine/capacity) -----------

    def _fn(self, kind: str, builder, *args):
        key = (kind, *args)
        fn = self._fns.get(key)
        if fn is None:
            fn = self._fns[key] = builder(self.engine, *args)
        return fn

    def begin(self) -> None:
        """Snapshot the reconciliation base: a jitted device-side copy
        of both tables (the train scans donate the live buffers, so a
        reference would dangle after the first dispatch)."""
        fn = self._fn("snapshot", _build_snapshot_fn)
        self._base = fn(self.engine.syn0, self.engine.syn1)

    def _carry_pair(self):
        """Lazy error-feedback residual state: one fp32 (num_rows+1,
        dim) buffer per table (the +1 row is the scatter dump slot).
        Engine-facing residual telemetry reads it via
        :meth:`residual_stats`."""
        if self._carry is None:
            import jax.numpy as jnp

            shape = (self.engine.num_rows + 1, self.engine.dim)
            self._carry = (
                jnp.zeros(shape, jnp.float32),
                jnp.zeros(shape, jnp.float32),
            )
        return self._carry

    def residual_stats(self) -> dict:
        """Host view of the error-feedback carry magnitude — the
        'residual carry state' the engine exposes on its exchange
        telemetry (set after each encoding round; zero before the first
        int8 round and right after a flush)."""
        return {"residual_abs": float(self._resid_abs)}  # graftlint: ignore[sync-point] host stat

    def _node_members(self, world: int, rank: int) -> tuple:
        """Static node membership for the two-level topology: ranks are
        grouped contiguously ``node_size`` at a time (the gang launcher
        numbers co-located processes contiguously); the node leader is
        the lowest rank in the group."""
        ns = self.node_size or world
        node = rank // ns
        return tuple(r for r in range(world) if r // ns == node)

    # -- harvest (the device->host seam) -------------------------------

    def harvest(self, *, flush: bool = False):
        """Run the jitted diff + wire-encode for this replica and bring
        the fixed-capacity buffers to host (the one device->host sync
        of the exchange; the transport needs host arrays). Returns
        ``(header_body, payload)`` where payload is
        ``(ids0, p0, s0, ids1, p1, s1)`` host arrays (payload lanes in
        the wire dtype, per-row scales for int8).

        Under ``topology="twolevel"`` the LOCAL hop always encodes
        exact fp32 (quantization and error feedback apply to the
        node-level stream at the inter-node hop — see ``sync``);
        ``flush=True`` encodes delta + carry exactly and stages a zero
        carry."""
        diff = self._fn("diff", _build_diff_fn)
        d0 = diff(self.engine.syn0, self._base[0])
        d1 = diff(self.engine.syn1, self._base[1])
        local_wire = self.wire
        if self.topology == "twolevel" and not flush:
            local_wire = "fp32"
        enc = self._fn(
            "encode", _build_encode_fn, self.capacity, local_wire,
            bool(flush),
        )
        c0, c1 = self._carry_pair()
        i0, p0, s0, n0, o0, nc0, r0 = enc(d0, c0)
        i1, p1, s1, n1, o1, nc1, r1 = enc(d1, c1)
        if self.topology != "twolevel" or flush:
            # flat path: the local encoding IS the wire encoding, so
            # its carry/residual are the ones to (maybe) adopt.
            self._pending_carry = (nc0, nc1)
            self._resid_abs = float(
                max(float(np.asarray(r0)), float(np.asarray(r1)))
            )
        payload = (
            np.asarray(i0), np.asarray(p0), np.asarray(s0),
            np.asarray(i1), np.asarray(p1), np.asarray(s1),
        )
        return (
            int(n0), int(np.asarray(o0)), int(n1), int(np.asarray(o1)),
        ), payload

    def _dense_delta(self, *, with_carry: bool = False):
        """Host fp32 per-rank deltas for a dense/spill round — full
        (num_rows, dim) per table. Part of the harvest seam: the dense
        wire payload is by definition a host copy of the table diff.
        ``with_carry`` folds the error-feedback carry in (the flush
        round's spill form)."""
        if with_carry:
            fn = self._fn("dense_carry", _build_dense_carry_fn)
            c0, c1 = self._carry_pair()
            return (
                np.asarray(fn(self.engine.syn0, self._base[0], c0)),
                np.asarray(fn(self.engine.syn1, self._base[1], c1)),
            )
        fn = self._fn("dense", _build_dense_fn)
        return (
            np.asarray(fn(self.engine.syn0, self._base[0])),
            np.asarray(fn(self.engine.syn1, self._base[1])),
        )

    def _empty_sparse(self, wire: Optional[str] = None):
        """All-zero sparse payload in the round's wire dtype (lockstep
        filler): zero ids scatter an exact +0.0 into row 0."""
        if wire is None:
            wire = "fp32" if self.topology == "twolevel" else self.wire
        cap, d = self.capacity, self.engine.dim
        wdt = _wire_np_dtype(wire)
        return (
            np.zeros(cap, np.int32), np.zeros((cap, d), wdt),
            np.zeros(cap, np.float32),
            np.zeros(cap, np.int32), np.zeros((cap, d), wdt),
            np.zeros(cap, np.float32),
        )

    def _empty_dense(self):
        v, d = self.engine.num_rows, self.engine.dim
        z = np.zeros((v, d), _WIRE_DTYPE)
        return z, z

    # -- coalescing / window bookkeeping --------------------------------

    def group_end(self, *, live: bool = True, done: bool = False) -> bool:
        """Account one dispatch group (or one drained-filler slot) and
        run a wire round at every ``every``-th call. Liveness/doneness
        latch across the window; every call advances it, so boundary
        rounds stay count-aligned across ranks no matter who drained
        first. Returns the latest gang-live verdict (True = keep
        looping)."""
        self._window += 1
        self._live_pending = self._live_pending or bool(live)
        self._done_pending = self._done_pending or bool(done)
        if self._window % self.every:
            return self._gang_live
        alive = self.sync(
            live=self._live_pending, done=self._done_pending,
            groups=self.every,
        )
        self._live_pending = False
        self._gang_live = alive
        return alive

    def flush(self) -> bool:
        """Checkpoint hook: drain the error-feedback carry through an
        exact fp32 wire round and zero it, so a resume from the
        checkpoint replays bitwise against the uninterrupted run. The
        go/no-go decision is pure config (int8 wire, multi-replica
        sparse mode) — identical on every rank, so the collective round
        inside never skews. No-op (returns False) otherwise."""
        if (self.short_circuit or self.mode != "sparse"
                or self.wire != "int8"):
            self._window = 0
            return False
        if self.topology == "twolevel":
            # carry is NODE-level state, identical on every member; only
            # the leader may ship it or the flush would add it
            # node_size times. Rank-derived, so still collective-safe.
            members = self._node_members(
                self.transport.world, self.transport.rank
            )
            if self.transport.rank != members[0]:
                self._carry = None
        self.sync(live=True, done=False, flush=True, groups=0)
        self._carry = None
        self._pending_carry = None
        self._resid_abs = 0.0
        self._window = 0
        return True

    def epoch_reset(self) -> None:
        """Rearm the window and the done/live latches after a gang
        drain — each epoch is its own lockstep generation."""
        self._window = 0
        self._live_pending = False
        self._done_pending = False
        self._gang_live = True

    def _adapt_capacity(self, max_n: int, overflowed: bool):
        """Header-driven capacity adaptation (every rank sees the same
        headers, so every rank takes the same decision): grow straight
        past an overflow's true touched count; shrink only after a full
        window of high-water marks sits below half the current
        capacity (2x headroom hysteresis). Returns "grow" | "shrink" |
        None for telemetry."""
        if self.capacity_pinned or self.mode != "sparse":
            return None
        limit = self.engine.num_rows
        if overflowed:
            new = min(next_pow2(max(max_n, CAPACITY_FLOOR)), limit)
            self._hw.clear()
            if new > self.capacity:
                self.capacity = new
                return "grow"
            return None
        self._hw.append(int(max_n))  # graftlint: ignore[sync-point] host header scalar
        if len(self._hw) == CAPACITY_WINDOW:
            target = min(
                max(CAPACITY_FLOOR, next_pow2(2 * max(self._hw))), limit
            )
            if target < self.capacity:
                self.capacity = target
                self._hw.clear()
                return "shrink"
        return None

    # -- the protocol ---------------------------------------------------

    def sync(self, *, live: bool = True, done: bool = False,
             flush: bool = False, groups: int = 1) -> bool:
        """One wire round. ``live``: this replica dispatched >=1 group
        since the last round (False = empty payload, lockstep filler).
        ``done``: this replica has no further groups this epoch.
        ``flush``: exact fp32 round that also drains the error-feedback
        carry (all ranks flush together by config). ``groups``: dispatch
        groups folded into this round (telemetry). Returns True while
        ANY replica is not done (keep looping)."""
        eng, tr = self.engine, self.transport
        if self.short_circuit:
            eng._note_exchange(
                bytes_sent=0, rows=0, overflow=False, dense=False,
                seconds=0.0, wire=self.wire, groups=int(groups),
                flush=False, world1_skip=True, intra_bytes=0,
                capacity=int(self.capacity),
            )
            return not done
        t0 = time.time()
        header = np.zeros(HEADER_LEN, np.int64)
        header[0], header[1] = int(live or flush), int(done)
        payload = None
        if live or flush:
            (n0, o0, n1, o1), payload = self.harvest(flush=flush)
            header[2:] = (n0, o0, n1, o1)
        faults.fire("exchange.pre_send")
        headers = tr.allgather(header)
        dense_round = decide_dense(self.mode, headers)
        sent = headers.nbytes // max(tr.world, 1)
        intra = 0
        wire_round = "fp32" if (dense_round or flush) else self.wire
        touched_ids = None
        cap = self.capacity
        max_n = int(max(headers[:, 2].max(), headers[:, 4].max()))
        if dense_round:
            if flush:
                d0, d1 = self._dense_delta(with_carry=True)
            elif live:
                d0, d1 = self._dense_delta()
            else:
                d0, d1 = self._empty_dense()
            deltas0 = tr.allgather(d0)
            deltas1 = tr.allgather(d1)
            sent += d0.nbytes + d1.nbytes
            fn = self._fn(
                "apply_dense", _build_apply_dense_fn, tr.world
            )
            syn0, syn1 = fn(*self._base, deltas0, deltas1)
            if flush:
                self._carry = None
        elif self.topology == "twolevel" and tr.world > 1 and not flush:
            syn0, syn1, hop = self._twolevel_round(payload, headers)
            sent += hop["intra"] + hop["inter"]
            intra = hop["intra"]
            wire_round = hop["wire"]
            dense_round = hop["dense"]
            touched_ids = hop["touched_ids"]
            max_n = max(max_n, hop["max_n"])
        else:
            if payload is None:
                payload = self._empty_sparse()
            i0, p0, s0, i1, p1, s1 = payload
            ids0, ps0 = tr.allgather(i0), tr.allgather(p0)
            ids1, ps1 = tr.allgather(i1), tr.allgather(p1)
            sent += i0.nbytes + p0.nbytes + i1.nbytes + p1.nbytes
            if wire_round == "int8":
                sc0, sc1 = tr.allgather(s0), tr.allgather(s1)
                sent += s0.nbytes + s1.nbytes
            else:
                sc0 = np.zeros((tr.world, cap), np.float32)
                sc1 = sc0
            fn = self._fn(
                "apply_sparse", _build_apply_sparse_fn, cap, tr.world,
                wire_round,
            )
            syn0, syn1 = fn(
                *self._base, ids0, ps0, sc0, ids1, ps1, sc1
            )
            touched_ids = np.unique(
                np.concatenate([ids0.ravel(), ids1.ravel()])
            )
            if flush:
                self._carry = None
            elif self.wire == "int8" and live:
                self._carry = self._pending_carry
        eng.exchange_adopt(syn0, syn1, touched_ids=touched_ids)
        self.begin()
        cap_event = self._adapt_capacity(
            max_n, bool((headers[:, 3] | headers[:, 5]).any())
        )
        eng._note_exchange(
            bytes_sent=int(sent),
            rows=int(header[2] + header[4]),
            overflow=bool(header[3] or header[5]),
            dense=bool(dense_round),
            seconds=time.time() - t0,
            wire=wire_round,
            groups=int(groups),
            flush=bool(flush),
            world1_skip=False,
            intra_bytes=int(intra),
            capacity=int(self.capacity),
            cap_event=cap_event,
            residual_abs=float(self._resid_abs),
        )
        return not bool(headers[:, 1].all())

    def _twolevel_round(self, payload, headers):
        """Level 1 + level 2 of a two-level sparse round (called from
        the ``sync`` seam; all host/device traffic here is the same
        reconciliation barrier). Exact fp32 local payloads cross the
        intra-node hop; members fold them into the node delta; the
        node delta re-encodes under the configured wire with the NODE
        carry; leaders alone ship it inter-node (non-leaders gather
        zero buffers). Returns the reconciled tables plus per-hop byte
        attribution."""
        tr, cap = self.transport, self.capacity
        if payload is None:
            payload = self._empty_sparse("fp32")
        i0, p0, s0, i1, p1, s1 = payload
        g_i0, g_p0 = tr.allgather(i0), tr.allgather(p0)
        g_i1, g_p1 = tr.allgather(i1), tr.allgather(p1)
        intra = i0.nbytes + p0.nbytes + i1.nbytes + p1.nbytes
        members = self._node_members(tr.world, tr.rank)
        leader = tr.rank == members[0]
        acc = self._fn("node_accum", _build_node_accum_fn, cap, members)
        nd0 = acc(g_i0, g_p0)
        nd1 = acc(g_i1, g_p1)
        enc = self._fn(
            "encode", _build_encode_fn, cap, self.wire, False
        )
        c0, c1 = self._carry_pair()
        ni0, np0, ns0, nn0, no0, nc0, nr0 = enc(nd0, c0)
        ni1, np1, ns1, nn1, no1, nc1, nr1 = enc(nd1, c1)
        h2 = np.zeros(HEADER_LEN, np.int64)
        h2[2:] = (
            int(nn0), int(np.asarray(no0)),
            int(nn1), int(np.asarray(no1)),
        )
        h2s = tr.allgather(h2)
        inter = h2s.nbytes // max(tr.world, 1)
        max_n = int(max(h2s[:, 2].max(), h2s[:, 4].max()))
        if bool((h2s[:, 3] | h2s[:, 5]).any()):
            # node-union spill: leaders ship the dense node delta (an
            # exact fp32 payload), carry stays put for the next round.
            if leader:
                d0, d1 = np.asarray(nd0), np.asarray(nd1)
            else:
                d0, d1 = self._empty_dense()
            deltas0 = tr.allgather(d0)
            deltas1 = tr.allgather(d1)
            inter += (d0.nbytes + d1.nbytes) if leader else 0
            fn = self._fn(
                "apply_dense", _build_apply_dense_fn, tr.world
            )
            syn0, syn1 = fn(*self._base, deltas0, deltas1)
            return syn0, syn1, {
                "intra": int(intra), "inter": int(inter),
                "wire": "fp32", "dense": True, "touched_ids": None,
                "max_n": max_n,
            }
        if leader:
            out = (
                np.asarray(ni0), np.asarray(np0), np.asarray(ns0),
                np.asarray(ni1), np.asarray(np1), np.asarray(ns1),
            )
        else:
            out = self._empty_sparse(self.wire)
        li0, lp0, ls0, li1, lp1, ls1 = out
        ids0, ps0 = tr.allgather(li0), tr.allgather(lp0)
        ids1, ps1 = tr.allgather(li1), tr.allgather(lp1)
        if leader:
            inter += li0.nbytes + lp0.nbytes + li1.nbytes + lp1.nbytes
        if self.wire == "int8":
            sc0, sc1 = tr.allgather(ls0), tr.allgather(ls1)
            if leader:
                inter += ls0.nbytes + ls1.nbytes
        else:
            sc0 = np.zeros((tr.world, cap), np.float32)
            sc1 = sc0
        fn = self._fn(
            "apply_sparse", _build_apply_sparse_fn, cap, tr.world,
            self.wire,
        )
        syn0, syn1 = fn(*self._base, ids0, ps0, sc0, ids1, ps1, sc1)
        if self.wire == "int8":
            self._carry = (nc0, nc1)
            self._resid_abs = float(
                max(float(np.asarray(nr0)), float(np.asarray(nr1)))
            )
        touched_ids = np.unique(
            np.concatenate([ids0.ravel(), ids1.ravel()])
        )
        return syn0, syn1, {
            "intra": int(intra), "inter": int(inter),
            "wire": self.wire, "dense": False,
            "touched_ids": touched_ids, "max_n": max_n,
        }


def decide_dense(mode: str, headers: np.ndarray) -> bool:
    """Spill rule shared by the transported and in-process drivers: a
    round is dense when the configured mode says so, the escape hatch
    forces it, or ANY replica overflowed its capacity buffer."""
    if os.environ.get("GLINT_DENSE_EXCHANGE", "0") == "1":
        return True
    return mode == "dense" or bool((headers[:, 3] | headers[:, 5]).any())


def sync_group(exchangers: Sequence[ReplicaExchanger], *,
               live: Optional[List[bool]] = None,
               flush: bool = False) -> dict:
    """In-process N-replica exchange round: harvest every replica,
    decide sparse vs dense with the same spill rule, reconstruct every
    replica's tables in the same rank order — the single-process driver
    the weak-scaling harness and the parity tests run replicas through
    (each replica is its own engine; the "wire" is process memory, but
    payload bytes are counted exactly as the transported protocol
    ships them). Mirrors ``ReplicaExchanger.sync`` across every wire
    format, the two-level topology (replica list index = rank), flush
    rounds, and the header-driven capacity adaptation."""
    world = len(exchangers)
    ex0 = exchangers[0]
    mode, wire, topo = ex0.mode, ex0.wire, ex0.topology
    cap = ex0.capacity
    if live is None:
        live = [True] * world
    t0 = time.time()
    headers = np.zeros((world, HEADER_LEN), np.int64)
    payloads = []
    for r, ex in enumerate(exchangers):
        headers[r, 0] = int(live[r] or flush)
        if live[r] or flush:
            (n0, o0, n1, o1), p = ex.harvest(flush=flush)
            headers[r, 2:] = (n0, o0, n1, o1)
            payloads.append(p)
        else:
            payloads.append(None)
    faults.fire("exchange.pre_send")
    dense_round = decide_dense(mode, headers)
    wire_round = "fp32" if (dense_round or flush) else wire
    max_n = int(max(headers[:, 2].max(), headers[:, 4].max()))
    hdr_bytes = headers[0].nbytes
    intra_by_rank = [0] * world
    inter_by_rank = [0] * world
    touched_ids = None
    if dense_round:
        deltas = [
            ex._dense_delta(with_carry=flush) if (live[r] or flush)
            else ex._empty_dense()
            for r, ex in enumerate(exchangers)
        ]
        d0 = np.stack([d[0] for d in deltas])
        d1 = np.stack([d[1] for d in deltas])
        for r in range(world):
            inter_by_rank[r] = hdr_bytes + d0[r].nbytes + d1[r].nbytes
        apply_args = [("apply_dense", (_build_apply_dense_fn, world),
                       (d0, d1))]
    elif topo == "twolevel" and world > 1:
        # level 1 (intra hop): exact fp32 local payloads.
        ps = [
            p if p is not None else ex._empty_sparse("fp32")
            for p, ex in zip(payloads, exchangers)
        ]
        ids0 = np.stack([p[0] for p in ps])
        ps0 = np.stack([p[1] for p in ps])
        ids1 = np.stack([p[3] for p in ps])
        ps1 = np.stack([p[4] for p in ps])
        l1 = ids0[0].nbytes + ps0[0].nbytes \
            + ids1[0].nbytes + ps1[0].nbytes
        for r in range(world):
            intra_by_rank[r] = l1
        # level 2: fold + re-encode once per node (every member would
        # compute the identical result; the leader's engine does it).
        h2 = np.zeros((world, HEADER_LEN), np.int64)
        node_enc = {}   # leader rank -> host sparse payload
        node_nd = {}    # leader rank -> device node deltas (for spill)
        node_carry = {}  # leader rank -> (nc0, nc1, resid_abs)
        for r, ex in enumerate(exchangers):
            members = ex._node_members(world, r)
            if r != members[0]:
                continue
            acc = ex._fn("node_accum", _build_node_accum_fn, cap, members)
            nd0, nd1 = acc(ids0, ps0), acc(ids1, ps1)
            enc = ex._fn("encode", _build_encode_fn, cap, wire, False)
            c0, c1 = ex._carry_pair()
            ni0, q0, sc0, nn0, no0, nc0, nr0 = enc(nd0, c0)
            ni1, q1, sc1, nn1, no1, nc1, nr1 = enc(nd1, c1)
            row = (
                int(nn0), int(np.asarray(no0)),
                int(nn1), int(np.asarray(no1)),
            )
            for m in members:
                h2[m, 2:] = row
            node_enc[r] = (
                np.asarray(ni0), np.asarray(q0), np.asarray(sc0),
                np.asarray(ni1), np.asarray(q1), np.asarray(sc1),
            )
            node_nd[r] = (nd0, nd1)
            node_carry[r] = (
                nc0, nc1,
                max(float(np.asarray(nr0)), float(np.asarray(nr1))),
            )
        max_n = max(max_n, int(max(h2[:, 2].max(), h2[:, 4].max())))
        if bool((h2[:, 3] | h2[:, 5]).any()):
            # node-union spill: leaders ship dense node deltas.
            dense_round = True
            wire_round = "fp32"
            rows0, rows1 = [], []
            for r, ex in enumerate(exchangers):
                members = ex._node_members(world, r)
                if r == members[0]:
                    nd0, nd1 = node_nd[r]
                    a, b = np.asarray(nd0), np.asarray(nd1)
                    inter_by_rank[r] = hdr_bytes + a.nbytes + b.nbytes
                else:
                    a, b = ex._empty_dense()
                    inter_by_rank[r] = hdr_bytes
                rows0.append(a)
                rows1.append(b)
            apply_args = [("apply_dense", (_build_apply_dense_fn, world),
                           (np.stack(rows0), np.stack(rows1)))]
        else:
            outs = []
            for r, ex in enumerate(exchangers):
                members = ex._node_members(world, r)
                if r == members[0]:
                    out = node_enc[r]
                    inter_by_rank[r] = hdr_bytes + out[0].nbytes \
                        + out[1].nbytes + out[3].nbytes + out[4].nbytes
                    if wire == "int8":
                        inter_by_rank[r] += out[2].nbytes + out[5].nbytes
                else:
                    out = ex._empty_sparse(wire)
                    inter_by_rank[r] = hdr_bytes
                outs.append(out)
            gi0 = np.stack([o[0] for o in outs])
            gq0 = np.stack([o[1] for o in outs])
            gs0 = np.stack([o[2] for o in outs])
            gi1 = np.stack([o[3] for o in outs])
            gq1 = np.stack([o[4] for o in outs])
            gs1 = np.stack([o[5] for o in outs])
            touched_ids = np.unique(
                np.concatenate([gi0.ravel(), gi1.ravel()])
            )
            apply_args = [("apply_sparse",
                           (_build_apply_sparse_fn, cap, world, wire),
                           (gi0, gq0, gs0, gi1, gq1, gs1))]
            for r, ex in enumerate(exchangers):
                if wire == "int8":
                    leader = ex._node_members(world, r)[0]
                    nc0, nc1, resid = node_carry[leader]
                    ex._carry = (nc0, nc1)
                    ex._resid_abs = resid
    else:
        ps = [
            p if p is not None else ex._empty_sparse(wire_round)
            for p, ex in zip(payloads, exchangers)
        ]
        ids0 = np.stack([p[0] for p in ps])
        q0 = np.stack([p[1] for p in ps])
        sc0 = np.stack([p[2] for p in ps])
        ids1 = np.stack([p[3] for p in ps])
        q1 = np.stack([p[4] for p in ps])
        sc1 = np.stack([p[5] for p in ps])
        per = ids0[0].nbytes + q0[0].nbytes + ids1[0].nbytes + q1[0].nbytes
        if wire_round == "int8":
            per += sc0[0].nbytes + sc1[0].nbytes
        for r in range(world):
            inter_by_rank[r] = hdr_bytes + per
        touched_ids = np.unique(
            np.concatenate([ids0.ravel(), ids1.ravel()])
        )
        apply_args = [("apply_sparse",
                       (_build_apply_sparse_fn, cap, world, wire_round),
                       (ids0, q0, sc0, ids1, q1, sc1))]
        for r, ex in enumerate(exchangers):
            if flush:
                ex._carry = None
            elif wire == "int8" and live[r]:
                ex._carry = ex._pending_carry
    kind, builder_args, args = apply_args[0]
    overflowed = bool((headers[:, 3] | headers[:, 5]).any())
    cap_event = None
    for r, ex in enumerate(exchangers):
        t1 = time.time()
        fn = ex._fn(kind, *builder_args)
        syn0, syn1 = fn(*ex._base, *args)
        ex.engine.exchange_adopt(syn0, syn1, touched_ids=touched_ids)
        ex.begin()
        cap_event = ex._adapt_capacity(max_n, overflowed)
        ex.engine._note_exchange(
            bytes_sent=int(intra_by_rank[r] + inter_by_rank[r]),
            rows=int(headers[r, 2] + headers[r, 4]),
            overflow=bool(headers[r, 3] or headers[r, 5]),
            dense=bool(dense_round),
            seconds=time.time() - t1,
            wire=wire_round,
            groups=1,
            flush=bool(flush),
            world1_skip=False,
            intra_bytes=int(intra_by_rank[r]),
            capacity=int(ex.capacity),
            cap_event=cap_event,
            residual_abs=float(ex._resid_abs),
        )
    return {
        "dense": bool(dense_round),
        "bytes_per_rank": int(
            sum(intra_by_rank[r] + inter_by_rank[r]
                for r in range(world)) // world
        ),
        "intra_bytes_per_rank": int(sum(intra_by_rank) // world),
        "inter_bytes_per_rank": int(sum(inter_by_rank) // world),
        "wire": wire_round,
        "capacity": int(exchangers[0].capacity),
        "cap_event": cap_event,
        "seconds": time.time() - t0,
        "rows": [int(headers[r, 2] + headers[r, 4]) for r in range(world)],
    }


def flush_group(exchangers: Sequence[ReplicaExchanger]) -> bool:
    """In-process twin of ``ReplicaExchanger.flush``: drain every
    replica's error-feedback carry through one exact fp32 round (the
    pre-checkpoint hook in tests and the weak-scaling harness). No-op
    unless the config actually accumulates a carry (int8 sparse)."""
    ex0 = exchangers[0]
    if ex0.mode != "sparse" or ex0.wire != "int8":
        for ex in exchangers:
            ex._window = 0
        return False
    world = len(exchangers)
    if ex0.topology == "twolevel":
        for r, ex in enumerate(exchangers):
            if r != ex._node_members(world, r)[0]:
                ex._carry = None  # node carry ships once, via the leader
    sync_group(exchangers, flush=True)
    for ex in exchangers:
        ex._carry = None
        ex._pending_carry = None
        ex._resid_abs = 0.0
        ex._window = 0
    return True
