"""Elastic training supervisor: gang launch, liveness, teardown, resume.

The reference gets its fault model for free from Akka — the Glint master
supervises server/worker actors, and a died actor is restarted by its
supervisor while pull/push round-trips retry under timeouts
(SURVEY.md §2.2). Our multi-process fits had the opposite property: SPMD
lockstep means ONE dead or wedged worker parks every surviving process in
a collective forever, and PR 5's crash-safe checkpoints only helped if an
operator noticed and relaunched by hand. This module is the active half:

  * launches the N-process gang for a distributed fit (fresh coordinator
    port per generation — a half-dead coordinator must never be rejoined);
  * watches liveness two ways: ``waitpid`` (crash — any worker exiting
    nonzero or on a signal) and the PR 3 ``--status-file`` heartbeat
    snapshots (hang — a status file of the current generation whose
    mtime goes stale while its process still runs);
  * on any failure tears the WHOLE gang down (SIGTERM, grace, SIGKILL —
    survivors are wedged in collectives and cannot make progress),
    re-resolves the last committed checkpoint through the integrity
    verifier (``utils.integrity.resolve_train_state`` — corrupt newest
    snapshot falls back to the kept previous one), and relaunches with
    capped exponential backoff under a max-restarts budget;
  * hands back a :class:`SupervisorReport` with restart counts and
    per-restart recovery latencies — the numbers ``scripts/chaos_drill.py``
    records into FAULT_BENCH.json.

Generation handshake: each launch exports ``GLINT_SUPERVISOR_GEN``; the
worker's heartbeat snapshot echoes it back as ``supervisor_generation``
(obs/heartbeat.py), so the supervisor never mistakes a pre-restart
status file for a live heartbeat of the current gang.

Single-process "gangs" (num_workers=1) are the degenerate case and fully
supported: the supervisor is then a restart-with-resume wrapper around
one fit.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import socket
import subprocess
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from glint_word2vec_tpu.utils import atomic_write_json

logger = logging.getLogger(__name__)

#: build_argv(rank, num_workers, coordinator_port, status_file,
#: generation) -> argv list for one worker process.
BuildArgv = Callable[[int, int, int, str, int], List[str]]


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def capped_backoff(restarts: int, base: float, cap: float) -> float:
    """Capped exponential restart backoff: ``base * 2**restarts``,
    never past ``cap``. Shared by the training-gang supervisor and the
    serving-fleet supervisor (``fleet.FleetSupervisor``), so both tiers
    pace their relaunches the same way."""
    return min(float(base) * (2 ** int(restarts)), float(cap))


def signal_process_group(proc: subprocess.Popen, sig) -> None:
    """Signal a child's whole process group (catching any
    grandchildren), falling back to the process itself when the group
    is gone or was never created."""
    try:
        os.killpg(os.getpgid(proc.pid), sig)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            proc.send_signal(sig)
        except (ProcessLookupError, OSError):
            pass


def terminate_process(proc: subprocess.Popen,
                      grace_seconds: float = 5.0) -> None:
    """SIGTERM a child's process group, wait out the grace window,
    SIGKILL whatever survives, and reap it. The single-process cousin
    of the gang teardown — the serving-fleet supervisor uses it to put
    down one hung replica without touching its siblings."""
    if proc.poll() is not None:
        return
    signal_process_group(proc, signal.SIGTERM)
    deadline = time.time() + max(0.0, grace_seconds)
    while time.time() < deadline and proc.poll() is None:
        time.sleep(0.05)
    if proc.poll() is None:
        signal_process_group(proc, signal.SIGKILL)
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:  # pragma: no cover
        logger.error("pid %d survived SIGKILL", proc.pid)


def cli_train_build_argv(train_rest: List[str]) -> BuildArgv:
    """:data:`BuildArgv` for workers running ``python -m
    glint_word2vec_tpu.cli train <train_rest>`` — the ONE place the
    worker launch contract (per-rank status file, distributed flags for
    gangs > 1) is encoded, shared by the CLI ``supervise`` subcommand
    and ``scripts/chaos_drill.py``."""
    import sys

    def build_argv(rank, n, port, status_file, generation):
        status_dir = os.path.dirname(status_file)
        argv = [
            sys.executable, "-m", "glint_word2vec_tpu.cli", "train",
            *train_rest, "--status-file", status_file,
            # Crash flight recorder (ISSUE 8): every worker mirrors its
            # event ring to a per-rank JSONL (flushed on the status
            # cadence) and dumps its step-time ledger at run end, so
            # the supervisor can collect a postmortem bundle even for
            # a SIGKILLed or wedged rank. Appended AFTER the operator's
            # train args, so these supervisor-owned paths win argparse's
            # last-value-wins if the operator also set them.
            "--event-log",
            os.path.join(status_dir, f"events-{rank}.jsonl"),
            "--steptime-out",
            os.path.join(status_dir, f"steptime-{rank}.json"),
        ]
        if n > 1:
            argv += [
                "--coordinator", f"127.0.0.1:{port}",
                "--num-processes", str(n), "--process-id", str(rank),
            ]
        return argv

    return build_argv


def cli_transform_build_argv(transform_rest: List[str]) -> BuildArgv:
    """:data:`BuildArgv` for ranks running ``python -m
    glint_word2vec_tpu.cli transform-file <transform_rest>`` — the
    bulk-embedding analogue of :func:`cli_train_build_argv` (ISSUE 17).
    Ranks are embarrassingly parallel: each derives its contiguous
    input span from ``--rank``/``--world``
    (:func:`parallel.distributed.shard_span`) and writes a private
    ``rank-NNNN/`` shard directory, so no coordinator flags are
    appended — a relaunched rank resumes from its own committed shards,
    independent of the others. Supervisor-owned flags come AFTER the
    operator's args so they win argparse's last-value-wins."""
    import sys

    def build_argv(rank, n, port, status_file, generation):
        status_dir = os.path.dirname(status_file)
        return [
            sys.executable, "-m", "glint_word2vec_tpu.cli",
            "transform-file", *transform_rest,
            "--status-file", status_file,
            "--metrics-out",
            os.path.join(status_dir, f"transform-{rank}.json"),
            "--rank", str(rank), "--world", str(n),
        ]

    return build_argv


@dataclass
class RestartRecord:
    generation: int  # the generation that FAILED
    reason: str
    resumed_from: Optional[str]  # verified checkpoint name, None = fresh
    backoff_seconds: float
    detect_to_relaunch_seconds: float
    #: Detection -> first heartbeat snapshot of the NEW generation (the
    #: honest recovery latency: includes backoff, jax bring-up, vocab
    #: rebuild, checkpoint restore). None when no heartbeat arrived
    #: before the run ended (very short tails).
    detect_to_heartbeat_seconds: Optional[float] = None
    #: Crash-flight-recorder bundles collected from the FAILED
    #: generation (postmortem-<gen>-<rank>/ under status_dir): each
    #: holds that rank's last heartbeat snapshot, event-ring JSONL,
    #: step-time ledger, and worker-log tail.
    postmortem: List[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "generation": self.generation,
            "reason": self.reason,
            "resumed_from": self.resumed_from,
            "backoff_seconds": round(self.backoff_seconds, 3),
            "detect_to_relaunch_seconds": round(
                self.detect_to_relaunch_seconds, 3
            ),
            "detect_to_heartbeat_seconds": (
                round(self.detect_to_heartbeat_seconds, 3)
                if self.detect_to_heartbeat_seconds is not None else None
            ),
            "postmortem": list(self.postmortem),
        }


@dataclass
class SupervisorReport:
    completed: bool = False
    restarts: int = 0
    generations: int = 0
    gave_up_reason: Optional[str] = None
    wall_seconds: float = 0.0
    restart_records: List[RestartRecord] = field(default_factory=list)
    #: EVERY flight-recorder bundle this run collected (restart AND
    #: give-up teardowns), newest last — the one list an operator (or
    #: scripts/chaos_drill.py) walks for post-incident forensics.
    postmortem_bundles: List[str] = field(default_factory=list)
    #: Bound port of the merged gang /metrics endpoint (None = off).
    metrics_port: Optional[int] = None

    def to_dict(self) -> dict:
        return {
            "completed": self.completed,
            "restarts": self.restarts,
            "generations": self.generations,
            "gave_up_reason": self.gave_up_reason,
            "wall_seconds": round(self.wall_seconds, 2),
            "restart_records": [r.to_dict() for r in self.restart_records],
            "postmortem_bundles": list(self.postmortem_bundles),
            "metrics_port": self.metrics_port,
        }


class Supervisor:
    """Supervise one N-worker training gang to completion.

    Parameters
    ----------
    build_argv:
        Callable producing each worker's argv (see :data:`BuildArgv`).
        The CLI ``supervise`` subcommand builds these from the raw
        ``train`` arguments; tests pass tiny stub scripts.
    num_workers:
        Gang size. 1 supervises a plain single-process fit.
    status_dir:
        Directory for per-rank status files (``status-<rank>.json``) and
        worker logs (``worker-<rank>.log``, appended across generations).
    checkpoint_dir:
        The fit's checkpoint directory; consulted between generations to
        log (and integrity-verify) what the relaunch will resume from.
        None skips re-resolution (the workers still resume themselves).
    env:
        Extra environment for every launch of every rank.
    rank_env_first_launch:
        Extra environment per rank applied ONLY to generation 0 — the
        chaos-drill seam: a ``GLINT_FAULTS`` kill schedule armed here
        fires once and is NOT re-armed on the relaunch (re-arming would
        kill every generation and burn the whole restart budget).
    heartbeat_stale_seconds:
        A current-generation status file older than this while its
        process lives is a hang. None disables hang detection (crash
        detection alone).
    startup_grace_seconds:
        How long a worker may run without producing its first
        current-generation heartbeat before that too is a hang
        (compilation can take minutes on cold starts — keep generous).
    metrics_port:
        Bind the merged gang observability endpoint here (0 =
        ephemeral; the bound port is on ``self.metrics_port``): one
        ``/metrics`` (JSON + Prometheus) + ``/healthz`` for the whole
        gang, fed from the per-rank status files each liveness sweep,
        generation-stamped. None (default) disables.
    serving_urls:
        Serving-replica JSON ``/metrics`` URLs to join into the merged
        exposition (scraped lazily per request, replica failures
        reported not fatal).
    """

    def __init__(
        self,
        build_argv: BuildArgv,
        num_workers: int,
        *,
        status_dir: str,
        checkpoint_dir: Optional[str] = None,
        env: Optional[Dict[str, str]] = None,
        rank_env_first_launch: Optional[Dict[int, Dict[str, str]]] = None,
        heartbeat_stale_seconds: Optional[float] = 120.0,
        startup_grace_seconds: float = 600.0,
        poll_interval: float = 0.25,
        max_restarts: int = 3,
        backoff_base_seconds: float = 1.0,
        backoff_cap_seconds: float = 30.0,
        kill_grace_seconds: float = 5.0,
        metrics_port: Optional[int] = None,
        metrics_host: str = "127.0.0.1",
        serving_urls: Optional[List[str]] = None,
    ):
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        self.build_argv = build_argv
        self.num_workers = int(num_workers)
        self.status_dir = status_dir
        self.checkpoint_dir = checkpoint_dir
        self.env = dict(env or {})
        self.rank_env_first_launch = dict(rank_env_first_launch or {})
        self.heartbeat_stale_seconds = heartbeat_stale_seconds
        self.startup_grace_seconds = float(startup_grace_seconds)
        self.poll_interval = float(poll_interval)
        self.max_restarts = int(max_restarts)
        self.backoff_base_seconds = float(backoff_base_seconds)
        self.backoff_cap_seconds = float(backoff_cap_seconds)
        self.kill_grace_seconds = float(kill_grace_seconds)
        self._procs: List[Optional[subprocess.Popen]] = []
        self._logs: List = []
        #: Per-generation gang trace id, minted at each launch and
        #: exported to every rank as ``GLINT_TRACE_ID``. The workers'
        #: EventRecorders stamp it into their clock-anchor lines, so
        #: ``cli trace-merge`` can tie one generation's rank rings (and
        #: the exchange-round spans inside them) to one gang-wide id;
        #: postmortem bundles carry it in meta.json.
        self._gen_trace_id: Optional[str] = None
        #: Merged gang observability endpoint (ISSUE 8). Bound in the
        #: constructor so callers know the port before run() blocks.
        self.gang_server = None
        self.metrics_port: Optional[int] = None
        if metrics_port is not None:
            from glint_word2vec_tpu.obs.aggregate import GangStatusServer

            self.gang_server = GangStatusServer(
                host=metrics_host, port=metrics_port,
                num_workers=self.num_workers, serving_urls=serving_urls,
            )
            self.gang_server.start()
            self.metrics_port = self.gang_server.port
            logger.info(
                "supervisor: merged gang metrics on http://%s:%d "
                "(/healthz, /metrics)",
                self.gang_server.host, self.gang_server.port,
            )

    # -- per-generation plumbing ----------------------------------------

    def _status_file(self, rank: int) -> str:
        return os.path.join(self.status_dir, f"status-{rank}.json")

    def _read_status(self, rank: int, generation: int) -> Optional[dict]:
        """The rank's status snapshot, or None if absent/unparseable/
        from a previous generation (the handshake: a stale pre-restart
        file must never count as a live heartbeat)."""
        try:
            with open(self._status_file(rank)) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            return None
        gen = snap.get("supervisor_generation")
        if gen is not None and int(gen) != generation:
            return None
        return snap

    def _launch(self, generation: int) -> None:
        from glint_word2vec_tpu.obs import events as obs_events

        os.makedirs(self.status_dir, exist_ok=True)
        port = free_port()
        # One trace id per generation, shared by every rank: the gang
        # analogue of the balancer-minted request id. A restart mints a
        # fresh id, so cross-generation events never stitch together.
        self._gen_trace_id = obs_events.mint_trace_id()
        self._procs, self._logs = [], []
        for rank in range(self.num_workers):
            sf = self._status_file(rank)
            try:
                os.remove(sf)
            except OSError:
                pass
            env = dict(os.environ)
            env.update(self.env)
            env["GLINT_SUPERVISOR"] = "1"
            env["GLINT_SUPERVISOR_GEN"] = str(generation)
            env["GLINT_TRACE_ID"] = self._gen_trace_id or ""
            if generation == 0:
                env.update(self.rank_env_first_launch.get(rank, {}))
            argv = self.build_argv(
                rank, self.num_workers, port, sf, generation
            )
            log = open(
                os.path.join(self.status_dir, f"worker-{rank}.log"), "ab"
            )
            log.write(
                f"\n===== generation {generation} rank {rank}: "
                f"{' '.join(argv)} =====\n".encode()
            )
            log.flush()
            self._logs.append(log)
            # Own session per worker: the gang teardown kills the whole
            # process group, catching any grandchildren, and an operator
            # Ctrl-C on the supervisor doesn't race the workers.
            self._procs.append(
                subprocess.Popen(
                    argv, env=env, stdout=log, stderr=subprocess.STDOUT,
                    start_new_session=True,
                )
            )
        logger.info(
            "supervisor: generation %d launched (%d workers, "
            "coordinator port %d)", generation, self.num_workers, port,
        )

    def _kill_gang(self) -> None:
        """SIGTERM every live worker's process group, grace, SIGKILL.
        Survivors of a partial failure are wedged in collectives — they
        cannot checkpoint or exit cleanly, so the teardown must not
        wait on their goodwill."""
        live = [p for p in self._procs if p is not None and p.poll() is None]
        for p in live:
            self._signal(p, signal.SIGTERM)
        deadline = time.time() + self.kill_grace_seconds
        while time.time() < deadline and any(
            p.poll() is None for p in live
        ):
            time.sleep(0.05)
        for p in live:
            if p.poll() is None:
                self._signal(p, signal.SIGKILL)
        for p in live:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:  # pragma: no cover
                logger.error(
                    "supervisor: worker pid %d survived SIGKILL", p.pid
                )
        for log in self._logs:
            try:
                log.close()
            except OSError:
                pass
        self._logs = []

    @staticmethod
    def _signal(proc: subprocess.Popen, sig) -> None:
        signal_process_group(proc, sig)

    # -- failure detection ----------------------------------------------

    def _check_failure(
        self, generation: int, launched_at: float
    ) -> Optional[str]:
        """One poll round: returns a failure reason, or None while the
        generation is healthy (or already fully done — the caller checks
        completion first)."""
        now = time.time()
        for rank, p in enumerate(self._procs):
            rc = p.poll()
            if rc is not None and rc != 0:
                if rc < 0:
                    try:
                        name = signal.Signals(-rc).name
                    except ValueError:
                        name = str(-rc)
                    return f"worker {rank} killed by signal {name}"
                return f"worker {rank} exited with code {rc}"
        if self.heartbeat_stale_seconds is None:
            return None
        for rank, p in enumerate(self._procs):
            if p.poll() == 0:
                continue  # finished cleanly; its file legitimately ages
            snap = self._read_status(rank, generation)
            if snap is None:
                if now - launched_at > self.startup_grace_seconds:
                    return (
                        f"worker {rank} produced no generation-"
                        f"{generation} heartbeat within "
                        f"{self.startup_grace_seconds:.0f}s"
                    )
                continue
            age = now - os.path.getmtime(self._status_file(rank))
            if age > self.heartbeat_stale_seconds:
                return (
                    f"worker {rank} heartbeat stale for {age:.1f}s "
                    f"(threshold {self.heartbeat_stale_seconds:.0f}s)"
                )
        return None

    # -- crash flight recorder ------------------------------------------

    #: Worker-log tail bytes copied into each postmortem bundle.
    POSTMORTEM_LOG_TAIL = 65536

    def _collect_postmortem(self, generation: int, reason: str) -> List[str]:
        """Flush each rank's on-disk observability remains into a
        ``postmortem-<gen>-<rank>/`` bundle after a gang teardown: the
        last heartbeat snapshot (``heartbeat.json``), the event-ring
        JSONL the worker mirrored (``events.jsonl``), the step-time
        ledger when the rank got far enough to dump one
        (``steptime.json``), the worker-log tail (``log_tail.txt``),
        and a ``meta.json`` naming the generation/rank/reason. A
        SIGKILLed rank cannot flush anything itself — these files are
        exactly why the launch contract writes them continuously.
        Collection is best-effort and must never block a restart."""
        import shutil

        bundles = []
        for rank in range(self.num_workers):
            sources = [
                (self._status_file(rank), "heartbeat.json"),
                (os.path.join(self.status_dir, f"events-{rank}.jsonl"),
                 "events.jsonl"),
                (os.path.join(self.status_dir, f"steptime-{rank}.json"),
                 "steptime.json"),
            ]
            if not any(os.path.exists(src) for src, _ in sources):
                continue  # rank died before producing anything
            bundle = os.path.join(
                self.status_dir, f"postmortem-{generation}-{rank}"
            )
            try:
                os.makedirs(bundle, exist_ok=True)
                for src, dst in sources:
                    if os.path.exists(src):
                        shutil.copyfile(src, os.path.join(bundle, dst))
                log_path = os.path.join(
                    self.status_dir, f"worker-{rank}.log"
                )
                if os.path.exists(log_path):
                    with open(log_path, "rb") as f:
                        f.seek(0, os.SEEK_END)
                        f.seek(max(0, f.tell() - self.POSTMORTEM_LOG_TAIL))
                        tail = f.read()
                    # Temp + replace: the bundle is what an operator (or
                    # the chaos drill) reads after a crash — a torn tail
                    # file would point the postmortem at a lie.
                    tail_path = os.path.join(bundle, "log_tail.txt")
                    tmp = f"{tail_path}.tmp.{os.getpid()}"
                    with open(tmp, "wb") as f:
                        f.write(tail)
                    os.replace(tmp, tail_path)
                atomic_write_json(os.path.join(bundle, "meta.json"), {
                    "generation": generation,
                    "rank": rank,
                    "reason": reason,
                    "trace": self._gen_trace_id,
                    "collected_at": time.time(),
                })
            except OSError as e:
                logger.warning(
                    "supervisor: postmortem collection for rank %d "
                    "failed: %s", rank, e,
                )
                continue
            bundles.append(bundle)
        if bundles:
            logger.error(
                "supervisor: flight-recorder bundles collected: %s",
                ", ".join(bundles),
            )
        return bundles

    def _update_gang_status(self, generation: int) -> None:
        """Feed the merged-metrics server this sweep's per-rank view."""
        if self.gang_server is None:
            return
        self.gang_server.update(generation, {
            rank: self._read_status(rank, generation)
            for rank in range(self.num_workers)
        })

    def _resolve_checkpoint(self) -> Optional[str]:
        """Integrity-verified name of the snapshot the relaunch will
        resume from (None = fresh start). Raises
        ``CheckpointCorruptError`` when a state file exists but nothing
        verifies — restarting would silently retrain from scratch."""
        if not self.checkpoint_dir:
            return None
        from glint_word2vec_tpu.utils.integrity import resolve_train_state

        resolved = resolve_train_state(self.checkpoint_dir)
        if resolved is None:
            return None
        state, _ = resolved
        return state.get("ckpt")  # legacy records carry no dir name

    # -- main loop ------------------------------------------------------

    def run(self) -> SupervisorReport:
        report = SupervisorReport(metrics_port=self.metrics_port)
        t0 = time.time()
        generation = 0
        pending_hb: Optional[RestartRecord] = None
        hb_detect_t = 0.0
        try:
            self._launch(generation)
            report.generations = 1
            launched_at = time.time()
            while True:
                self._update_gang_status(generation)
                if all(p.poll() == 0 for p in self._procs):
                    report.completed = True
                    logger.info(
                        "supervisor: generation %d completed (%d "
                        "restarts total)", generation, report.restarts,
                    )
                    return report
                if pending_hb is not None and any(
                    self._read_status(r, generation) is not None
                    for r in range(self.num_workers)
                ):
                    pending_hb.detect_to_heartbeat_seconds = (
                        time.time() - hb_detect_t
                    )
                    pending_hb = None
                reason = self._check_failure(generation, launched_at)
                if reason is None:
                    time.sleep(self.poll_interval)
                    continue

                detect_t = time.time()
                logger.error(
                    "supervisor: generation %d FAILED: %s; tearing the "
                    "gang down", generation, reason,
                )
                self._kill_gang()
                # Flight recorder: capture the failed generation's
                # per-rank remains NOW — the relaunch reopens (and
                # truncates) the per-rank event logs and status files.
                bundles = self._collect_postmortem(generation, reason)
                report.postmortem_bundles.extend(bundles)
                if report.restarts >= self.max_restarts:
                    report.gave_up_reason = (
                        f"{reason} (restart budget {self.max_restarts} "
                        "exhausted)"
                    )
                    logger.error(
                        "supervisor: giving up: %s", report.gave_up_reason
                    )
                    return report
                try:
                    resumed_from = self._resolve_checkpoint()
                except Exception as e:
                    report.gave_up_reason = (
                        f"{reason}; no verifiable checkpoint to resume "
                        f"from: {e}"
                    )
                    logger.error(
                        "supervisor: giving up: %s", report.gave_up_reason
                    )
                    return report
                backoff = capped_backoff(
                    report.restarts, self.backoff_base_seconds,
                    self.backoff_cap_seconds,
                )
                logger.warning(
                    "supervisor: restart %d/%d in %.1fs (resuming from "
                    "%s)", report.restarts + 1, self.max_restarts,
                    backoff, resumed_from or "scratch",
                )
                time.sleep(backoff)
                generation += 1
                self._launch(generation)
                launched_at = time.time()
                report.restarts += 1
                report.generations += 1
                rec = RestartRecord(
                    generation=generation - 1,
                    reason=reason,
                    resumed_from=resumed_from,
                    backoff_seconds=backoff,
                    detect_to_relaunch_seconds=time.time() - detect_t,
                    postmortem=bundles,
                )
                report.restart_records.append(rec)
                pending_hb, hb_detect_t = rec, detect_t
        finally:
            self._kill_gang()
            self._update_gang_status(generation)
            if self.gang_server is not None:
                self.gang_server.stop()
                self.gang_server = None
            report.wall_seconds = time.time() - t0
        return report
