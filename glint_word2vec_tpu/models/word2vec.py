"""The Word2Vec estimator and fitted model — the user-facing API layer.

Reference mapping (SURVEY.md §2):
  - :class:`Word2Vec` = the trainer/estimator pair C1+C6
    (mllib/feature/ServerSideGlintWord2Vec.scala:65-451 and
    ml/feature/ServerSideGlintWord2Vec.scala:228-317), with the reference's
    fluent setter surface (mllib:92-243) in snake_case.
  - :class:`Word2VecModel` = the model pair C3+C7 (mllib:460-669,
    ml:319-497): transform in its three reference flavors, findSynonyms,
    analogy arithmetic, getVectors, toLocal, save/load/stop.
  - :class:`LocalWord2VecModel` = the ``toLocal`` result (mllib:651-657):
    a host-only numpy model with the same query surface.

The PS-cluster topology parameters (``parameterServerHost``,
``parameterServerConfig``) have no analogue — device placement is a
``jax.sharding.Mesh`` passed directly (or defaulted) — and the training loop
is synchronous: one jit step per minibatch instead of the reference's
per-partition async future chains (mllib:417-429).
"""

from __future__ import annotations

import functools
import json
import logging
import os
import time
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from glint_word2vec_tpu.corpus.batching import (
    BatchGroup,
    SkipGramBatcher,
    chunk_sentences,
    context_width,
    encode_sentences,
    group_batches,
    packed_pair_batch,
)
from glint_word2vec_tpu.corpus.vocab import (
    Vocabulary,
    build_vocab,
    saved_model_vocabulary,
)
from glint_word2vec_tpu.obs import TrainingDiverged, start_run
from glint_word2vec_tpu.utils import faults, next_pow2
from glint_word2vec_tpu.utils.metrics import TrainingMetrics
from glint_word2vec_tpu.utils.params import Word2VecParams
from glint_word2vec_tpu.utils.prefetch import prefetch

logger = logging.getLogger(__name__)

#: Rows per query chunk — the reference batches word/sentence requests
#: 10,000 at a time (mllib:531, ml:449). Here it only bounds HBM spikes.
MAX_QUERY_ROWS = 10_000


def _flip_checkpoint_state(
    checkpoint_dir: str, state_path: str, ck_name: str, *,
    epochs_completed: int, step: int, words_done: int,
    extra: Optional[dict] = None,
) -> None:
    """Atomically point train_state.json at a finished table snapshot and
    prune superseded snapshot dirs. The tables must already be on disk:
    a crash mid-write can never yield a state file referencing partial
    tables (shared by the batcher and corpus-resident training loops).
    ``extra`` merges additional progress counters into the state (the
    packed corpus loop records its consumed-position counter and
    grid-equivalent step base so mid-epoch resumes are exact).

    Keep-last-2 retention: the previously committed record rides along
    under ``"prev"`` and its snapshot directory survives the prune, so a
    checkpoint that later fails integrity verification (bit rot, torn
    write) has a committed fallback
    (utils.integrity.resolve_train_state). Everything older is GC'd."""
    import shutil

    prev = None
    if os.path.exists(state_path):
        try:
            with open(state_path) as f:
                prev = json.load(f)
            prev.pop("prev", None)  # keep exactly two, not a chain
        except (OSError, ValueError):
            prev = None
    if prev is not None and (
        # A legacy record with no snapshot-dir name cannot serve as a
        # fallback; re-committing the same name (repeated
        # stop_after_epochs runs) must not point prev at ourselves.
        "ckpt" not in prev or prev["ckpt"] == ck_name
    ):
        prev = None
    tmp = state_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(
            {
                "epochs_completed": epochs_completed,
                "step": step,
                "words_done": words_done,
                "ckpt": ck_name,
                **(extra or {}),
                **({"prev": prev} if prev else {}),
            },
            f,
        )
    os.replace(tmp, state_path)
    keep = {ck_name}
    if prev:
        keep.add(prev["ckpt"])
    for entry in os.listdir(checkpoint_dir):
        if entry.startswith("ckpt-") and entry not in keep:
            shutil.rmtree(
                os.path.join(checkpoint_dir, entry), ignore_errors=True
            )


def _resolve_resume(checkpoint_dir: str) -> Optional[dict]:
    """Resume-state resolution shared by both fit loops: the newest
    committed checkpoint whose snapshot passes integrity verification
    (manifest sha256 + sizes), falling back to the previous committed
    record kept by the keep-last-2 retention. One clean log line per
    rejected candidate; ``CheckpointCorruptError`` when nothing
    verifies (never a silent from-scratch retrain)."""
    from glint_word2vec_tpu.utils.integrity import resolve_train_state

    resolved = resolve_train_state(checkpoint_dir)
    if resolved is None:
        return None
    state, _ = resolved
    return state


def _process_count() -> int:
    import jax

    return jax.process_count()


def _multiprocess_barrier(tag: str) -> None:
    """All-process rendezvous (replica-exchange checkpoints, ISSUE 15):
    a rank must not flip ``train_state.json`` while peers are still
    writing their shard blocks — the flip would commit a snapshot whose
    per-shard manifests don't all exist yet. No-op single-process."""
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("ckpt_" + tag)


def _ckpt_wait_timeout() -> Optional[float]:
    """Fit-exit barrier timeout for in-flight async checkpoint writes:
    a writer thread wedged on a dead filesystem must fail the run with
    a named job, not pin fit exit forever. Seconds;
    ``GLINT_CKPT_WAIT_TIMEOUT=0`` restores the unbounded wait."""
    raw = os.environ.get("GLINT_CKPT_WAIT_TIMEOUT", "900")
    try:
        t = float(raw)
    except ValueError:
        logger.warning(
            "GLINT_CKPT_WAIT_TIMEOUT=%r is not a number; using 900", raw
        )
        t = 900.0
    return t if t > 0 else None


def _checkpoint_tables(
    engine, obs_run, metrics, ck_path: str, ck_name: str, commit
) -> None:
    """Write one checkpoint without stalling the dispatch pipeline.

    Default (single-process): ``engine.save_async`` — the calling thread
    blocks only for the device->host snapshot copy (``ckpt_snapshot``
    span) and returns to dispatching; serialization, durability fsyncs,
    the atomic directory commit, and the ``commit`` callback (the
    ``train_state.json`` flip) all run on the engine's single writer
    thread (``ckpt_write`` span), strictly in that order, so a crash at
    any point leaves the previous committed checkpoint authoritative.
    ``GLINT_SYNC_CKPT=1`` (or multi-process) forces the fully blocking
    path. Either way the call-site duration is charged to the
    ``device_stall_seconds`` proxy — the wall-clock pause ``bench.py
    stall_overlap`` measures (async removes the write/fsync share of
    it, >80% at the benched config)."""
    t0 = time.time()
    if engine.async_saves_enabled():
        with obs_run.span("ckpt_snapshot", ckpt=ck_name):
            engine.save_async(ck_path, on_commit=commit)
    else:
        with obs_run.span("checkpoint_save", ckpt=ck_name):
            engine.save(ck_path)
            # Multi-process saves write disjoint shard files; the
            # barrier orders every rank's writes (and sidecar
            # manifests) before ANY rank's state flip makes the
            # snapshot authoritative.
            _multiprocess_barrier(ck_name)
            commit()
    metrics.record_stall(time.time() - t0)


def _save_diverged_snapshot(engine, checkpoint_dir, obs_run) -> None:
    """Canary abort tail shared by both fit loops: the event log is
    already flushed (ObsRun); leave a final table snapshot for the
    post-mortem WITHOUT flipping train_state.json — a resume must
    restart from the last healthy checkpoint, not the diverged tables."""
    if not checkpoint_dir:
        return
    ck = os.path.join(checkpoint_dir, "ckpt-diverged")
    with obs_run.span("checkpoint_save", ckpt="ckpt-diverged"):
        engine.save(ck)
    logger.error("canary abort: diverged tables saved to %s", ck)


class Word2Vec:
    """Skip-gram/negative-sampling estimator over a TPU mesh.

    Construct with a :class:`Word2VecParams`, keyword overrides, or use the
    reference-style fluent setters::

        model = (Word2Vec()
                 .set_vector_size(100)
                 .set_window_size(5)
                 .set_step_size(0.025)
                 .set_seed(1)
                 .fit(sentences))
    """

    def __init__(
        self,
        params: Optional[Word2VecParams] = None,
        mesh=None,
        obs=None,
        **overrides,
    ):
        self.params = (params or Word2VecParams()).replace(**overrides)
        self.mesh = mesh
        #: Optional obs.ObsConfig: run-scoped observability (event log,
        #: heartbeat, canary). Like ``mesh``, it is run config — never
        #: part of Word2VecParams or the saved model.
        self.obs = obs

    # Fluent setters (reference mllib:92-243 / python bindings :172-302).
    def _set(self, **kw) -> "Word2Vec":
        self.params = self.params.replace(**kw)
        return self

    def set_vector_size(self, v: int) -> "Word2Vec":
        return self._set(vector_size=v)

    def set_window_size(self, v: int) -> "Word2Vec":
        return self._set(window=v)

    def set_step_size(self, v: float) -> "Word2Vec":
        return self._set(step_size=v)

    def set_batch_size(self, v: int) -> "Word2Vec":
        return self._set(batch_size=v)

    def set_num_negatives(self, v: int) -> "Word2Vec":
        """Reference param ``n`` (negative samples per positive pair)."""
        return self._set(num_negatives=v)

    def set_subsample_ratio(self, v: float) -> "Word2Vec":
        return self._set(subsample_ratio=v)

    def set_min_count(self, v: int) -> "Word2Vec":
        return self._set(min_count=v)

    def set_num_iterations(self, v: int) -> "Word2Vec":
        return self._set(num_iterations=v)

    def set_max_sentence_length(self, v: int) -> "Word2Vec":
        return self._set(max_sentence_length=v)

    def set_seed(self, v: int) -> "Word2Vec":
        return self._set(seed=v)

    def set_num_partitions(self, v: int) -> "Word2Vec":
        """Data-parallel axis size (reference ``numPartitions``)."""
        return self._set(num_partitions=v)

    def set_num_shards(self, v: int) -> "Word2Vec":
        """Model-parallel axis size (reference ``numParameterServers``)."""
        return self._set(num_shards=v)

    def set_dtype(self, v: str) -> "Word2Vec":
        return self._set(dtype=v)

    def set_compute_dtype(self, v: str) -> "Word2Vec":
        """MXU operand dtype for the step's dense contractions ("float32"
        default, "bfloat16" = MXU-native fast path; f32 accumulation
        either way)."""
        return self._set(compute_dtype=v)

    def set_layout(self, v: str) -> "Word2Vec":
        """Model-axis table partitioning: "rows" (default) or "dims"
        (CIKM'16 column sharding — scalar-logit model-axis traffic)."""
        return self._set(layout=v)

    def set_steps_per_call(self, v: int) -> "Word2Vec":
        return self._set(steps_per_call=v)

    def set_shared_negatives(self, v: int) -> "Word2Vec":
        """Shared noise-pool size per step (0 = per-pair reference
        semantics; see Word2VecParams.shared_negatives)."""
        return self._set(shared_negatives=v)

    def set_batch_packing(self, v: str) -> "Word2Vec":
        """Device-corpus dispatch shape: "dense" (the default — valid
        (center, context) pairs prefix-sum-compacted into dense
        fixed-shape pair batches on device before the update, so ~every
        dispatched FLOP is a useful pair, and the shape the fused
        Pallas megakernel accelerates) or "grid" (the legacy reference
        (batch, context) window grids — ~43% live lanes at window 5 —
        kept for A/B comparison and old mid-epoch grid checkpoints).
        See README "Dense pair packing"."""
        return self._set(batch_packing=v)

    def set_exchange(self, v: str) -> "Word2Vec":
        """Cross-replica reconciliation mode for multi-process runs
        (ISSUE 15): "none" = SPMD global mesh, "sparse" = touched-row
        delta exchange between data-parallel replicas, "dense" = full
        delta exchange on the same cadence (parity baseline). See
        README "Pod-scale training"."""
        return self._set(exchange=v)

    def set_exchange_capacity(self, v: int) -> "Word2Vec":
        """Fixed touched-row buffer capacity per exchange sync (0 =
        auto-sized from the dispatch-group pair budget, then adapted
        down from observed telemetry; nonzero pins it)."""
        return self._set(exchange_capacity=v)

    def set_exchange_wire(self, v: str) -> "Word2Vec":
        """Sparse exchange payload encoding (ISSUE 16): "fp32" (exact),
        "bf16", or "int8" (per-row maxabs scale with error-feedback
        residual carry). See README "Pod-scale training"."""
        return self._set(exchange_wire=v)

    def set_exchange_every(self, v: int) -> "Word2Vec":
        """Coalesce R dispatch groups into one exchange round (ISSUE
        16); 1 = sync every group."""
        return self._set(exchange_every=v)

    def set_exchange_topology(self, v: str) -> "Word2Vec":
        """Exchange sync topology (ISSUE 16): "flat" or "twolevel"
        (intra-node exact hop + leaders-only quantized inter-node
        hop; GLINT_RANKS_PER_NODE sets the node size)."""
        return self._set(exchange_topology=v)

    def set_exchange_shard(self, v: str) -> "Word2Vec":
        """Replica corpus sharding: "roundrobin" or "locality"
        (sentences clustered by rarest token to concentrate each
        replica's touched rows; ISSUE 16)."""
        return self._set(exchange_shard=v)

    def set_observability(self, obs) -> "Word2Vec":
        """Attach an :class:`obs.ObsConfig` for subsequent fits (event
        log, live heartbeat, status file, divergence canary)."""
        self.obs = obs
        return self

    # ------------------------------------------------------------------

    def _make_mesh(self, local: bool = False):
        from glint_word2vec_tpu.parallel.mesh import make_mesh

        if self.mesh is not None:
            return self.mesh
        p = self.params
        if local:
            # Replica-exchange mode (ISSUE 15): each process owns a
            # mesh over ITS devices only — cross-process traffic is the
            # host-level delta exchange, never an SPMD collective.
            import jax

            return make_mesh(
                p.num_partitions, p.num_shards,
                devices=jax.local_devices(),
            )
        return make_mesh(p.num_partitions, p.num_shards)

    def fit(
        self,
        sentences: Iterable[Sequence[str]],
        checkpoint_dir: Optional[str] = None,
        checkpoint_every_epochs: int = 1,
        stop_after_epochs: Optional[int] = None,
    ) -> "Word2VecModel":
        """Train on an iterable of tokenized sentences.

        The full reference ``fit`` path (mllib:310-439): vocab scan ->
        encode/chunk -> per-epoch subsample+window passes -> minibatched
        SGNS with the linear LR anneal (floor ``step_size * 1e-4``,
        mllib:405-413) -> fitted model.

        ``checkpoint_dir`` enables epoch-granular checkpoint/resume — a
        capability the reference lacks entirely (SURVEY.md §5 "no checkpoint
        mid-training"): after every ``checkpoint_every_epochs`` epochs the
        tables + progress counters are written, and a rerun of the same fit
        with the same directory resumes after the last completed epoch.
        ``stop_after_epochs`` ends the run early after that many epochs
        *this invocation* (train-in-slices operation; the LR schedule is
        unaffected because it depends only on global progress counters).
        """
        p = self.params
        if not isinstance(sentences, list):
            # Non-rewindable input: single-pass streaming scan+encode
            # into the flat representation (~4 bytes/kept word) instead
            # of materializing a Python sentence list (~15x the RAM).
            # Produces the same vocab/encoding as the list path below.
            from glint_word2vec_tpu.corpus.vocab import scan_and_encode_stream

            vocab, ids, offsets = scan_and_encode_stream(
                sentences, min_count=p.min_count,
                max_sentence_length=p.max_sentence_length,
            )
            return self._fit_flat(
                vocab, ids, offsets, checkpoint_dir,
                checkpoint_every_epochs, stop_after_epochs,
            )
        vocab = build_vocab(sentences, min_count=p.min_count)
        encoded = chunk_sentences(
            encode_sentences(sentences, vocab), p.max_sentence_length
        )
        lens = np.array([s.size for s in encoded], dtype=np.int64)
        if p.exchange != "none" and _process_count() > 1:
            ids = (
                np.concatenate(encoded).astype(np.int32, copy=False)
                if encoded else np.zeros(0, np.int32)
            )
            offsets = np.zeros(len(lens) + 1, np.int64)
            np.cumsum(lens, out=offsets[1:])
            return self._fit_replica_exchange(
                vocab, ids, offsets, checkpoint_dir,
                checkpoint_every_epochs, stop_after_epochs,
            )
        pc, local_batch, steps_per_epoch = self._multihost_plan(lens)
        if pc == 1 and self._device_corpus_eligible(int(lens.sum())):
            # encode_sentences already yields int32; copy=False avoids a
            # second full-corpus copy at peak host-memory time.
            ids = (
                np.concatenate(encoded).astype(np.int32, copy=False)
                if encoded else np.zeros(0, np.int32)
            )
            offsets = np.zeros(len(lens) + 1, np.int64)
            np.cumsum(lens, out=offsets[1:])
            return self._fit_corpus_resident(
                vocab, ids, offsets, checkpoint_dir,
                checkpoint_every_epochs, stop_after_epochs,
            )
        if pc > 1:
            from glint_word2vec_tpu.parallel import distributed as dist

            encoded = dist.shard_sentences_for_process(encoded)
        batcher = SkipGramBatcher(
            encoded,
            vocab,
            batch_size=local_batch,
            window=p.window,
            subsample_ratio=p.subsample_ratio,
            seed=p.seed,
        )
        return self._fit_with_batcher(
            vocab, batcher, checkpoint_dir, checkpoint_every_epochs,
            stop_after_epochs, steps_per_epoch=steps_per_epoch,
        )

    def fit_file(
        self,
        path: str,
        lowercase: bool = False,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every_epochs: int = 1,
        stop_after_epochs: Optional[int] = None,
    ) -> "Word2VecModel":
        """Train directly from a text file (one sentence per line) with
        streaming ingestion: two passes over the file (vocab scan, then
        flat int32 encode), never materializing Python sentence objects —
        host memory is ~4 bytes/kept word. The scaling path for the
        Common-Crawl-class configs (BASELINE.json): the reference gets the
        same property from Spark RDD streaming; a plain Python list of
        sentences costs ~15x more RAM than the flat encoding."""
        from glint_word2vec_tpu.corpus.vocab import scan_and_encode_file

        p = self.params
        vocab, ids, offsets = scan_and_encode_file(
            path, min_count=p.min_count,
            max_sentence_length=p.max_sentence_length, lowercase=lowercase,
        )
        return self._fit_flat(
            vocab, ids, offsets, checkpoint_dir, checkpoint_every_epochs,
            stop_after_epochs,
        )

    def fit_stream(
        self,
        sentences: Iterable[Sequence[str]],
        publish_dir: Optional[str] = None,
        **stream_kw,
    ) -> "Word2VecModel":
        """Incremental training on an unbounded sentence stream (ISSUE
        10, the ISGNS construction arXiv:1704.03956): one look at each
        sentence, adaptive noise/subsample distributions recomputed
        from live counts on a cadence, online vocabulary growth onto
        the engine's spare extra rows, and — with ``publish_dir`` —
        committed model generations published for a serving fleet to
        hot-swap under load (streaming/publish.py).

        Returns the fitted model when the stream ends or a
        ``max_words``/``max_seconds`` bound trips. Cadence and capacity
        knobs are forwarded to
        :class:`glint_word2vec_tpu.streaming.trainer.StreamTrainer`."""
        from glint_word2vec_tpu.streaming.trainer import StreamTrainer

        return StreamTrainer(
            self, publish_dir=publish_dir, **stream_kw
        ).run(sentences)

    def _fit_flat(
        self,
        vocab: Vocabulary,
        ids: np.ndarray,
        offsets: np.ndarray,
        checkpoint_dir: Optional[str],
        checkpoint_every_epochs: int,
        stop_after_epochs: Optional[int],
    ) -> "Word2VecModel":
        """Train from the flat encoded corpus ``(ids, offsets)`` — the
        common tail of ``fit_file`` and streaming-``fit``: route to the
        device-resident scan when eligible, else shard across processes
        and run the host batcher pipeline."""
        p = self.params
        if p.exchange != "none" and _process_count() > 1:
            return self._fit_replica_exchange(
                vocab, ids, offsets, checkpoint_dir,
                checkpoint_every_epochs, stop_after_epochs,
            )
        pc, local_batch, steps_per_epoch = self._multihost_plan(np.diff(offsets))
        if pc == 1 and self._device_corpus_eligible(int(ids.size)):
            return self._fit_corpus_resident(
                vocab, ids, offsets, checkpoint_dir,
                checkpoint_every_epochs, stop_after_epochs,
            )
        if pc > 1:
            from glint_word2vec_tpu.parallel import distributed as dist

            ids, offsets = dist.shard_flat_for_process(ids, offsets)
        batcher = SkipGramBatcher.from_flat(
            ids, offsets, vocab,
            batch_size=local_batch,
            window=p.window,
            subsample_ratio=p.subsample_ratio,
            seed=p.seed,
        )
        return self._fit_with_batcher(
            vocab, batcher, checkpoint_dir, checkpoint_every_epochs,
            stop_after_epochs, steps_per_epoch=steps_per_epoch,
        )

    def _fit_replica_exchange(
        self,
        vocab: Vocabulary,
        ids: np.ndarray,
        offsets: np.ndarray,
        checkpoint_dir: Optional[str],
        checkpoint_every_epochs: int,
        stop_after_epochs: Optional[int],
    ) -> "Word2VecModel":
        """Multi-process replica-exchange fit (ISSUE 15): every process
        takes its round-robin corpus shard, trains it on a LOCAL mesh,
        and reconciles tables with its peers through the touched-row
        delta exchange after every dispatch group
        (parallel/exchange.py) — no SPMD collective ever crosses
        processes, so cross-host bytes scale with rows touched instead
        of vocab size. Identical engine seeds give every replica the
        same initial tables; each sync leaves all replicas
        value-identical again."""
        from glint_word2vec_tpu.parallel import distributed as dist

        if self.params.exchange_shard == "locality":
            ids, offsets = dist.shard_flat_locality(ids, offsets)
        else:
            ids, offsets = dist.shard_flat_for_process(ids, offsets)
        # graftlint: ignore[sync-point] ids is host numpy here
        if not self._device_corpus_eligible(int(ids.size)):
            raise ValueError(
                "replica-exchange training needs the device-resident "
                "corpus path: this process's corpus shard exceeds the "
                "device corpus budget (GLINT_DEVICE_CORPUS_MAX_BYTES) "
                "or GLINT_HOST_BATCHER=1 is set"
            )
        return self._fit_corpus_resident(
            vocab, ids, offsets, checkpoint_dir,
            checkpoint_every_epochs, stop_after_epochs,
        )

    def _device_corpus_eligible(self, corpus_words: int = 0) -> bool:
        """Whether the device-resident corpus path applies: word-level
        centers (subword grouping overrides this to False), the corpus
        fits the HBM budget reserved for it (GLINT_DEVICE_CORPUS_MAX_BYTES
        overrides the 2 GiB default; tables need the rest), and no env
        escape hatch. Frequency subsampling no longer disqualifies —
        the per-epoch compaction pass runs on device
        (ops/device_batching.subsample_compact) — but it triples the HBM
        charge: the flat corpus plus the compacted buffer plus the
        transient prefix sums hold ~12 bytes/word replicated per device,
        vs ~4 bytes/word without subsampling. Single-process only — the
        caller checks process count."""
        raw_budget = os.environ.get("GLINT_DEVICE_CORPUS_MAX_BYTES")
        try:
            budget = int(raw_budget) if raw_budget is not None else 2 << 30
        except ValueError:
            logger.warning(
                "GLINT_DEVICE_CORPUS_MAX_BYTES=%r is not an integer; "
                "using the 2 GiB default", raw_budget,
            )
            budget = 2 << 30
        bytes_per_word = 12 if self.params.subsample_ratio > 0 else 4
        return (
            bytes_per_word * corpus_words <= budget
            # upload_corpus indexes the flat corpus with int32; an
            # oversized corpus routes to the host batcher, not an error.
            and corpus_words < 2**31
            and os.environ.get("GLINT_HOST_BATCHER", "0") != "1"
        )

    def _fit_corpus_resident(
        self,
        vocab: Vocabulary,
        ids: np.ndarray,
        offsets: np.ndarray,
        checkpoint_dir: Optional[str],
        checkpoint_every_epochs: int,
        stop_after_epochs: Optional[int],
    ) -> "Word2VecModel":
        """Training loop for the device-resident corpus path: the flat
        encoded corpus is uploaded to HBM once (EmbeddingEngine
        .upload_corpus) and every minibatch is assembled inside the
        jitted scan (ops/device_batching) — per-dispatch host->device
        traffic is scalars, and the host thread's only jobs are the LR
        schedule and metrics. With ``subsample_ratio > 0`` a per-epoch
        jitted pass subsample-compacts the corpus on device
        (EmbeddingEngine.compact_corpus); the host reads back one scalar
        (``n_kept``) plus the compacted sentence offsets per epoch to
        size the step loop and keep the pre-subsampling words_done
        accounting. Batch-for-batch the un-subsampled stream matches the
        host pipeline's packing, so quality gates and LR accounting
        match; the subsample/window-shrink RNG streams differ (device
        threefry), like the native C++ pass already differs from the
        Python fallback."""
        import jax

        p = self.params
        subsampling = p.subsample_ratio > 0
        logger.info(
            "vocab: %d words, %d train words (device-resident corpus%s)",
            vocab.size, vocab.train_words_count,
            ", on-device subsampling" if subsampling else "",
        )
        from glint_word2vec_tpu.ops.device_batching import (
            corpus_words_done,
            corpus_words_done_compacted,
        )

        replica_mode = p.exchange != "none" and jax.process_count() > 1
        mesh = self._make_mesh(local=replica_mode)
        if p.batch_size % mesh.shape["data"]:
            raise ValueError(
                f"batch_size ({p.batch_size}) must be divisible by the "
                f"data-axis size ({mesh.shape['data']})"
            )
        engine = self._make_engine(mesh, vocab)
        twc = vocab.train_words_count
        obs_run = start_run(
            self.obs, pipeline="device_corpus",
            total_epochs=p.num_iterations,
            total_words=p.num_iterations * twc, engine=engine,
        )
        try:
            with obs_run.span("upload_corpus", words=int(ids.shape[0])):
                engine.upload_corpus(ids, offsets)
            if subsampling:
                engine.set_keep_probs(
                    vocab.device_keep_probabilities(p.subsample_ratio)
                )
            N = int(ids.shape[0])
            B, spc = p.batch_size, p.steps_per_call
            total_words = p.num_iterations * twc + 1
            base_key = jax.random.PRNGKey(p.seed)
            step = 0
            start_epoch = 0
            # Dense pair packing (the default): dispatch prefix-sum-
            # compacted pair batches instead of half-masked window
            # grids. Pair slots per step cover ~B center positions in
            # EXPECTATION (corpus/batching.packed_pair_batch), so a
            # packed step trains the same effective synchronous batch
            # as a grid step — identical update dynamics/stability —
            # while spending ~zero dispatched lanes on masked padding
            # (each step is ~density x the grid step's FLOPs).
            packed = p.batch_packing == "dense"
            pair_batch = packed_pair_batch(
                B, p.window, mesh.shape["data"]
            )
            resume_position = 0
            # Grid-equivalent step counter: pins the packed path's
            # window-shrink draws to the position->draw mapping the grid
            # scan would use for this run, keeping the per-epoch valid-
            # pair multiset identical across the two modes.
            gstep = 0
            # Preemption drill / mid-epoch checkpoint test hook: stop the
            # packed run after this many dispatch groups, saving a
            # mid-epoch checkpoint carrying the consumed-position counter.
            stop_after_groups = os.environ.get(
                "GLINT_PACKED_STOP_AFTER_GROUPS"
            )
            stop_after_groups = (
                int(stop_after_groups) if stop_after_groups else None
            )
            packed_groups = packed_pairs = packed_slots = 0
            early_stop = False

            state_path = (
                os.path.join(checkpoint_dir, "train_state.json")
                if checkpoint_dir
                else None
            )
            resume_words = None
            state = _resolve_resume(checkpoint_dir) if state_path else None
            if state is not None:
                with obs_run.span("checkpoint_restore", ckpt=state["ckpt"]):
                    engine.load_tables(
                        os.path.join(checkpoint_dir, state["ckpt"])
                    )
                start_epoch = state["epochs_completed"]
                step = state["step"]
                # Packed states carry the mid-epoch consumed-position
                # counter and the epoch's grid-equivalent step base; a
                # grid-written state implies position 0 and gstep == step
                # (the grid step counter IS the grid-equivalent counter).
                # A MID-EPOCH state is only resumable in the dispatch
                # mode that wrote it: a cross-mode resume would silently
                # drop (or misread) the consumed-position counter and
                # re-train the epoch's consumed prefix on tables that
                # already hold its updates.
                state_packing = state.get("batch_packing", "grid")
                if (
                    int(state.get("position", 0)) > 0
                    and state_packing != p.batch_packing
                ):
                    raise ValueError(
                        f"mid-epoch checkpoint at {checkpoint_dir} was "
                        f"written with batch_packing="
                        f"{state_packing!r} (position "
                        f"{state['position']}); resume with the same "
                        "packing mode, or restart from an epoch-boundary "
                        "checkpoint"
                    )
                # position is 0 in every epoch-boundary state (both
                # modes record it uniformly); a nonzero value already
                # passed the same-mode check above.
                resume_position = int(state.get("position", 0))
                gstep = int(state.get("gstep", state["step"]))
                resume_words = int(state.get("words_done", start_epoch * twc))
                logger.info(
                    "resuming after epoch %d (step %d, position %d)",
                    start_epoch, step, resume_position,
                )
            metrics = TrainingMetrics(
                base_words=(
                    resume_words if resume_words is not None
                    else start_epoch * twc
                )
            )
            obs_run.attach_metrics(metrics)
            # Replica exchange (ISSUE 15): constructed AFTER any resume
            # restore so the reconciliation base snapshots the restored
            # tables. Per-rank key decorrelation folds the process rank
            # into the step-key stream (table INIT stays seed-identical
            # across replicas — reconciliation depends on it); the save
            # split makes every rank checkpoint only its own row block.
            exchanger = None
            if p.exchange != "none":
                from glint_word2vec_tpu.parallel import exchange as exmod

                transport = (
                    exmod.ProcessTransport()
                    if jax.process_count() > 1 else exmod.NullTransport()
                )
                if transport.world > 1:
                    if stop_after_groups is not None:
                        # The stop-early drill breaks the lockstep
                        # protocol mid-epoch: peers would wait in the
                        # exchange collective forever. Fail loudly
                        # instead of deadlocking the gang.
                        raise ValueError(
                            "GLINT_PACKED_STOP_AFTER_GROUPS is not "
                            "supported with multi-process replica "
                            "exchange (peers would deadlock in the "
                            "exchange collective)"
                        )
                    engine.set_save_split(transport.rank, transport.world)
                    base_key = jax.random.fold_in(
                        base_key, transport.rank
                    )
                else:
                    logger.info(
                        "replica exchange on a single process: the "
                        "reconciliation protocol runs for parity/"
                        "telemetry (one extra table pair of HBM, one "
                        "sync per dispatch group) with no cross-rank "
                        "traffic"
                    )
                exchanger = exmod.ReplicaExchanger(
                    engine, mode=p.exchange,
                    capacity=p.exchange_capacity or None,
                    transport=transport,
                    pair_batch=pair_batch if packed else B,
                    steps_per_call=spc,
                    wire=p.exchange_wire,
                    every=p.exchange_every,
                    topology=p.exchange_topology,
                )
            # Mutated by _harvest_packed (declared before the epoch loop
            # so the closure binds the method scope, not a loop body).
            n_pos, offsets_c, epoch, epoch_wd = N, None, start_epoch, 0

            def _prefetch_next_compact(next_epoch: int) -> None:
                # ISSUE 5 prefetch overlap: DISPATCH (don't adopt) the
                # next epoch's subsample-compact pass while the current
                # epoch's tail group is still executing; the next
                # compact_corpus call adopts the bitwise-identical
                # buffers without re-running the pass. Skipped when the
                # run won't reach that epoch (the transient buffer would
                # just burn HBM). GLINT_NO_COMPACT_PREFETCH=1 restores
                # the serialized epoch boundary (debug escape hatch).
                if not subsampling or next_epoch >= p.num_iterations:
                    return
                if (
                    stop_after_epochs is not None
                    and (next_epoch - start_epoch) >= stop_after_epochs
                ):
                    return
                if os.environ.get("GLINT_NO_COMPACT_PREFETCH", "0") == "1":
                    return
                with obs_run.span("subsample_prefetch", epoch=next_epoch):
                    engine.prefetch_compact_corpus(
                        jax.random.fold_in(base_key, next_epoch)
                    )

            def _harvest_packed(pend) -> int:
                # Convert ONE dispatched packed group's result scalars
                # and fold them into the step/LR/canary accounting;
                # returns the group's final consumed position. Under the
                # deferred schedule the NEXT group is already dispatched
                # when this blocks, so the device never idles behind the
                # conversion — and the metric/canary view lags the
                # device by exactly one dispatch group (documented;
                # tests/test_stall.py pins it). A group dispatched
                # entirely past the corpus end (the deferred schedule's
                # one possible phantom tail group) records nothing and
                # does NOT advance the step counter: its steps were all
                # zero-pair no-ops, and the epoch-end ``dstep = step``
                # reset drops its fold_in keys so the next epoch's key
                # schedule matches the synchronous loop bitwise.
                nonlocal step, epoch_wd
                nonlocal packed_pairs, packed_slots, packed_groups
                losses, pair_counts, pos_ends, alphas_d, start_h = pend
                with metrics.timing("step"), obs_run.span(
                    "readback_harvest", packed=True
                ) as hspan:
                    pos_ends_h = np.asarray(pos_ends)
                    pairs_h = np.asarray(pair_counts)
                    alphas_h = np.asarray(alphas_d)
                    starts = np.concatenate(([start_h], pos_ends_h[:-1]))
                    # Live steps form a prefix: positions only ever
                    # advance, so the first start past the corpus end
                    # makes all later steps no-ops.
                    n_real = int((starts < n_pos).sum())
                    hspan.update(n=n_real)
                    for i in range(n_real):
                        step += 1
                        end_pos = int(min(pos_ends_h[i], n_pos))
                        if subsampling:
                            done = corpus_words_done_compacted(
                                offsets, offsets_c, end_pos, n_pos
                            )
                        else:
                            done = corpus_words_done(offsets, end_pos)
                        epoch_wd = epoch * twc + done
                        metrics.record_step(
                            int(epoch_wd), loss=losses[i],
                            alpha=float(alphas_h[i]),
                        )
                    obs_run.observe_losses(step - n_real, losses, n_real)
                if n_real:
                    obs_run.update(
                        step=step, words_done=int(epoch_wd),
                        alpha=float(alphas_h[n_real - 1]),
                    )
                    step += spc - n_real  # tail no-ops consumed keys
                packed_pairs += int(pairs_h[:n_real].sum())
                packed_slots += n_real * pair_batch
                packed_groups += 1
                return int(pos_ends_h[-1])

            for epoch in range(start_epoch, p.num_iterations):
                obs_run.update(epoch=epoch)
                if subsampling:
                    # The epoch's subsample draws are keyed by epoch alone
                    # (the reference reseeds per iteration, mllib:371-373),
                    # so a resumed run recompacts epoch e to the identical
                    # buffers — no compaction state needs checkpointing.
                    # The blocking n_kept sync is charged to the stall
                    # proxy; with the pass prefetched during the previous
                    # epoch's tail it is near zero.
                    with metrics.timing("step"), metrics.stall_timing(), \
                            obs_run.span("subsample_compact", epoch=epoch):
                        n_pos = engine.compact_corpus(
                            jax.random.fold_in(base_key, epoch)
                        )
                    offsets_c = engine.compacted_offsets()
                else:
                    n_pos, offsets_c = N, None
                steps_per_epoch = max(1, -(-n_pos // B))
                groups = max(1, -(-steps_per_epoch // spc))
                if packed:
                    pos = resume_position
                    resume_position = 0
                    epoch_wd = epoch * twc
                    # Deferred readbacks (ISSUE 5): the dispatch of group
                    # g+1 chains on group g's final position as a DEVICE
                    # scalar (no host sync), and group g's scalars are
                    # harvested while g+1 executes — the per-group host
                    # conversion stops serializing the device. Identical
                    # dispatch arguments to the synchronous schedule
                    # except one possible zero-pair phantom tail group
                    # per epoch (rolled out of the key schedule at epoch
                    # end), so tables are bitwise-identical either way
                    # (tests/test_stall.py). GLINT_SYNC_READBACK=1 — and
                    # the stop-after-groups drill, which must know each
                    # group's end position before deciding to dispatch —
                    # force the synchronous schedule.
                    defer = (
                        stop_after_groups is None
                        and os.environ.get("GLINT_SYNC_READBACK", "0")
                        != "1"
                        # Exchange rounds are reconciliation barriers:
                        # every group ends with a host-level sync, so
                        # the one-group-deferred schedule has nothing
                        # to overlap.
                        and exchanger is None
                    )
                    gang_live = (
                        exchanger is not None
                        and exchanger.transport.world > 1
                    )
                    pending = None
                    next_start = pos  # host int now, device scalar later
                    dstep = step  # dispatch-time step0 (runs ahead)
                    while pos < n_pos:
                        faults.fire("worker.step")
                        with metrics.timing("step"), obs_run.span(
                            "device_steps", step0=dstep, n=spc, packed=True
                        ):
                            (
                                losses, pair_counts, pos_ends, alphas_d,
                            ) = engine.train_steps_corpus_packed(
                                next_start, pair_batch, p.window, B,
                                base_key, spc, step0=dstep,
                                grid_step0=gstep, step_size=p.step_size,
                                total_words=total_words,
                                words_base=epoch * twc,
                            )
                        dstep += spc
                        next_start = pos_ends[-1]  # device scalar chain
                        new_pend = [
                            losses, pair_counts, pos_ends, alphas_d, pos,
                        ]
                        if pending is not None:
                            # Harvest g-1 while g runs; its end position
                            # is g's true start for the live-step count.
                            pos = _harvest_packed(pending)
                            new_pend[4] = pos
                        pending = new_pend
                        if not defer:
                            pos = _harvest_packed(pending)
                            pending = None
                            next_start = pos
                            if exchanger is not None:
                                with metrics.timing("step"), obs_run.span(
                                    "exchange_sync", packed=True
                                ):
                                    gang_live = exchanger.group_end(
                                        live=True, done=pos >= n_pos
                                    )
                            if (
                                stop_after_groups is not None
                                and packed_groups >= stop_after_groups
                            ):
                                early_stop = True
                                break
                    if not early_stop:
                        # Enqueue the next epoch's compaction BEFORE
                        # draining: it lands behind the tail group in the
                        # device queue and runs while the host drains.
                        _prefetch_next_compact(epoch + 1)
                    if pending is not None:
                        pos = _harvest_packed(pending)
                        pending = None
                    # Lockstep fillers (replica exchange): a drained
                    # rank keeps answering the gang's exchange rounds
                    # with empty payloads until EVERY rank reports done
                    # — no peer is ever left waiting in a collective.
                    if exchanger is not None and not early_stop:
                        while gang_live:
                            with metrics.timing("step"), obs_run.span(
                                "exchange_sync", filler=True
                            ):
                                gang_live = exchanger.group_end(
                                    live=False, done=True
                                )
                        exchanger.epoch_reset()
                    # Drop the phantom tail group's keys (if any) so the
                    # next epoch's step0 matches the synchronous loop.
                    dstep = step
                    if early_stop:
                        if state_path:
                            ck_name = f"ckpt-e{epoch}-p{pos}"
                            _checkpoint_tables(
                                engine, obs_run, metrics,
                                os.path.join(checkpoint_dir, ck_name),
                                ck_name,
                                functools.partial(
                                    _flip_checkpoint_state,
                                    checkpoint_dir, state_path, ck_name,
                                    epochs_completed=epoch, step=step,
                                    words_done=int(epoch_wd),
                                    extra={
                                        "position": pos, "gstep": gstep,
                                        "batch_packing": "dense",
                                    },
                                ),
                            )
                        logger.info(
                            "stopping mid-epoch %d at position %d "
                            "(GLINT_PACKED_STOP_AFTER_GROUPS)", epoch, pos,
                        )
                        break
                    # Advance the grid-equivalent counter exactly as the
                    # grid loop advances its step counter for this epoch
                    # (spc keys per group, tail no-ops included).
                    gstep += groups * spc
                else:
                    gang_live = (
                        exchanger is not None
                        and exchanger.transport.world > 1
                    )
                    for g in range(groups):
                        faults.fire("worker.step")
                        start_pos = g * spc * B
                        with metrics.timing("host"), obs_run.span(
                            "host_batch", epoch=epoch, group=g
                        ):
                            # LR anneal: the host batcher's
                            # pre-subsampling words_done accounting —
                            # from the original offsets alone, or looked
                            # up through the epoch's compacted offsets
                            # when subsampling.
                            alphas = np.empty(spc, np.float32)
                            wds = np.empty(spc, np.int64)
                            for j in range(spc):
                                end_pos = min(start_pos + (j + 1) * B, n_pos)
                                if subsampling:
                                    done = corpus_words_done_compacted(
                                        offsets, offsets_c, end_pos, n_pos
                                    )
                                else:
                                    done = corpus_words_done(
                                        offsets, end_pos
                                    )
                                wd = epoch * twc + done
                                wds[j] = wd
                                alphas[j] = max(
                                    p.step_size * (1 - wd / total_words),
                                    p.step_size * 1e-4,
                                )
                        # An epoch subsampled to nothing dispatches its
                        # one no-op group but records no steps — the host
                        # batcher likewise yields no batches then.
                        n_real = min(
                            spc, max(0, -(-(n_pos - start_pos) // B))
                        )
                        with metrics.timing("step"), obs_run.span(
                            "device_steps", step0=step, n=n_real
                        ):
                            losses = engine.train_steps_corpus(
                                start_pos, B, p.window, base_key, alphas,
                                step,
                            )
                            for i in range(n_real):
                                step += 1
                                metrics.record_step(
                                    int(wds[i]), loss=losses[i],
                                    alpha=float(alphas[i]),
                                )
                            # Inside the step bucket: the canary's
                            # periodic loss sync waits on the device, and
                            # device waits outside both buckets would
                            # skew host_frac.
                            obs_run.observe_losses(
                                step - n_real, losses, n_real
                            )
                        if n_real:
                            obs_run.update(
                                step=step, words_done=int(wds[n_real - 1]),
                                alpha=float(alphas[n_real - 1]),
                            )
                        step += spc - n_real  # tail no-ops consumed keys
                        if exchanger is not None:
                            with metrics.timing("step"), obs_run.span(
                                "exchange_sync"
                            ):
                                gang_live = exchanger.group_end(
                                    live=True, done=(g == groups - 1)
                                )
                    if exchanger is not None:
                        # Lockstep fillers: see the packed branch.
                        while gang_live:
                            with metrics.timing("step"), obs_run.span(
                                "exchange_sync", filler=True
                            ):
                                gang_live = exchanger.group_end(
                                    live=False, done=True
                                )
                        exchanger.epoch_reset()
                    gstep = step
                    # Grid dispatches are asynchronous: the tail group is
                    # still executing here, so the next epoch's
                    # compaction queues right behind it.
                    _prefetch_next_compact(epoch + 1)
                stopping = (
                    stop_after_epochs is not None
                    and (epoch + 1 - start_epoch) >= stop_after_epochs
                )
                if state_path and (
                    stopping
                    or (epoch + 1) % max(checkpoint_every_epochs, 1) == 0
                ):
                    if exchanger is not None:
                        # Drain the error-feedback carry through one
                        # exact wire round (no-op unless the int8 wire
                        # accumulated one) so a resume from this
                        # checkpoint replays bitwise against the
                        # uninterrupted run. Config-gated on every
                        # rank identically — collective-safe.
                        with obs_run.span("exchange_flush"):
                            exchanger.flush()
                    ck_name = f"ckpt-{epoch + 1}"
                    _checkpoint_tables(
                        engine, obs_run, metrics,
                        os.path.join(checkpoint_dir, ck_name), ck_name,
                        functools.partial(
                            _flip_checkpoint_state, checkpoint_dir,
                            state_path, ck_name,
                            epochs_completed=epoch + 1, step=step,
                            words_done=(epoch + 1) * twc,
                            # Uniform state record for BOTH dispatch
                            # modes (the grid-only special case is
                            # gone): epoch boundaries always carry
                            # position 0, the grid-equivalent step
                            # base, and the mode that wrote them.
                            extra={
                                "position": 0, "gstep": gstep,
                                "batch_packing": p.batch_packing,
                                # Exchange wire config at write time:
                                # a resumed run replays bitwise only
                                # under the same (wire, every) cell
                                # (the flush above zeroed the carry).
                                "exchange_wire": p.exchange_wire,
                                "exchange_every": p.exchange_every,
                            },
                        ),
                    )
                if stopping:
                    logger.info("stopping early after epoch %d", epoch + 1)
                    break
            # Fit-exit barrier: the fit must not return (and the model
            # must not be saved over) while a snapshot write is in
            # flight; a failed async write surfaces HERE, loudly — and a
            # HUNG writer raises after the bounded wait instead of
            # pinning fit exit forever (GLINT_CKPT_WAIT_TIMEOUT).
            engine.wait_pending_saves(timeout=_ckpt_wait_timeout())
        except TrainingDiverged:
            engine.wait_pending_saves(
                reraise=False, timeout=_ckpt_wait_timeout()
            )
            _save_diverged_snapshot(engine, checkpoint_dir, obs_run)
            raise
        except BaseException:
            engine.wait_pending_saves(
                reraise=False, timeout=_ckpt_wait_timeout()
            )
            obs_run.close(failed=True)
            raise
        finally:
            obs_run.close()
        logger.info("training done: %s", metrics.summary())
        model = self._make_model(vocab, engine)
        model.training_metrics = {
            **metrics.summary(), "pipeline": "device_corpus",
        }
        # Step-time attribution (ISSUE 8): where the fit thread's wall
        # went, by phase — the breakdown that replaces eyeballing the
        # single device_stall_seconds proxy. None when obs is off.
        steptime = obs_run.steptime_totals()
        if steptime:
            model.training_metrics["steptime"] = steptime
        model.training_metrics["batch_packing"] = p.batch_packing
        if exchanger is not None:
            model.training_metrics["exchange_mode"] = p.exchange
            model.training_metrics["exchange_wire"] = p.exchange_wire
            model.training_metrics["exchange_every"] = p.exchange_every
            model.training_metrics["exchange_topology"] = p.exchange_topology
            model.training_metrics["exchange"] = engine.exchange_stats()
        if packed and packed_slots:
            # Packed fill = live pairs / dispatched pair slots — the
            # effective mask density of the packed dispatches (the grid
            # path runs ~0.43 at window 5; the CI smoke job gates >= 0.9).
            model.training_metrics.update(
                packed_pairs=packed_pairs,
                packed_mask_density=round(packed_pairs / packed_slots, 4),
                # Whether the dispatches rode the fused Pallas megakernel
                # (ops/pallas_sgns) instead of the composed XLA pair step.
                pallas_fused=bool(getattr(engine, "_pallas_fused", False)),
            )
        return model

    # -- multi-host helpers (SURVEY.md §2.3 DP row; VERDICT.md missing #1) --

    def _multihost_plan(self, sentence_lengths: np.ndarray):
        """(process_count, local_batch_size, steps_per_epoch) for this run.

        Multi-host contract (shared by fit and fit_file): every process
        reads the same corpus (the shared-filesystem contract, like the
        reference's HDFS corpus), builds the identical global vocab with
        zero communication, and materializes only its round-robin shard
        (Client.runWithWord2VecMatrixOnSpark's partition placement,
        mllib:345,354-362). The per-epoch step count is fixed up front from
        the max shard word count so every process dispatches in lockstep
        (SPMD collectives deadlock otherwise). Single process returns
        (1, batch_size, None).
        """
        import jax

        pc = jax.process_count()
        if pc <= 1:
            return 1, self.params.batch_size, None
        local_batch = self._local_batch_size(pc)
        return pc, local_batch, self._steps_per_epoch(
            sentence_lengths, pc, local_batch
        )

    def _local_batch_size(self, pc: int) -> int:
        """Per-process rows of the global batch (each host feeds only the
        data-axis rows its own devices hold)."""
        p = self.params
        if p.batch_size % pc:
            raise ValueError(
                f"batch_size ({p.batch_size}) must be divisible by the "
                f"process count ({pc}) for multi-host training"
            )
        return p.batch_size // pc

    @staticmethod
    def _steps_per_epoch(
        sentence_lengths: np.ndarray, pc: int, local_batch: int
    ) -> int:
        """Agreed per-epoch step count: enough for the wordiest shard.

        Computable identically on every host with no communication (see
        distributed.per_process_word_counts). Subsampling only *removes*
        center positions, so this is always an upper bound; short hosts pad
        zero-mask batches up to it.
        """
        from glint_word2vec_tpu.parallel import distributed as dist

        counts = dist.per_process_word_counts(sentence_lengths, pc)
        return max(1, int(-(-int(counts.max()) // local_batch)))

    def _fit_with_batcher(
        self,
        vocab: Vocabulary,
        batcher: SkipGramBatcher,
        checkpoint_dir: Optional[str],
        checkpoint_every_epochs: int,
        stop_after_epochs: Optional[int],
        steps_per_epoch: Optional[int] = None,
    ) -> "Word2VecModel":
        """Shared training loop. ``steps_per_epoch`` (multi-host only) fixes
        the number of steps every process dispatches per epoch; None (single
        process) runs the batcher to exhaustion."""
        import jax

        p = self.params
        pc = jax.process_count()
        if p.batch_packing == "dense":
            # Dense packing is the default but applies only to the
            # device-resident corpus path; host-batcher routes
            # (multi-process, HBM budget, GLINT_HOST_BATCHER, subword
            # grouping) always build grid-shaped batches. One info line,
            # not a warning — the default config lands here legitimately.
            logger.info(
                "host-batcher route: training with grid-shaped batches "
                "(dense pair packing applies to the device-resident "
                "corpus path only)"
            )
        logger.info(
            "vocab: %d words, %d train words", vocab.size, vocab.train_words_count
        )
        mesh = self._make_mesh()
        if p.batch_size % mesh.shape["data"]:
            raise ValueError(
                f"batch_size ({p.batch_size}) must be divisible by the "
                f"data-axis size ({mesh.shape['data']})"
            )
        if pc > 1 and mesh.shape["data"] % pc:
            raise ValueError(
                f"data-axis size ({mesh.shape['data']}) must be a multiple "
                f"of the process count ({pc}) so each host's devices form "
                "whole data rows (set num_partitions accordingly)"
            )
        engine = self._make_engine(mesh, vocab)
        obs_run = start_run(
            self.obs, pipeline="host", total_epochs=p.num_iterations,
            total_words=p.num_iterations * vocab.train_words_count,
            engine=engine,
        )
        try:
            # LR schedule denominator: iterations * total train words + 1
            # (reference ``totalWordsCount``, mllib:405-410).
            total_words = p.num_iterations * vocab.train_words_count + 1
            base_key = jax.random.PRNGKey(p.seed)
            step = 0
            start_epoch = 0

            state_path = (
                os.path.join(checkpoint_dir, "train_state.json")
                if checkpoint_dir
                else None
            )
            # Integrity-verified resolution with fallback to the
            # previous committed snapshot (keep-last-2); legacy records
            # without a "ckpt" key come back as-is for the legacy path.
            state = _resolve_resume(checkpoint_dir) if state_path else None
            if state is not None:
                with obs_run.span(
                    "checkpoint_restore", ckpt=state.get("ckpt", "ckpt")
                ):
                    if "ckpt" in state:
                        engine.load_tables(
                            os.path.join(checkpoint_dir, state["ckpt"])
                        )
                    else:  # legacy single-file layout
                        engine.set_tables(
                            np.load(
                                os.path.join(checkpoint_dir, "ckpt", "syn0.npy")
                            ),
                            np.load(
                                os.path.join(checkpoint_dir, "ckpt", "syn1.npy")
                            ),
                        )
                start_epoch = state["epochs_completed"]
                step = state["step"]
                batcher.words_done = state["words_done"]
                logger.info(
                    "resuming after epoch %d (step %d)", start_epoch, step
                )
            # Metrics count only THIS invocation's work; on resume the restored
            # global counter must not inflate throughput numbers.
            metrics = TrainingMetrics(base_words=batcher.words_done)
            obs_run.attach_metrics(metrics)

            def save_checkpoint(epochs_completed: int) -> None:
                # Atomic: the sharded table snapshot lands in a fresh directory
                # first; state.json (atomic rename) flips to it last, so a crash
                # mid-write can never yield a state file pointing at mismatched
                # or partial tables. Older snapshot dirs are pruned after.
                # Single-process: the whole sequence runs on the engine's
                # background writer thread (non-blocking checkpointing,
                # ISSUE 5) — the fit loop keeps dispatching.
                # Multi-host: every process writes its own table shards
                # (engine.save, blocking — the barrier needs them on
                # disk), then a barrier ensures all shards are written
                # before process 0 alone flips state.json and prunes —
                # per-host counters can diverge only by padding, and a
                # lone writer keeps the flip atomic.
                ck_name = f"ckpt-{epochs_completed}"
                # words_done feeds the resumed run's metrics base and the
                # single-host LR accounting; under the multi-host schedule
                # the global pro-rata count is the coherent value (the
                # local batcher count is per-shard and would mix units).
                wd = (
                    batcher.words_done
                    if steps_per_epoch is None
                    else epochs_completed * vocab.train_words_count
                )
                if pc == 1:
                    _checkpoint_tables(
                        engine, obs_run, metrics,
                        os.path.join(checkpoint_dir, ck_name), ck_name,
                        functools.partial(
                            _flip_checkpoint_state, checkpoint_dir,
                            state_path, ck_name,
                            epochs_completed=epochs_completed, step=step,
                            words_done=wd,
                        ),
                    )
                    return
                with obs_run.span("checkpoint_save", ckpt=ck_name):
                    engine.save(os.path.join(checkpoint_dir, ck_name))
                if pc > 1:
                    from jax.experimental import multihost_utils

                    multihost_utils.sync_global_devices(
                        f"glint_w2v_ckpt_{epochs_completed}"
                    )
                if jax.process_index() == 0:
                    _flip_checkpoint_state(
                        checkpoint_dir, state_path, ck_name,
                        epochs_completed=epochs_completed, step=step,
                        words_done=wd,
                    )
                if pc > 1:
                    from jax.experimental import multihost_utils

                    multihost_utils.sync_global_devices(
                        f"glint_w2v_ckpt_done_{epochs_completed}"
                    )

            spc = p.steps_per_call
            twc = vocab.train_words_count
            # Multi-host: steps_per_epoch fixes the dispatch count; groups are
            # the scan-length quantized version of it.
            forced_groups = (
                None if steps_per_epoch is None
                else max(1, -(-steps_per_epoch // spc))
            )

            def _zero_group() -> BatchGroup:
                # Lockstep padding group: exactly spc zero-mask batches
                # (the scan length every host dispatches) so batch
                # stacks, alphas, and PRNG key advancement stay in
                # multi-host lockstep; excluded from metrics (n_real=0).
                B, C = batcher.batch_size, context_width(batcher.window)
                return BatchGroup(
                    centers=np.zeros((spc, B), np.int32),
                    contexts=np.zeros((spc, B, C), np.int32),
                    mask=np.zeros((spc, B, C), np.float32),
                    words_done=[batcher.words_done] * spc,
                    n_real=0,
                )

            def _harvest_host(pend) -> None:
                # Deferred loss sync (ISSUE 5): group g's records and
                # canary check run after group g+1 is dispatched, so the
                # periodic loss sync they force waits on a device that
                # already has the next group queued behind it — the
                # metric/canary view lags the device by exactly one
                # dispatch group. The dispatch schedule itself is
                # untouched (records only), so tables are unaffected.
                losses, wds_l, alphas_l, n_real, step_base = pend
                if not n_real:
                    return
                with metrics.timing("step"), obs_run.span(
                    "readback_harvest", step0=step_base, n=n_real
                ):
                    for i in range(n_real):
                        metrics.record_step(
                            wds_l[i], loss=losses[i], alpha=alphas_l[i]
                        )
                    obs_run.observe_losses(step_base, losses, n_real)
                obs_run.update(
                    step=step_base + n_real,
                    words_done=int(wds_l[n_real - 1]),
                    alpha=float(alphas_l[n_real - 1]),
                )

            def _sched_alpha(idx_in_epoch: int, epoch: int) -> tuple:
                # Deterministic global LR schedule for multi-host lockstep:
                # every process must compute the identical alpha without
                # exchanging its (slightly different) local word counts. The
                # epoch's words are attributed pro-rata over its agreed step
                # count — the same linear anneal as the reference's global
                # wordCount-driven schedule (mllib:405-413), quantized to steps.
                frac = min((idx_in_epoch + 1) / steps_per_epoch, 1.0)
                wd = epoch * twc + frac * twc
                return (
                    max(p.step_size * (1 - wd / total_words), p.step_size * 1e-4),
                    int(wd),
                )

            for epoch in range(start_epoch, p.num_iterations):
                obs_run.update(epoch=epoch)
                # Group-granular producer pipeline: windowing, batch
                # stacking, and tail padding ALL run on a background
                # thread (corpus/batching.group_batches under
                # utils/prefetch, depth 2 dispatch groups), so the
                # training thread's per-group host work collapses to one
                # queue pop + the LR schedule. The pop's wait time is
                # charged to the device_stall_seconds proxy — if the
                # producer falls behind the device, it shows up there.
                it = prefetch(
                    group_batches(batcher.epoch(epoch), spc), depth=2
                )
                g = 0
                pending = None  # previous group's deferred loss records
                while True:
                    if forced_groups is not None and g >= forced_groups:
                        if next(it, None) is not None:
                            raise RuntimeError(
                                "internal error: local shard produced more "
                                "batches than the agreed per-epoch step count"
                            )
                        break
                    faults.fire("worker.step")
                    with metrics.timing("host"), metrics.stall_timing(), \
                            obs_run.span("host_batch", epoch=epoch,
                                         group=g):
                        grp = next(it, None)
                    pad_only = False
                    if grp is None:
                        if forced_groups is None:
                            break
                        # This host's shard is exhausted but other hosts
                        # still have batches — keep dispatching zero-mask
                        # groups up to the agreed count (see _zero_group).
                        grp = _zero_group()
                        pad_only = True
                    n_real = 0 if pad_only else grp.n_real
                    if steps_per_epoch is None:
                        wds = list(grp.words_done)
                        alphas = [
                            max(
                                p.step_size * (1 - wd / total_words),
                                p.step_size * 1e-4,
                            )
                            for wd in wds
                        ]
                    else:
                        sched = [
                            _sched_alpha(g * spc + j, epoch)
                            for j in range(spc)
                        ]
                        alphas = [a for a, _ in sched]
                        wds = [w for _, w in sched]
                    with metrics.timing("step"), obs_run.span(
                        "device_steps", step0=step, n=n_real
                    ):
                        losses = self._train_batches(
                            engine, grp, base_key, step,
                            np.asarray(alphas, np.float32),
                        )
                    new_pend = (losses, wds, alphas, n_real, step)
                    step += spc  # pad/tail steps consumed keys too
                    # Harvest group g-1's records while group g runs
                    # (one-group deferred loss sync, see _harvest_host).
                    if pending is not None:
                        _harvest_host(pending)
                    pending = new_pend
                    g += 1
                if pending is not None:
                    # Epoch-end drain: metrics/canary catch up before the
                    # checkpoint reads words_done.
                    _harvest_host(pending)
                    pending = None
                stopping = (
                    stop_after_epochs is not None
                    and (epoch + 1 - start_epoch) >= stop_after_epochs
                )
                if state_path and (
                    stopping
                    or (epoch + 1) % max(checkpoint_every_epochs, 1) == 0
                ):
                    save_checkpoint(epoch + 1)
                if stopping:
                    logger.info("stopping early after epoch %d", epoch + 1)
                    break
            # Fit-exit barrier for in-flight async checkpoint writes
            # (failed writes surface here loudly; hung writers raise
            # after the bounded wait, GLINT_CKPT_WAIT_TIMEOUT).
            engine.wait_pending_saves(timeout=_ckpt_wait_timeout())
        except TrainingDiverged:
            engine.wait_pending_saves(
                reraise=False, timeout=_ckpt_wait_timeout()
            )
            _save_diverged_snapshot(engine, checkpoint_dir, obs_run)
            raise
        except BaseException:
            engine.wait_pending_saves(
                reraise=False, timeout=_ckpt_wait_timeout()
            )
            obs_run.close(failed=True)
            raise
        finally:
            obs_run.close()
        logger.info("training done: %s", metrics.summary())
        model = self._make_model(vocab, engine)
        model.training_metrics = {**metrics.summary(), "pipeline": "host"}
        steptime = obs_run.steptime_totals()
        if steptime:
            model.training_metrics["steptime"] = steptime
        return model

    # Hooks specialized by subword/other model families (models/fasttext.py).

    def _make_engine(self, mesh, vocab: Vocabulary):
        from glint_word2vec_tpu.parallel.engine import EmbeddingEngine

        p = self.params
        return EmbeddingEngine(
            mesh,
            vocab.size,
            p.vector_size,
            vocab.counts,
            num_negatives=p.num_negatives,
            unigram_power=p.unigram_power,
            unigram_table_size=p.unigram_table_size,
            seed=p.seed,
            dtype=p.dtype,
            shared_negatives=p.shared_negatives,
            compute_dtype=p.compute_dtype,
            layout=p.layout,
        )

    def _train_batches(self, engine, group: BatchGroup, base_key, step0,
                       alphas):
        """Dispatch one pre-stacked :class:`BatchGroup` as one on-device
        scan; returns the per-batch losses (lazy device array). The
        stacking itself happens on the producer thread
        (corpus/batching.group_batches) so this hook is dispatch-only."""
        return engine.train_steps(
            group.centers, group.contexts, group.mask, base_key, alphas,
            step0,
        )

    def _make_model(self, vocab: Vocabulary, engine) -> "Word2VecModel":
        return Word2VecModel(vocab, engine, self.params)


class Word2VecModel:
    """Fitted model: query/serving surface over the sharded matrix."""

    def __init__(self, vocab: Vocabulary, engine, params: Word2VecParams):
        self.vocab = vocab
        self.engine = engine
        self.params = params
        self.training_metrics: Optional[dict] = None

    # ------------------------------------------------------------------
    # transform — the reference's three flavors (SURVEY.md §3.2)
    # ------------------------------------------------------------------

    @property
    def vector_size(self) -> int:
        return self.engine.cols

    def transform(self, word: str) -> np.ndarray:
        """Single word -> vector. Raises KeyError on OOV (mllib:511-519;
        documented there as the slow path — one pull per word)."""
        idx = self.vocab.word_index.get(word)
        if idx is None:
            raise KeyError(f"word {word!r} not in vocabulary")
        return np.asarray(self.engine.pull(np.array([idx], np.int32)))[0]

    def transform_words(self, words: Sequence[str]) -> np.ndarray:
        """Batch of words -> (N, d). Raises on OOV, requests chunked
        MAX_QUERY_ROWS at a time (mllib:529-543)."""
        idx = self.vocab.encode_strict(words)
        out = np.empty((len(idx), self.vector_size), np.float32)
        for s in range(0, len(idx), MAX_QUERY_ROWS):
            out[s : s + MAX_QUERY_ROWS] = np.asarray(
                self.engine.pull(idx[s : s + MAX_QUERY_ROWS])
            )
        return out

    def transform_sentences(
        self, sentences: Iterable[Sequence[str]]
    ) -> np.ndarray:
        """Sentences -> (S, d) mean vectors, computed device-side.

        The DataFrame ``transform`` path (ml:443-459): OOV words silently
        dropped, rows chunked MAX_QUERY_ROWS at a time, empty/all-OOV
        sentences yield zero vectors. Only S*d floats return to host
        (the ``pullAverage`` network-efficiency property)."""
        sents = [self.vocab.encode(s) for s in sentences]
        d = self.vector_size
        out = np.zeros((len(sents), d), np.float32)
        for s in range(0, len(sents), MAX_QUERY_ROWS):
            block = sents[s : s + MAX_QUERY_ROWS]
            L = max((len(x) for x in block), default=0)
            if L == 0:
                continue
            # Rows and max-length pad to power-of-two buckets so repeated
            # serving calls with jittering shapes hit a small compiled
            # family instead of one jit per (S, L). Padding is mask-0:
            # padded rows come back as the zero vector (sliced off) and
            # padded columns add exact +0.0 terms to each masked mean.
            idx = np.zeros((next_pow2(len(block)), next_pow2(L)), np.int32)
            m = np.zeros(idx.shape, np.float32)
            for i, x in enumerate(block):
                idx[i, : len(x)] = x
                m[i, : len(x)] = 1.0
            out[s : s + len(block)] = np.asarray(
                self.engine.pull_average(idx, m)
            )[: len(block)]
        return out

    def transform_packed(self, idx: np.ndarray, mask: np.ndarray) -> np.ndarray:
        """One pre-packed pow2 ``(rows, len)`` block -> ``(rows, d)`` host
        means — the bulk-transform hot path (``glint_word2vec_tpu.batch``).
        The producer owns encoding and padding
        (:func:`corpus.batching.pack_query_block`); this is exactly the
        per-chunk ``pull_average`` dispatch of :meth:`transform_sentences`
        with the packing factored out, so the two paths share the padding
        exactness contract (mask-0 rows -> zero vectors, mask-0 columns
        -> exact +0.0 terms). Subword families override with their
        compose dispatch."""
        return np.asarray(self.engine.pull_average(idx, mask))

    def bulk_warmup(self, rows: int, max_len: int) -> int:
        """Compile the whole program family the bulk transform will
        dispatch — one ``pull_average`` shape per pow2 length bucket up
        to ``next_pow2(max_len)`` at the fixed ``rows`` bucket — before
        the stream starts, so steady state pays zero jit compiles
        (asserted by the pipeline via ``engine.query_compiles``, the
        serving warmup discipline applied to batch inference). Returns
        the number of shapes compiled (0 = already warm)."""
        lens, L = [], 1
        top = next_pow2(max_len)
        while L <= top:
            lens.append(L)
            L *= 2
        return self.engine.warmup(
            q_buckets=(), k_buckets=(),
            sentence_lens=tuple(lens), sentence_rows=(rows,),
        )

    # ------------------------------------------------------------------
    # Similarity / analogy serving (SURVEY.md §3.3)
    # ------------------------------------------------------------------

    def find_synonyms(self, word: str, num: int) -> List[Tuple[str, float]]:
        """Top-``num`` most-similar words, the query word excluded
        (mllib:554-560: fetch num+1 then drop the word itself)."""
        vec = self.transform(word)
        results = self.find_synonyms_vector(vec, num + 1)
        return [(w, s) for w, s in results if w != word][:num]

    def _query_engine(self):
        """Engine whose syn0 answers similarity queries. The word-level
        model queries the training table directly; subword families override
        (FastTextModel composes per-word vectors into a second engine)."""
        return self.engine

    def _decode_hits(self, sims, idx) -> List[Tuple[str, float]]:
        # Non-finite scores are masked filler, never results: the
        # exact path's -inf entries ride padding-row ids (>= vocab
        # size, caught by the index check), but the ANN path's empty
        # member slots carry id 0 — a REAL word — so dropping by score
        # is the only filter that covers both (and a -inf would also
        # serialize as invalid JSON).
        return [
            (self.vocab.words[int(i)], float(s))
            for s, i in zip(sims, idx)
            if int(i) < self.vocab.size and np.isfinite(s)
        ]

    def find_synonyms_vector(
        self, vector: np.ndarray, num: int
    ) -> List[Tuple[str, float]]:
        """Top-``num`` words by cosine similarity to an arbitrary vector
        (mllib:570-629) — distributed matvec + on-device top-k instead of
        the reference's O(vocab) driver-side scan."""
        if num <= 0:
            raise ValueError("num must be > 0")
        num = min(num, self.vocab.size)
        sims, idx = self._query_engine().top_k_cosine(
            np.asarray(vector, np.float32), num
        )
        return self._decode_hits(sims, idx)

    def find_synonyms_batch(
        self, vectors: np.ndarray, num: int, *, approximate: bool = False
    ) -> List[List[Tuple[str, float]]]:
        """Top-``num`` neighbors for a whole (Q, d) query batch in one
        distributed dispatch — the batch form of
        :meth:`find_synonyms_vector` (the reference answers findSynonyms
        for arrays by looping single queries, ml:375-420).
        ``approximate=True`` rides the engine's two-stage coarse index
        (ISSUE 12) instead of the exact masked GEMM — requires an
        adopted index; the serving layer owns the recall gate. A
        ``num`` beyond the index's probe capacity (nprobe x member
        slots — thousands at the default geometry) silently routes to
        the exact path: correctness outranks the speedup there."""
        if num <= 0:
            raise ValueError("num must be > 0")
        num = min(num, self.vocab.size)
        eng = self._query_engine()
        if approximate:
            idx_obj = eng.ann_index
            conf = getattr(eng, "_ann_conf", None) or {}
            cap = (
                conf.get("nprobe", 0) * idx_obj.slots
                if idx_obj is not None else 0
            )
            approximate = num <= cap
        if approximate:
            sims, idx = eng.ann_top_k_batch(
                np.asarray(vectors, np.float32), num
            )
        else:
            sims, idx = eng.top_k_cosine_batch(
                np.asarray(vectors, np.float32), num
            )
        return [self._decode_hits(s, i) for s, i in zip(sims, idx)]

    def analogy(
        self, positive: Sequence[str], negative: Sequence[str], num: int
    ) -> List[Tuple[str, float]]:
        """king - man + woman style queries: sum(positive) - sum(negative),
        query words excluded from results. The reference exposes this as
        caller-side vector arithmetic + findSynonyms
        (ServerSideGlintWord2VecSpec.scala:342-344); provided here as a
        first-class method."""
        vec = np.zeros(self.vector_size, np.float32)
        for w in positive:
            vec += self.transform(w)
        for w in negative:
            vec -= self.transform(w)
        exclude = set(positive) | set(negative)
        res = self.find_synonyms_vector(vec, num + len(exclude))
        return [(w, s) for w, s in res if w not in exclude][:num]

    # ------------------------------------------------------------------
    # Export (SURVEY.md §2 C3 getVectors / toLocal)
    # ------------------------------------------------------------------

    def get_vectors(self) -> Iterator[Tuple[str, np.ndarray]]:
        """Stream (word, vector) pairs, pulled MAX_QUERY_ROWS at a time
        (mllib:638-644 / ml:342-364) — never materializes the full matrix
        on host, killing the reference's 8 GB broadcast ceiling
        (README.md:71-73)."""
        for s in range(0, self.vocab.size, MAX_QUERY_ROWS):
            idx = np.arange(s, min(s + MAX_QUERY_ROWS, self.vocab.size), dtype=np.int32)
            rows = np.asarray(self.engine.pull(idx))
            for i, r in zip(idx, rows):
                yield self.vocab.words[int(i)], r

    def to_local(self) -> "LocalWord2VecModel":
        """Materialize a host-side numpy model (mllib:651-657)."""
        vecs = np.empty((self.vocab.size, self.vector_size), np.float32)
        for s in range(0, self.vocab.size, MAX_QUERY_ROWS):
            idx = np.arange(s, min(s + MAX_QUERY_ROWS, self.vocab.size), dtype=np.int32)
            vecs[s : s + len(idx)] = np.asarray(self.engine.pull(idx))
        return LocalWord2VecModel(list(self.vocab.words), vecs)

    # ------------------------------------------------------------------
    # Persistence / lifecycle (SURVEY.md §3.4)
    # ------------------------------------------------------------------

    def save(self, path: str) -> None:
        """Matrix shards + words list + params metadata (mllib:493-498:
        ``matrix.save`` + the words text file; ml:504-507 params metadata).

        Crash-safe: every file goes through write-temp-then-rename (the
        matrix via the engine's snapshot commit, words/params here), so
        re-saving over an existing model directory can never leave a
        truncated words file or params blob behind."""
        from glint_word2vec_tpu.utils import (
            atomic_write_json,
            atomic_write_text,
        )

        os.makedirs(path, exist_ok=True)
        self.engine.save(os.path.join(path, "matrix"))
        for w in self.vocab.words:
            if "\n" in w or "\r" in w:
                raise ValueError(
                    f"vocab word {w!r} contains a newline and cannot be "
                    "saved to the line-oriented words file"
                )
        atomic_write_text(
            os.path.join(path, "words.txt"),
            "".join(w + "\n" for w in self.vocab.words),
        )
        atomic_write_json(
            os.path.join(path, "params.json"),
            json.loads(self.params.to_json()),
        )

    #: Params class used by :meth:`load`; model families override.
    _PARAMS_CLS = Word2VecParams

    @classmethod
    def load(cls, path: str, mesh=None) -> "Word2VecModel":
        """Rebuild from :meth:`save` output onto any mesh — the analogue of
        loading onto a fresh or *different* PS cluster (mllib:696-725;
        host-override at ml:584-586). With no explicit mesh, the saved
        topology is clamped to the live device count, so a model trained
        on a big mesh loads on a small host. Shared by all model families;
        the family-specific tail lives in :meth:`_from_loaded`."""
        import jax

        from glint_word2vec_tpu.parallel.engine import EmbeddingEngine
        from glint_word2vec_tpu.parallel.mesh import make_mesh

        with open(os.path.join(path, "params.json")) as f:
            try:
                params = cls._PARAMS_CLS.from_json(f.read())
            except TypeError as e:
                # e.g. a params.json from a different model family fed to
                # the wrong loader (use models.load_model to dispatch).
                raise ValueError(
                    f"params.json at {path} does not describe a "
                    f"{cls._PARAMS_CLS.__name__} model: {e}"
                )
        if mesh is None:
            n_dev = len(jax.devices())
            num_model = max(1, min(params.num_shards, n_dev))
            num_data = max(1, min(params.num_partitions, n_dev // num_model))
            mesh = make_mesh(num_data, num_model)
        engine = EmbeddingEngine.load(os.path.join(path, "matrix"), mesh)
        vocab = saved_model_vocabulary(
            path, engine._counts,
            engine.vocab_size + engine.extra_rows_assigned,
        )
        return cls._from_loaded(vocab, engine, params)

    @classmethod
    def _from_loaded(cls, vocab, engine, params) -> "Word2VecModel":
        return cls(vocab, engine, params)

    def stop(self) -> None:
        """Release device memory (reference ``model.stop`` terminating the
        PS client/cluster, mllib:664-667)."""
        self.engine.destroy()


class LocalWord2VecModel:
    """Host-only numpy model — the ``toLocal`` result (mllib:651-657).

    Same query surface, no device required; convertible back by training
    code via ``EmbeddingEngine.set_tables`` if needed.
    """

    def __init__(self, words: List[str], vectors: np.ndarray):
        if vectors.shape[0] != len(words):
            raise ValueError("words/vectors length mismatch")
        self.words = words
        self.vectors = vectors.astype(np.float32)
        self.word_index = {w: i for i, w in enumerate(words)}
        self._norms = np.linalg.norm(self.vectors, axis=1)

    @property
    def vector_size(self) -> int:
        return self.vectors.shape[1]

    def transform(self, word: str) -> np.ndarray:
        idx = self.word_index.get(word)
        if idx is None:
            raise KeyError(f"word {word!r} not in vocabulary")
        return self.vectors[idx]

    def find_synonyms_vector(self, vector, num: int) -> List[Tuple[str, float]]:
        v = np.asarray(vector, np.float32)
        nv = np.linalg.norm(v)
        if nv > 0:
            v = v / nv
        safe = np.where(self._norms > 0, self._norms, 1.0)
        cos = np.where(self._norms > 0, (self.vectors @ v) / safe, 0.0)
        top = np.argsort(-cos)[:num]
        return [(self.words[i], float(cos[i])) for i in top]

    def find_synonyms(self, word: str, num: int) -> List[Tuple[str, float]]:
        res = self.find_synonyms_vector(self.transform(word), num + 1)
        return [(w, s) for w, s in res if w != word][:num]

    def get_vectors(self) -> Dict[str, np.ndarray]:
        return {w: self.vectors[i] for i, w in enumerate(self.words)}

    def save(self, path: str) -> None:
        """Crash-safe: both files land via write-temp-then-rename
        (utils.atomic_write_npy), so overwriting a previous save can
        never leave a truncated ``vectors.npy`` behind."""
        from glint_word2vec_tpu.utils import (
            atomic_write_npy,
            atomic_write_text,
        )

        os.makedirs(path, exist_ok=True)
        atomic_write_npy(os.path.join(path, "vectors.npy"), self.vectors)
        atomic_write_text(
            os.path.join(path, "words.txt"),
            "".join(w + "\n" for w in self.words),
        )

    @classmethod
    def load(cls, path: str) -> "LocalWord2VecModel":
        vectors = np.load(os.path.join(path, "vectors.npy"))
        with open(os.path.join(path, "words.txt"), encoding="utf-8") as f:
            words = [line.rstrip("\n") for line in f if line.rstrip("\n")]
        return cls(words, vectors)
