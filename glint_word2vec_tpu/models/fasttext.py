"""fastText-style subword model family on the same sharded-matrix engine.

Extends the word-level SGNS framework with character-n-gram bucket rows
(BASELINE.json stretch config): the engine's table grows by ``bucket``
extra rows (corpus/subword.py), a center word trains as the mean of its
subword group's rows (``EmbeddingEngine.train_step_grouped``), and word
vectors — including OOV words, which the word-level reference cannot
represent at all — compose on device via ``pull_average``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import numpy as np

from glint_word2vec_tpu.corpus.subword import build_subword_table, subword_group
from glint_word2vec_tpu.corpus.vocab import Vocabulary
from glint_word2vec_tpu.obs import events as obs_events
from glint_word2vec_tpu.models.word2vec import (
    MAX_QUERY_ROWS,
    LocalWord2VecModel,
    Word2Vec,
    Word2VecModel,
)
from glint_word2vec_tpu.utils.params import Word2VecParams, _require


@dataclass
class FastTextParams(Word2VecParams):
    """Word2Vec params + subword geometry (fastText conventions)."""

    min_n: int = 3
    max_n: int = 6
    bucket: int = 2_000_000
    max_subwords: int = 32

    def validate(self) -> None:
        super().validate()
        _require(0 < self.min_n <= self.max_n, "need 0 < min_n <= max_n")
        _require(self.bucket > 0, "bucket must be > 0")
        _require(self.max_subwords >= 2, "max_subwords must be >= 2")


class FastTextWord2Vec(Word2Vec):
    """Subword SGNS estimator. Same fluent surface as Word2Vec, plus
    subword knobs; fit() shares the full word-level training loop
    (LR anneal, metrics, checkpoint/resume) via the family hooks."""

    def __init__(self, params: Optional[FastTextParams] = None, mesh=None, **kw):
        super().__init__(params or FastTextParams(), mesh=mesh, **kw)
        if not isinstance(self.params, FastTextParams):
            raise TypeError("FastTextWord2Vec requires FastTextParams")
        self._sub_ids: Optional[np.ndarray] = None
        self._sub_mask: Optional[np.ndarray] = None

    def set_min_n(self, v: int) -> "FastTextWord2Vec":
        return self._set(min_n=v)

    def set_max_n(self, v: int) -> "FastTextWord2Vec":
        return self._set(max_n=v)

    def set_bucket(self, v: int) -> "FastTextWord2Vec":
        return self._set(bucket=v)

    def set_max_subwords(self, v: int) -> "FastTextWord2Vec":
        return self._set(max_subwords=v)

    # Family hooks -----------------------------------------------------

    def _device_corpus_eligible(self, corpus_words: int = 0) -> bool:
        # Subword centers need the host-side group expansion
        # (_train_batches below); the device corpus batcher assembles
        # word-level centers only.
        return False

    def _make_engine(self, mesh, vocab: Vocabulary):
        from glint_word2vec_tpu.parallel.engine import EmbeddingEngine

        p = self.params
        self._sub_ids, self._sub_mask = build_subword_table(
            vocab.words, vocab.size, p.bucket, p.min_n, p.max_n, p.max_subwords
        )
        return EmbeddingEngine(
            mesh,
            vocab.size,
            p.vector_size,
            vocab.counts,
            num_negatives=p.num_negatives,
            unigram_power=p.unigram_power,
            unigram_table_size=p.unigram_table_size,
            seed=p.seed,
            dtype=p.dtype,
            extra_rows=p.bucket,
            shared_negatives=p.shared_negatives,
            compute_dtype=p.compute_dtype,
            layout=p.layout,
        )

    def _train_batches(self, engine, group, base_key, step0, alphas):
        # Host-side expansion of center words to their subword groups;
        # padded batch rows (center 0) carry zero context masks, so their
        # group updates are zeroed by the gradient coefficients. The
        # expansion is this family's extra host-side phase, so it gets
        # its own span inside the fit loop's device_steps window. The
        # batch stacking itself already happened on the producer thread
        # (the group arrives as a pre-stacked BatchGroup).
        with obs_events.span("subword_expand", step0=step0):
            groups = self._sub_ids[group.centers]
            gmask = self._sub_mask[group.centers]
        return engine.train_steps_grouped(
            groups,
            gmask,
            group.contexts,
            group.mask,
            base_key,
            alphas,
            step0,
        )

    def _make_model(self, vocab: Vocabulary, engine) -> "FastTextModel":
        return FastTextModel(
            vocab, engine, self.params, self._sub_ids, self._sub_mask
        )


class FastTextModel(Word2VecModel):
    """Fitted subword model: all word vectors (in-vocab AND out-of-vocab)
    compose on device as the mean of subword rows."""

    def __init__(self, vocab, engine, params: FastTextParams, sub_ids, sub_mask):
        super().__init__(vocab, engine, params)
        self._sub_ids = sub_ids
        self._sub_mask = sub_mask

    # -- composition ---------------------------------------------------

    #: Fixed row-block size for composition calls: bounds XLA to at most
    #: two compiled shapes (full block + final remainder) regardless of
    #: input sizes.
    COMPOSE_BLOCK = 4096

    def _compose_device(self, groups: np.ndarray, gmask: np.ndarray):
        """Compose one block on device; returns a device array."""
        return self.engine.pull_average(groups, gmask)

    def _compose(self, groups: np.ndarray, gmask: np.ndarray) -> np.ndarray:
        """Compose arbitrarily many rows, block-quantized to COMPOSE_BLOCK
        (padded with row 0 / zero mask, sliced off after) so repeated calls
        never trigger per-shape recompiles."""
        n = groups.shape[0]
        B = self.COMPOSE_BLOCK
        out = np.empty((n, self.vector_size), np.float32)
        for s in range(0, n, B):
            e = min(s + B, n)
            g, m = groups[s:e], gmask[s:e]
            if e - s < B:
                pad = B - (e - s)
                g = np.pad(g, ((0, pad), (0, 0)))
                m = np.pad(m, ((0, pad), (0, 0)))
            out[s:e] = np.asarray(self._compose_device(g, m))[: e - s]
        return out

    def _oov_group(self, word: str) -> Tuple[np.ndarray, np.ndarray]:
        p: FastTextParams = self.params
        ids = subword_group(
            word, None, self.vocab.size, p.bucket, p.min_n, p.max_n,
            p.max_subwords,
        )
        if not ids:
            raise KeyError(
                f"word {word!r} is OOV and too short for any "
                f"[{p.min_n},{p.max_n}]-gram"
            )
        g = np.zeros((1, p.max_subwords), np.int32)
        m = np.zeros((1, p.max_subwords), np.float32)
        g[0, : len(ids)] = ids
        m[0, : len(ids)] = 1.0
        return g, m

    def transform(self, word: str) -> np.ndarray:
        """Word -> composed vector. Unlike the word-level model, OOV words
        are representable (fastText's defining capability)."""
        idx = self.vocab.word_index.get(word)
        if idx is not None:
            g, m = self._sub_ids[idx : idx + 1], self._sub_mask[idx : idx + 1]
        else:
            g, m = self._oov_group(word)
        return self._compose(g, m)[0]

    def transform_words(self, words: Sequence[str]) -> np.ndarray:
        out = np.empty((len(words), self.vector_size), np.float32)
        for s in range(0, len(words), MAX_QUERY_ROWS):
            chunk = words[s : s + MAX_QUERY_ROWS]
            idx = self.vocab.encode_strict(chunk)  # strict, like word-level
            out[s : s + len(chunk)] = self._compose(
                self._sub_ids[idx], self._sub_mask[idx]
            )
        return out

    def transform_sentences(self, sentences) -> np.ndarray:
        """Mean of composed word vectors per sentence (OOV words dropped,
        matching the word-level DataFrame-transform semantics).

        All chunk words are composed in fixed-size device blocks (one or
        two compiled shapes total), then segment-averaged on host — no
        per-sentence device calls."""
        sentences = list(sentences)
        out = np.zeros((len(sentences), self.vector_size), np.float32)
        encoded = [self.vocab.encode(s) for s in sentences]
        flat = (
            np.concatenate([e for e in encoded if e.size])
            if any(e.size for e in encoded)
            else np.zeros(0, np.int32)
        )
        if flat.size == 0:
            return out
        vecs = self._compose(self._sub_ids[flat], self._sub_mask[flat])
        pos = 0
        for i, e in enumerate(encoded):
            if e.size:
                out[i] = vecs[pos : pos + e.size].mean(axis=0)
                pos += e.size
        return out

    def transform_packed(self, idx: np.ndarray, mask: np.ndarray) -> np.ndarray:
        """Bulk-transform hook on the subword-compose path: the packed
        word-id block is flattened back to its real tokens (row-major, so
        the flat order matches :meth:`transform_sentences`' concatenation)
        and composed in the usual fixed COMPOSE_BLOCK device blocks, then
        segment-averaged on host. Row results are independent of how the
        producer batched the stream — each composed word vector is a
        within-row reduction — so resume/bitwise guarantees carry over."""
        rows = idx.shape[0]
        out = np.zeros((rows, self.vector_size), np.float32)
        lens = mask.astype(bool).sum(axis=1)
        flat = idx[mask > 0.0].astype(np.int32)
        if flat.size == 0:
            return out
        vecs = self._compose(self._sub_ids[flat], self._sub_mask[flat])
        pos = 0
        for i in range(rows):
            n = int(lens[i])
            if n:
                out[i] = vecs[pos : pos + n].mean(axis=0)
                pos += n
        return out

    def bulk_warmup(self, rows: int, max_len: int) -> int:
        """The compose path dispatches only ``(COMPOSE_BLOCK,
        max_subwords)`` pull-average blocks regardless of the producer's
        packing (``_compose`` pads every partial block), so ONE shape
        warms the whole stream — the producer's (rows, len) geometry
        never reaches the device here."""
        before = self.engine.query_compiles
        g = np.zeros(
            (self.COMPOSE_BLOCK, self.params.max_subwords), np.int32
        )
        np.asarray(self._compose_device(g, np.zeros(g.shape, np.float32)))
        return self.engine.query_compiles - before

    # -- similarity over composed vectors ------------------------------

    def _query_engine(self):
        """A second sharded engine whose syn0 holds the composed per-word
        vectors, assembled entirely on device (compose block ->
        ``write_rows``; nothing of O(vocab x dim) ever touches the host).
        Built lazily, cached; similarity queries then reuse the standard
        distributed top-k."""
        if getattr(self, "_qeng", None) is None:
            from glint_word2vec_tpu.parallel.engine import EmbeddingEngine

            with obs_events.span(
                "compose_query_engine", vocab=self.vocab.size
            ):
                qeng = EmbeddingEngine(
                    self.engine.mesh,
                    self.vocab.size,
                    self.vector_size,
                    self.vocab.counts,
                    num_negatives=self.engine.num_negatives,
                    seed=0,
                )
                B = self.COMPOSE_BLOCK
                for s in range(0, self.vocab.size, B):
                    e = min(s + B, self.vocab.size)
                    block = self._compose_device(
                        self._sub_ids[s:e], self._sub_mask[s:e]
                    )
                    qeng.write_rows(s, block)
            self._qeng = qeng
        return self._qeng

    def to_local(self) -> LocalWord2VecModel:
        qeng = self._query_engine()
        vecs = np.empty((self.vocab.size, self.vector_size), np.float32)
        for s in range(0, self.vocab.size, MAX_QUERY_ROWS):
            idx = np.arange(s, min(s + MAX_QUERY_ROWS, self.vocab.size), dtype=np.int32)
            vecs[s : s + len(idx)] = np.asarray(qeng.pull(idx))
        return LocalWord2VecModel(list(self.vocab.words), vecs)

    def get_vectors(self):
        qeng = self._query_engine()
        for s in range(0, self.vocab.size, MAX_QUERY_ROWS):
            idx = np.arange(s, min(s + MAX_QUERY_ROWS, self.vocab.size), dtype=np.int32)
            rows = np.asarray(qeng.pull(idx))
            for i, r in zip(idx, rows):
                yield self.vocab.words[int(i)], r

    def stop(self) -> None:
        if getattr(self, "_qeng", None) is not None:
            self._qeng.destroy()
            self._qeng = None
        super().stop()

    # -- persistence ---------------------------------------------------
    # save() is inherited: engine.save persists bucket rows via extra_rows
    # and params.json carries the subword geometry. load() shares the base
    # path via the hooks below; the subword table is rebuilt
    # deterministically from the words + geometry.

    _PARAMS_CLS = FastTextParams

    @classmethod
    def _from_loaded(cls, vocab, engine, params) -> "FastTextModel":
        sub_ids, sub_mask = build_subword_table(
            vocab.words, vocab.size, params.bucket, params.min_n,
            params.max_n, params.max_subwords,
        )
        return cls(vocab, engine, params, sub_ids, sub_mask)
