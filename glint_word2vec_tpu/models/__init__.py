"""Model layer: the Word2Vec estimator and fitted Word2VecModel."""
