"""Model layer: the Word2Vec estimator and fitted Word2VecModel."""

import json
import os


def load_model(path: str, mesh=None):
    """Load a saved model of ANY family, dispatching on its params.json.

    The analogue of the reference's single load entry point
    (``ServerSideGlintWord2VecModel.load``, mllib:671-726): the caller names
    a directory; the family is recovered from the persisted metadata.
    FastText metadata carries the subword-geometry keys (``bucket`` et al.,
    models/fasttext.py FastTextParams); plain word2vec metadata does not.
    """
    params_path = os.path.join(path, "params.json")
    try:
        with open(params_path) as f:
            meta = json.load(f)
    except FileNotFoundError:
        raise FileNotFoundError(f"no model at {path!r} (missing params.json)")
    except OSError as e:
        raise ValueError(f"cannot read model metadata at {params_path}: {e}")
    except json.JSONDecodeError as e:
        raise ValueError(f"corrupt model metadata at {params_path}: {e}")
    if "bucket" in meta:
        from glint_word2vec_tpu.models.fasttext import FastTextModel

        return FastTextModel.load(path, mesh=mesh)
    from glint_word2vec_tpu.models.word2vec import Word2VecModel

    return Word2VecModel.load(path, mesh=mesh)
