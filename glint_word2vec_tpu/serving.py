"""Persistent model serving: the separate-PS-cluster deployment, restated.

The reference's second deployment topology keeps a Glint parameter-server
cluster alive independently of any one training/serving app
(README.md:45-57: `glint.Main` launched standalone; trainers and
transformers connect by host and come and go; the cluster survives
`model.stop()` unless a client passes ``terminateOtherClients=true``,
mllib:664-667). The TPU-native restatement: the model lives in one serving
process's device memory, exposed over HTTP; client apps (trainers, batch
jobs, notebooks) query it without loading the tables themselves, and their
lifecycles don't affect it.

Endpoints (JSON in/out, stdlib-only server):

  GET  /healthz            -> {"status": "ok", "vocab_size": V, "dim": d, ...}
  POST /synonyms           {"word": w, "num": k}
  POST /synonyms_vector    {"vector": [...], "num": k}
  POST /analogy            {"positive": [...], "negative": [...], "num": k}
  POST /vector             {"word": w}            (strict OOV -> 404)
  POST /transform          {"sentences": [[w, ...], ...]}  (OOV dropped)
  POST /shutdown           stops the server (the terminateOtherClients
                           analogue: an explicit, remote, cross-client kill)

Start from the CLI:  glint-word2vec-tpu serve --model DIR --port 8801
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)


class ModelServer:
    """Holds one loaded model and serves its query surface over HTTP."""

    def __init__(self, model, host: str = "127.0.0.1", port: int = 8801):
        self.model = model
        # Device queries are jitted functions on shared tables; serialize
        # them (the reference's PS likewise processes a shard's requests
        # on its actor mailbox, one at a time).
        self._lock = threading.Lock()
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route to logging, not stderr
                logger.debug("serve: " + fmt, *args)

            def _send(self, code: int, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    m = server.model
                    self._send(
                        200,
                        {
                            "status": "ok",
                            "family": type(m).__name__,
                            "vocab_size": m.vocab.size,
                            "dim": m.vector_size,
                        },
                    )
                else:
                    self._send(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                except (ValueError, json.JSONDecodeError) as e:
                    return self._send(400, {"error": f"bad request: {e}"})
                try:
                    with server._lock:
                        out = server._dispatch(self.path, req)
                except KeyError as e:
                    return self._send(
                        404, {"error": e.args[0] if e.args else str(e)}
                    )
                except ValueError as e:
                    return self._send(400, {"error": str(e)})
                if out is None:
                    return self._send(404, {"error": f"no route {self.path}"})
                self._send(200, out)
                if self.path == "/shutdown":
                    threading.Thread(target=server.stop, daemon=True).start()

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    # -- request dispatch ---------------------------------------------

    def _dispatch(self, path: str, req: dict):
        m = self.model
        if path == "/synonyms":
            return [
                [w, float(s)]
                for w, s in m.find_synonyms(req["word"], int(req.get("num", 10)))
            ]
        if path == "/synonyms_vector":
            vec = np.asarray(req["vector"], np.float32)
            return [
                [w, float(s)]
                for w, s in m.find_synonyms_vector(vec, int(req.get("num", 10)))
            ]
        if path == "/analogy":
            return [
                [w, float(s)]
                for w, s in m.analogy(
                    req.get("positive", []),
                    req.get("negative", []),
                    int(req.get("num", 10)),
                )
            ]
        if path == "/vector":
            return [float(x) for x in m.transform(req["word"])]
        if path == "/transform":
            vecs = m.transform_sentences(req["sentences"])
            return [[float(x) for x in v] for v in np.asarray(vecs)]
        if path == "/shutdown":
            return {"status": "shutting down"}
        return None

    # -- lifecycle -----------------------------------------------------

    def serve_forever(self) -> None:
        logger.info("serving model on %s:%d", self.host, self.port)
        self._httpd.serve_forever()

    def start_background(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def serve_model_dir(
    model_dir: str, host: str = "127.0.0.1", port: int = 8801
) -> None:
    """Load a saved model (any family) and serve it until killed."""
    from glint_word2vec_tpu import load_model

    server = ModelServer(load_model(model_dir), host=host, port=port)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.stop()
