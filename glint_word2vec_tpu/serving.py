"""Persistent model serving: the separate-PS-cluster deployment, restated.

The reference's second deployment topology keeps a Glint parameter-server
cluster alive independently of any one training/serving app
(README.md:45-57: `glint.Main` launched standalone; trainers and
transformers connect by host and come and go; the cluster survives
`model.stop()` unless a client passes ``terminateOtherClients=true``,
mllib:664-667). The TPU-native restatement: the model lives in one serving
process's device memory, exposed over HTTP; client apps (trainers, batch
jobs, notebooks) query it without loading the tables themselves, and their
lifecycles don't affect it.

Endpoints (JSON in/out, stdlib-only server):

  GET  /healthz            -> {"status": "ok", "vocab_size": V, "dim": d,
                               "compiles": n, "post_warmup_compiles": n, ...}
  GET  /metrics            per-endpoint latency histograms (p50/p95/p99),
                           coalesced-batch-size distribution, compile counts
  GET  /metrics?format=prometheus
                           the same snapshot as Prometheus text exposition
                           (scrape-ready; JSON stays the default)
  POST /synonyms           {"word": w, "num": k}
  POST /synonyms_vector    {"vector": [...], "num": k}
  POST /analogy            {"positive": [...], "negative": [...], "num": k}
  POST /vector             {"word": w}            (strict OOV -> 404)
  POST /transform          {"sentences": [[w, ...], ...]}  (OOV dropped)
  POST /shutdown           stops the server (the terminateOtherClients
                           analogue: an explicit, remote, cross-client kill)
  POST /reload             hot-swap the served tables to a published
                           generation: {"dir": GEN_DIR} loads that
                           directory; {} polls the --watch-checkpoint
                           publish dir immediately

Hot-swap (ISSUE 10): a :class:`SnapshotWatcher` polls a streaming
trainer's publish directory (``LATEST.json``, streaming/publish.py) and
flips each new generation into the live engine. Staging — disk reads,
integrity verification, building the re-sharded device arrays — runs
entirely OFF the request path (``EmbeddingEngine.stage_tables``); the
flip itself (``adopt_tables`` + the vocabulary swap) happens under the
device lock, so every in-flight dispatch drains against the tables it
started with and no response ever mixes generations. The flip ticks
``table_version``, emptying the synonym result cache wholesale, and the
swapped tables have the same shapes as the old ones, so every warmed
compiled program is reused — zero post-warmup compiles across swaps.

Every device dispatch on the hot path belongs to a small, pre-warmed
shape family: coalesced batches pad to power-of-two Q buckets (capped at
``max_batch``), top-k requests round up to k buckets
(engine.TOPK_MIN_K_BUCKET), the coalesced word pull chunks at
``MAX_QUERY_ROWS`` exactly like ``transform_words``, and ``ModelServer``
compiles the whole family BEFORE binding the port — so the first real
request (and every later one inside the family) never pays a jit compile.

Overload protection (ISSUE 7): device-touching requests are bounded by
an admission high-water mark (shed with 429 + ``Retry-After`` past
``max_inflight``), carry a per-request deadline answered with 504
instead of occupying a dispatch slot, and while the device lock is held
past ``degraded_after`` the server runs a degraded cache-only mode —
cache hits served, misses shed with 429. Shed/deadline/degraded
counters are on ``/metrics`` in both renderers.

Multi-model serving (ISSUE 20): one process hosts N models behind one
port through a :class:`ModelCatalog`. Every endpoint takes a model id
— a ``/m/<id>/`` path prefix or the ``X-Glint-Model`` header; neither
routes to the default model, so every pre-catalog client keeps
working unchanged. Each entry owns its result cache, metrics (+SLO
engine), and publish watcher; the compiled program family is
process-level and shape-keyed (parallel/engine ``_QUERY_MEMO``), so a
same-(V, d) second model warms with ZERO new XLA compiles. With
``--model-memory-budget`` set, cold models LRU stage-out to their
committed host snapshots (``release_tables``) and stage back in
through ``stage_tables`` off the request path on first miss —
requests to a staging model queue behind the bounded stage-in and are
answered from the new tables, never a 5xx.

Start from the CLI:  glint-word2vec-tpu serve --model DIR --port 8801
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import sys
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

from glint_word2vec_tpu.obs import events as obs_events
from glint_word2vec_tpu.obs.prometheus import serving_to_prometheus
from glint_word2vec_tpu.obs.slo import (
    FlightRecorder,
    ShedBurstDetector,
    SloEngine,
)
from glint_word2vec_tpu.utils import faults, next_pow2
from glint_word2vec_tpu.utils.metrics import ServingMetrics

logger = logging.getLogger(__name__)

#: Endpoints whose requests touch the device (or wait on the device
#: lock) — the population the admission bound, per-request deadlines,
#: and degraded mode govern. /healthz, /metrics, /shutdown stay exempt:
#: an overloaded server must still be probeable and stoppable.
_DEVICE_PATHS = frozenset(
    ("/synonyms", "/synonyms_vector", "/analogy", "/vector", "/transform")
)

#: Model id every request without an explicit id routes to — the whole
#: pre-catalog single-model surface (clients, fleet probes, CI smokes)
#: keeps working unchanged against it.
DEFAULT_MODEL_ID = "default"


def split_model_path(path: str, header: Optional[str] = None):
    """Resolve ``(model_id, endpoint_path)`` for one request (ISSUE 20).

    A ``/m/<id>/<endpoint>`` path prefix wins; otherwise the
    ``X-Glint-Model`` header names the model; otherwise ``model_id`` is
    None (the default model). The returned endpoint path is what
    routing, metrics keys, and the admission population see — so
    ``/m/a/synonyms`` and a header-addressed ``/synonyms`` land in the
    same per-model histogram bucket."""
    if path.startswith("/m/"):
        sep = path.find("/", 3)
        if sep < 0:
            return (path[3:] or None), "/"
        return (path[3:sep] or None), (path[sep:] or "/")
    return (header or None), path


def parse_memory_budget(value) -> Optional[int]:
    """``--model-memory-budget`` parser: plain bytes, or a
    kb/mb/gb-suffixed size ("512mb", "1.5gb"). None/empty/0 disables
    the budget (every model stays resident)."""
    if value is None:
        return None
    if isinstance(value, (int, float)):
        n = int(value)
        return n if n > 0 else None
    s = str(value).strip().lower()
    if not s:
        return None
    mult = 1
    for suffix, m in (
        ("kb", 1 << 10), ("mb", 1 << 20), ("gb", 1 << 30),
        ("k", 1 << 10), ("m", 1 << 20), ("g", 1 << 30), ("b", 1),
    ):
        if s.endswith(suffix):
            s = s[: -len(suffix)]
            mult = m
            break
    n = int(float(s) * mult)
    return n if n > 0 else None


class DeadlineExceeded(Exception):
    """A request's deadline passed before (or while) it could reach the
    device — answered 504 so the client's own timeout budget, not the
    server's queue depth, bounds its wait."""


class _TrackedLock:
    """``threading.Lock`` that remembers when it was acquired, so the
    overload layer can observe "the device has been busy for X seconds"
    without instrumenting every dispatch site. API-compatible with the
    plain lock for ``with`` use; ``acquire`` grows a timeout."""

    __slots__ = ("_lock", "_held_since")

    def __init__(self):
        self._lock = threading.Lock()
        self._held_since: Optional[float] = None

    def acquire(self, timeout: Optional[float] = None) -> bool:
        if timeout is None:
            ok = self._lock.acquire()
        else:
            ok = self._lock.acquire(timeout=max(0.0, timeout))
        if ok:
            self._held_since = time.monotonic()
        return ok

    def release(self) -> None:
        self._held_since = None
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def held_for(self) -> float:
        """Seconds the lock has been continuously held; 0.0 when free.
        Reads a single attribute — safe (and deliberately lock-free)
        from any thread; a racing release just reads as 0.0."""
        hs = self._held_since
        return 0.0 if hs is None else time.monotonic() - hs


def _pull_coalesced(engine, idx: np.ndarray) -> np.ndarray:
    """Pull word rows for a coalesced batch through the same
    ``MAX_QUERY_ROWS`` chunking ``transform_words`` uses (the coalescer
    used to bypass it entirely — an unbounded HBM spike under a giant
    burst, ADVICE.md round 5), with each chunk padded to its
    power-of-two bucket (row-0 padding, sliced off) so concurrency
    jitter never compiles a fresh pull shape."""
    from glint_word2vec_tpu.models import word2vec as _w2v

    out = np.empty((idx.shape[0], engine.dim), np.float32)
    mqr = _w2v.MAX_QUERY_ROWS
    for s in range(0, idx.shape[0], mqr):
        sub = idx[s : s + mqr]
        n = sub.shape[0]
        n_b = next_pow2(n)
        if n_b != n:
            sub = np.concatenate([sub, np.zeros(n_b - n, np.int32)])
        out[s : s + n] = np.asarray(engine.pull(sub), np.float32)[:n]
    return out


class _SynonymCoalescer:
    """Leader-elected micro-batching for the synonym endpoints.

    Device queries are serialized by the server lock, so under N
    concurrent clients each /synonyms request used to wait for N-1
    single-query dispatches (QPS flat in N). Here every waiting request
    lands in a pending list; whichever thread next wins the device lock
    becomes leader, drains the list, answers ALL of them with ONE
    ``engine.pull`` + ONE ``find_synonyms_batch`` dispatch per
    ``max_batch`` chunk (the batch top-k the reference lacks — it loops
    findSynonyms, ml:375-420), and wakes the waiters. Exclusion
    semantics match find_synonyms exactly (fetch num+1, drop the query
    word, truncate). Dispatches are shape-bucketed: the engine pads Q to
    powers of two and rounds k up to its bucket, so every chunk reuses a
    pre-warmed compiled program.

    Only the base word-level family batches: a subclass overriding
    ``find_synonyms``/``find_synonyms_vector``/``transform`` (FastText
    serves OOV words through subwords) keeps its own semantics via the
    single-query path.
    """

    def __init__(self, model, device_lock, max_batch: int = 64,
                 metrics: Optional[ServingMetrics] = None,
                 cache_size: int = 65536):
        from glint_word2vec_tpu.models.word2vec import Word2VecModel

        self.model = model
        self.device_lock = device_lock
        #: Device-dispatch cap: a drained pending list larger than this
        #: is served in max_batch-sized chunks. Rounded up to a power of
        #: two so chunk shapes coincide with the warmed Q buckets.
        self.max_batch = next_pow2(max(1, int(max_batch)))
        self.metrics = metrics
        self._mu = threading.Lock()
        self._pending: list = []
        #: Straggler-consolidation grace (seconds). When a drained batch
        #: already shows concurrency (>= 2 waiters), the leader briefly
        #: sleeps — releasing the GIL so handler threads mid-read can
        #: enqueue — and re-drains before dispatching. Under a closed
        #: loop of N clients the round otherwise fragments: the leader
        #: catches the first few arrivals and each straggler serializes
        #: a full extra device round behind it (a ~2.7x p95/p50 gap at
        #: 16 clients, SERVING_BENCH). A few ms of grace is noise next
        #: to the batched dispatch it merges into; batches of 1 (the
        #: low-concurrency path) never pay it.
        self.batch_grace = 0.002
        #: Bounded (word, num) -> result cache for the base word family.
        #: Synonym traffic over a vocabulary is zipfian, so a hot set a
        #: tiny fraction of vocab_size absorbs most of the load without
        #: a device dispatch; entries are validated against the engine's
        #: ``table_version`` so any table mutation (a training step, a
        #: push, set_tables) empties it wholesale. FIFO eviction at
        #: ``cache_size`` entries (0 disables). Word queries only — the
        #: raw-vector endpoint has no hashable hot key.
        self.cache_size = max(0, int(cache_size))
        self._cache: dict = {}
        self._cache_version = None
        #: Mode supplier installed by the server once the ANN index is
        #: built and gated: True = default requests ride the
        #: approximate path. Requests carrying ``exact=true`` always
        #: take the exact path (the escape hatch); cache keys carry
        #: the mode so the two paths never serve each other's results.
        self.ann_active = lambda: False
        #: nprobe the server resolved for the approximate path (for
        #: the probes/query accounting).
        self.ann_nprobe = 0
        #: True while an index EXISTS but the recall gate is holding
        #: the approximate path back — those exact serves are counted
        #: as gate fallbacks, not user-requested ones.
        self.gate_failing = lambda: False
        self.can_batch = (
            isinstance(model, Word2VecModel)
            and type(model).find_synonyms is Word2VecModel.find_synonyms
            # A family overriding only the vector endpoint must not be
            # silently served base batched top-k (ADVICE.md round 5).
            and type(model).find_synonyms_vector
            is Word2VecModel.find_synonyms_vector
            and type(model).transform is Word2VecModel.transform
        )

    def _acquire_device(self, deadline: Optional[float]) -> bool:
        """Take the device lock, bounded by the request deadline: a
        request that cannot reach the device in time must answer 504
        WITHOUT ever occupying a dispatch slot."""
        if deadline is None:
            return self.device_lock.acquire()
        return self.device_lock.acquire(
            timeout=deadline - time.monotonic()
        )

    def cache_lookup(self, word, num, exact: bool = False):
        """Result-cache probe with NO device work — the degraded
        cache-only mode's read path. Returns the cached hit list or
        None; never blocks on the device lock."""
        if word is None or not self.cache_size:
            return None
        mode = "exact" if (exact or not self.ann_active()) else "ann"
        with self._mu:
            self._cache_sync_locked()
            return self._cache.get((word, int(num), mode))

    def query(self, word=None, vector=None, num: int = 10,
              deadline: Optional[float] = None, exact: bool = False,
              trace=None):
        tr = trace if trace is not None else obs_events.NULL_TRACE
        if not self.can_batch:
            # Overriding families define their own semantics end to end
            # (FastText OOV-by-subwords, its own num validation).
            with tr.phase("req.queue"):
                acquired = self._acquire_device(deadline)
            if not acquired:
                raise DeadlineExceeded("deadline waiting for device")
            try:
                with tr.phase("req.query", mode="exact"):
                    if word is not None:
                        return self.model.find_synonyms(word, num)
                    return self.model.find_synonyms_vector(vector, num)
            finally:
                self.device_lock.release()
        if num <= 0:
            # Exact single-query behavior for the base family.
            # find_synonyms(w, num): transform(w) runs FIRST (OOV ->
            # KeyError -> 404), then find_synonyms_vector(vec, num+1)
            # raises unless num+1 > 0 — so num=0 with a known word is []
            # (truncation) and num<0 is a 400. The bare vector endpoint
            # always raises on num<=0.
            if word is not None:
                if word not in self.model.vocab.word_index:
                    raise KeyError(f"word {word!r} not in vocabulary")
                if num == 0:
                    return []
            raise ValueError("num must be > 0")
        # Mode resolves ONCE at enqueue (not at dispatch): a gate flip
        # mid-wait must not hand a request a mode its cache key and
        # accounting never saw.
        mode = "exact" if (exact or not self.ann_active()) else "ann"
        if word is not None and self.cache_size:
            with self._mu:
                self._cache_sync_locked()
                hit = self._cache.get((word, num, mode))
            if self.metrics is not None:
                self.metrics.record_cache(hit is not None)
            if hit is not None:
                return hit
        req = {
            "word": word, "vector": vector, "num": int(num),
            "event": threading.Event(), "result": None, "error": None,
            "deadline": deadline, "abandoned": False,
            "mode": mode, "exact_requested": bool(exact),
            # Tracing (ISSUE 18): the leader stamps dispatch-window
            # perf_counter() pairs onto the dict; THIS waiter thread
            # converts them into queue/query/readback phases below.
            "trace": tr.trace_id if trace is not None else None,
            "t_enq": time.perf_counter(),
        }
        with self._mu:
            self._pending.append(req)
        # Leaders set every batched event BEFORE releasing the device
        # lock, so a waiter whose result is already in hand must not
        # queue behind the next leader's whole dispatch (lock convoy —
        # it showed up as a 7x p95 inflation at 16 clients).
        if not req["event"].is_set():
            if self._acquire_device(deadline):
                try:
                    if not req["event"].is_set():
                        with self._mu:
                            batch, self._pending = self._pending, []
                        if len(batch) > 1 and self.batch_grace > 0:
                            # Concurrency detected: absorb stragglers
                            # until one quiet grace window (or the chunk
                            # cap) so the whole round rides one bucketed
                            # dispatch. A request missing the drain
                            # costs a FULL extra device round; the
                            # worst-case grace (16ms) is well under one.
                            for _ in range(8):
                                n0 = len(batch)
                                time.sleep(self.batch_grace)
                                with self._mu:
                                    if self._pending:
                                        batch += self._pending
                                        self._pending = []
                                if (len(batch) == n0
                                        or len(batch) >= self.max_batch):
                                    break
                        if batch:
                            self._process(batch)
                finally:
                    self.device_lock.release()
        if deadline is None:
            req["event"].wait()
        elif not req["event"].wait(deadline - time.monotonic()):
            # Timed out waiting for a leader. Mark the request abandoned
            # AND pull it out of the pending list under the lock, so the
            # list cannot grow without bound while the device is wedged
            # (no future leader may ever drain it) and a future leader
            # that does run spends no dispatch work on a client that
            # already got its 504. If the result landed in the race,
            # serve it.
            with self._mu:
                if not req["event"].is_set():
                    req["abandoned"] = True
                    try:
                        self._pending.remove(req)
                    except ValueError:
                        pass  # a leader already drained it
            if req["abandoned"]:
                raise DeadlineExceeded("deadline waiting for dispatch")
            req["event"].wait()
        if req.get("t_dis0") is not None:
            # Leader-stamped dispatch window -> this request's phases:
            # queue wait (enqueue to leader drain), the device query
            # window, and the host materialization tail.
            tr.add_phase("req.queue", req["t_enq"],
                         req["t_dis0"] - req["t_enq"])
            tr.add_phase("req.query", req["t_dis0"],
                         req["t_dis1"] - req["t_dis0"], mode=mode)
            tr.add_phase("req.readback", req["t_dis1"],
                         req["t_rb1"] - req["t_dis1"])
        if req["error"] is not None:
            raise req["error"]
        return req["result"]

    def _cache_sync_locked(self) -> int:
        """Drop every cached result if the tables moved since they were
        computed; returns the version the cache is now valid for.
        Caller holds ``self._mu``."""
        ver = self.model.engine.table_version
        if ver != self._cache_version:
            self._cache.clear()
            self._cache_version = ver
        return ver

    def _process(self, batch) -> None:
        m = self.model
        live = []
        now = time.monotonic()
        for r in batch:
            # Dead requests first: an abandoned waiter already answered
            # 504, and one whose deadline passed while queued must not
            # consume dispatch work either — its waiter raises
            # DeadlineExceeded from the recorded error.
            if r.get("abandoned"):
                r["event"].set()
                continue
            dl = r.get("deadline")
            if dl is not None and now > dl:
                r["error"] = DeadlineExceeded(
                    "deadline exceeded before dispatch"
                )
                r["event"].set()
                continue
            # Validation failures must fail ONLY their own request: an
            # exception escaping here would strand every co-batched
            # waiter on an event that never fires.
            try:
                if r["word"] is not None:
                    i = m.vocab.word_index.get(r["word"])
                    if i is None:
                        raise KeyError(
                            f"word {r['word']!r} not in vocabulary"
                        )
                    r["idx"] = i
                else:
                    v = np.asarray(r["vector"], dtype=np.float32)
                    if v.shape != (m.vector_size,):
                        raise ValueError(
                            f"vector must have shape ({m.vector_size},), "
                            f"got {v.shape}"
                        )
                    r["vec"] = v
            except KeyError as e:
                r["error"] = e
                r["event"].set()
                continue
            except Exception as e:
                # Anything np.asarray can throw on garbage (TypeError,
                # ragged-list ValueError) is a bad request, not a 500.
                r["error"] = ValueError(f"bad vector: {e}")
                r["event"].set()
                continue
            live.append(r)
        try:
            # A drained batch can mix modes (per-request exact=true
            # riding alongside approximate defaults): each mode group
            # is its own dispatch — the approximate and exact programs
            # are different compiled families.
            for mode in ("ann", "exact"):
                group = [r for r in live if r.get("mode", "exact") == mode]
                for s in range(0, len(group), self.max_batch):
                    self._dispatch(group[s : s + self.max_batch], mode)
        except Exception as e:  # pragma: no cover - device failure path
            for r in live:
                if r["error"] is None and r["result"] is None:
                    r["error"] = e
        finally:
            for r in live:
                r["event"].set()

    def _dispatch(self, chunk, mode: str = "exact") -> None:
        """Answer one <= max_batch slice of the drained batch with one
        bucketed pull + one bucketed batch top-k dispatch (exact masked
        GEMM, or the two-stage coarse+rerank when ``mode == "ann"``)."""
        faults.fire("serving.dispatch")
        m = self.model
        # Version BEFORE the reads: if a table mutation lands mid-
        # dispatch these results are from the old tables and must not
        # enter the cache under the new version.
        ver = m.engine.table_version
        # Device lane (ISSUE 18): one always-recorded span per coalesced
        # dispatch (never tail-sampled — a kept request's stitched trace
        # must always show the batch it rode in; the trace ids it
        # carried are on the args).
        t_dis0 = time.perf_counter()
        with obs_events.phase_span(
            "req.dispatch", batch=len(chunk), mode=mode,
            traces=[r["trace"] for r in chunk if r.get("trace")],
        ):
            word_rows = [r for r in chunk if "idx" in r]
            if word_rows:
                pulled = _pull_coalesced(
                    m.engine,
                    np.asarray([r["idx"] for r in word_rows], np.int32),
                )
                for r, v in zip(word_rows, pulled):
                    r["vec"] = v
            k = max(
                r["num"] + (1 if r["word"] is not None else 0)
                for r in chunk
            )
            hits = m.find_synonyms_batch(
                np.stack([r["vec"] for r in chunk]),
                min(k, m.vocab.size),
                approximate=(mode == "ann"),
            )
        t_dis1 = time.perf_counter()
        if self.metrics is not None:
            self.metrics.record_batch(len(chunk))
            if mode == "ann":
                self.metrics.record_ann_query(len(chunk), self.ann_nprobe)
            elif self.ann_active() or self.gate_failing():
                # Attribute per REQUEST, not from dispatch-time global
                # state: an explicit exact=true is the escape hatch
                # ("requested") even while the gate is failing; only
                # defaults held back BY the gate count as "gate".
                n_req = sum(
                    1 for r in chunk if r.get("exact_requested")
                )
                if n_req:
                    self.metrics.record_exact_fallback(
                        n_req, "requested"
                    )
                if len(chunk) - n_req and self.gate_failing():
                    self.metrics.record_exact_fallback(
                        len(chunk) - n_req, "gate"
                    )
        for r, hs in zip(chunk, hits):
            if r["word"] is not None:
                hs = [(w, s) for w, s in hs if w != r["word"]]
            r["result"] = hs[: r["num"]]
        t_rb1 = time.perf_counter()
        for r in chunk:
            # Dispatch-window stamps the waiter threads convert into
            # their own queue/query/readback phases (single-writer per
            # trace: only the owning waiter touches its RequestTrace).
            r["t_dis0"], r["t_dis1"], r["t_rb1"] = t_dis0, t_dis1, t_rb1
        if self.cache_size:
            with self._mu:
                if self._cache_sync_locked() != ver:
                    return  # mutated mid-dispatch: results are stale
                for r in chunk:
                    if r["word"] is not None:
                        while len(self._cache) >= self.cache_size:
                            self._cache.pop(next(iter(self._cache)))
                        self._cache[
                            (r["word"], r["num"], mode)
                        ] = r["result"]


class SnapshotWatcher:
    """Background poller that follows a publish directory's
    ``LATEST.json`` pointer (streaming/publish.py) and hot-swaps each
    new generation into the live server.

    The pointer is only ever flipped AFTER a generation's atomic
    commit, so the watcher can never observe a partial snapshot — and
    staging verifies the matrix manifest besides, so a corrupt
    generation is a counted ``swap_failure`` (the previous tables stay
    live), never a bad serve. A failed generation is not retried until
    the pointer moves again.

    Transient storage trouble is NOT failure: a pointer or
    generation-dir read error (mid-rename visibility on a network
    filesystem, an NFS attribute-cache hiccup) backs off with a capped
    doubling delay and retries on a later poll — counted as
    ``watch_errors`` on ``/metrics`` — instead of either stalling the
    watcher thread or permanently skipping a generation that is in
    fact committed and fine."""

    #: Transient-error backoff ceiling (seconds).
    BACKOFF_CAP = 30.0
    #: Consecutive polls a referenced generation directory may be
    #: invisible before it is branded failed: on network filesystems
    #: the directory rename's visibility can lag the pointer flip by a
    #: beat (transient — retried with backoff), while an operator
    #: deletion stays missing forever (permanent after the strikes).
    MISSING_DIR_STRIKES = 2
    #: Consecutive transient staging read errors (OSError inside an
    #: EXISTING generation dir) tolerated for one generation before it
    #: too is branded failed: storage hiccups clear within a few
    #: backed-off polls; a permanently unreadable file (deleted shard,
    #: permissions) does not, and must not retry forever.
    STAGING_ERROR_STRIKES = 5

    def __init__(self, server: "ModelServer", watch_dir: str,
                 poll_seconds: float = 1.0,
                 model_id: Optional[str] = None):
        self.server = server
        self.watch_dir = watch_dir
        #: Which catalog entry this watcher swaps (None = the default
        #: model): one model's pointer move rolls ONLY that model, and
        #: its swap/watch-error counters land on that model's metrics.
        self.model_id = model_id
        self.poll_seconds = max(0.05, float(poll_seconds))
        #: Current transient-error backoff (seconds; 0 while healthy —
        #: doubles per consecutive error up to BACKOFF_CAP, resets on
        #: any successful poll).
        self._backoff = 0.0
        #: monotonic time before which polls are skipped (backoff).
        self._retry_at = 0.0
        #: (generation, consecutive polls its dir was missing).
        self._missing = (None, 0)
        #: (generation, consecutive transient staging read errors).
        self._stage_errs = (None, 0)
        #: Generation name currently served (watcher-thread written;
        #: /reload reads it for its "unchanged" answer — a stale read
        #: only costs one redundant poll).
        self.current: Optional[str] = None
        #: Last generation that failed staging — not retried until the
        #: pointer names a different one.
        self._failed: Optional[str] = None
        #: Serializes polls between the watcher thread and POST
        #: /reload request threads: without it both could stage the
        #: same generation (duplicate disk reads + device transfers)
        #: and adopt it twice, double-counting table_swaps.
        self._poll_mu = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def metrics(self) -> ServingMetrics:
        """The watched model's own metrics — per-model swap and
        watch-error counters (ISSUE 20). Servers without a catalog
        (duck-typed test stands-ins) expose ``.metrics`` directly."""
        lookup = getattr(self.server, "_entry", None)
        if lookup is None:
            return self.server.metrics
        return lookup(self.model_id).metrics

    def poll_once(self) -> Optional[str]:
        """One pointer check; returns the generation name when a swap
        happened, else None. Never raises — failures are logged and
        counted on the serving metrics."""
        with self._poll_mu:
            return self._poll_once_locked()

    def _poll_once_locked(self) -> Optional[str]:
        from glint_word2vec_tpu.streaming.publish import read_latest

        if time.monotonic() < self._retry_at:
            return None  # backing off after a transient read error
        try:
            latest = read_latest(self.watch_dir, raise_errors=True)
        except (OSError, ValueError) as e:
            return self._watch_error_locked(f"unreadable pointer: {e}")
        if latest is None:
            self._backoff = 0.0
            return None
        gen = str(latest["generation"])
        if gen == self.current or gen == self._failed:
            self._backoff = 0.0
            return None
        gen_dir = os.path.join(self.watch_dir, gen)
        if not os.path.isdir(gen_dir):
            mgen, n = self._missing
            n = n + 1 if mgen == gen else 1
            self._missing = (gen, n)
            if n < self.MISSING_DIR_STRIKES:
                # First miss(es): rename-visibility lag on a network
                # filesystem looks exactly like this — back off and
                # look again before condemning the generation.
                return self._watch_error_locked(
                    f"referenced generation {gen} not visible yet "
                    f"(miss {n}/{self.MISSING_DIR_STRIKES})"
                )
            # Still missing after the strikes: an operator deletion —
            # branded failed and not retried until the pointer moves
            # (the PR 10 contract).
            logger.error(
                "hot-swap of %s failed: generation directory missing "
                "after %d polls", gen, n,
            )
            self.metrics.record_swap(gen, ok=False)
            self._failed = gen
            return None
        self._missing = (None, 0)
        try:
            kwargs = {"generation": gen}
            if self.model_id is not None:
                kwargs["model_id"] = self.model_id
            self.server.reload_generation(gen_dir, **kwargs)
        except OSError as e:
            # The directory EXISTS but a read inside it failed: the
            # pointer only ever names committed generations, so this
            # is transient storage trouble (mid-rename visibility, an
            # NFS attribute-cache hiccup) — back off and retry the
            # poll. Only a sustained run of read errors on the same
            # generation brands it failed (a permanently unreadable
            # file is not a hiccup).
            sgen, n = self._stage_errs
            n = n + 1 if sgen == gen else 1
            self._stage_errs = (gen, n)
            if n >= self.STAGING_ERROR_STRIKES:
                logger.error(
                    "hot-swap of %s failed: %d consecutive staging "
                    "read errors (%s)", gen, n, e,
                )
                self.metrics.record_swap(gen, ok=False)
                self._failed = gen
                return None
            return self._watch_error_locked(
                f"transient read error staging {gen}: {e} "
                f"(strike {n}/{self.STAGING_ERROR_STRIKES})"
            )
        except Exception as e:
            logger.error("hot-swap of %s failed: %s", gen, e)
            self.metrics.record_swap(gen, ok=False)
            self._failed = gen
            return None
        self.current = gen
        self._failed = None
        self._backoff = 0.0
        self._stage_errs = (None, 0)
        return gen

    def _watch_error_locked(self, msg: str) -> None:
        """Count one transient publish-dir read failure and arm the
        capped-doubling retry delay; the watcher thread stays live and
        the next eligible poll retries from scratch."""
        self._backoff = min(
            max(self.poll_seconds, self._backoff * 2), self.BACKOFF_CAP
        )
        self._retry_at = time.monotonic() + self._backoff
        self.metrics.record_watch_error()
        logger.warning(
            "snapshot watcher: %s (retrying in %.1fs)", msg, self._backoff
        )
        return None

    def start(self) -> None:
        suffix = f"-{self.model_id}" if self.model_id else ""
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"glint-snapshot-watcher{suffix}",
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_seconds):
            self.poll_once()

    def stop(self) -> None:
        self._stop.set()


class ServedModel:
    """One catalog entry (ISSUE 20): a loaded model plus everything the
    server keys PER model — its result-cache coalescer, its
    ServingMetrics (+SLO engine), its publish watcher, and its
    residency state under the device-memory budget. The per-model
    coalescer is what makes a cross-model cache hit structurally
    impossible: each cache validates against its own engine's
    ``table_version`` and is never consulted for another model id."""

    def __init__(self, model_id: str, model, coalescer, metrics,
                 source_dir: Optional[str] = None):
        self.model_id = model_id
        self.model = model
        self.coalescer = coalescer
        self.metrics = metrics
        #: Committed snapshot directory (a loadable model dir) the
        #: entry stages back in from after an eviction; refreshed by
        #: every successful per-model hot-swap.
        self.source_dir = source_dir
        self.watcher: Optional["SnapshotWatcher"] = None
        #: Pin count: a pinned entry is never staged out — the default
        #: model (permanently), a mid-swap generation, a fleet hold or
        #: warm spare (via POST /models/pin).
        self.pins = 0
        #: LRU clock: last request touch (catalog-lock guarded).
        self.last_used = time.monotonic()
        #: Device bytes the tables cost while resident — remembered
        #: across stage-out so the budget can plan the stage-in.
        self.cost_bytes = 0
        #: Serializes stage-in: the first request to a cold model
        #: stages; the rest queue here (bounded by their own deadlines)
        #: and are answered from the newly resident tables.
        self.stage_mu = threading.Lock()
        self.stage_ins = 0
        self.evictions = 0

    @property
    def resident(self) -> bool:
        """Whether the tables are on device right now. Models without
        a stage-out-capable engine always read resident."""
        eng = getattr(self.model, "engine", None)
        return bool(getattr(eng, "tables_resident", True))

    @property
    def evictable(self) -> bool:
        """Only the base word-level family round-trips through
        ``release_tables``/``stage_tables``, and only with a committed
        snapshot to stage back from."""
        from glint_word2vec_tpu.models.word2vec import Word2VecModel

        return (
            self.source_dir is not None
            and type(self.model) is Word2VecModel
        )

    def resident_bytes(self) -> int:
        """Device bytes this entry holds right now (0 when staged
        out)."""
        eng = getattr(self.model, "engine", None)
        fn = getattr(eng, "resident_bytes", None)
        if fn is None or not self.resident:
            return 0
        return int(fn())


class ModelCatalog:
    """model-id -> :class:`ServedModel` routing table plus the
    device-memory budget (ISSUE 20).

    All N models share ONE device lock, ONE admission/overload layer,
    and ONE process-level shape-keyed compiled program family
    (parallel/engine ``_QUERY_MEMO`` — loading a same-(V, d) model #2
    builds zero new programs); the catalog adds per-model result
    caches/metrics/watchers and, when ``budget_bytes`` is set, LRU
    stage-out of cold tables to their committed host snapshots.
    Stage-in runs OFF the request path: the winning request stages
    (``stage_tables`` with no lock held, ``adopt_tables`` under the
    device lock), concurrent requests queue behind ``entry.stage_mu``
    bounded by their own deadlines and are answered from the new
    tables — never a 5xx."""

    #: Read-mostly references guarded by insertion discipline rather
    #: than the catalog lock: ``entries`` is only ever grown (install
    #: holds ``_mu``; dict reads are atomic under the GIL and a racing
    #: reader simply sees the catalog before/after the install —
    #: equally correct), ``default_id`` is written once at install
    #: time, and ``budget_bytes`` is a boot-time scalar.
    _ATOMIC_ATTRS = frozenset({"entries", "default_id", "budget_bytes"})

    def __init__(self, server: "ModelServer",
                 budget_bytes: Optional[int] = None):
        self._server = server
        self._mu = threading.Lock()
        self.entries: "OrderedDict[str, ServedModel]" = OrderedDict()
        self.default_id: Optional[str] = None
        self.budget_bytes = budget_bytes
        self.evictions = 0
        self.stage_ins = 0
        self.stage_in_seconds = 0.0
        #: Requests that found their model cold (the eviction-miss
        #: population: each either staged in or queued behind one).
        self.cold_hits = 0

    # -- membership ----------------------------------------------------

    def install(self, entry: ServedModel, default: bool = False) -> None:
        with self._mu:
            if entry.model_id in self.entries:
                raise ValueError(
                    f"model id {entry.model_id!r} already served"
                )
            self.entries[entry.model_id] = entry
            if default or self.default_id is None:
                self.default_id = entry.model_id

    @property
    def default(self) -> ServedModel:
        return self.entries[self.default_id]

    def get(self, model_id: Optional[str]) -> ServedModel:
        """Entry for a model id (None = default); KeyError -> 404."""
        mid = model_id if model_id is not None else self.default_id
        entry = self.entries.get(mid)
        if entry is None:
            raise KeyError(f"unknown model {mid!r}")
        return entry

    def ids(self):
        return list(self.entries)

    # -- pin / hold -----------------------------------------------------

    def pin(self, model_id: Optional[str]) -> None:
        """Hold a model resident: a pinned entry is never staged out
        (rollout holds, shadow canaries, warm spares)."""
        entry = self.get(model_id)
        with self._mu:
            entry.pins += 1

    def unpin(self, model_id: Optional[str]) -> None:
        entry = self.get(model_id)
        with self._mu:
            entry.pins = max(0, entry.pins - 1)

    # -- residency ------------------------------------------------------

    def touch(self, entry: ServedModel) -> None:
        """LRU bookkeeping for one request: most-recently-used moves to
        the back of the eviction order."""
        with self._mu:
            entry.last_used = time.monotonic()
            if entry.model_id in self.entries:
                self.entries.move_to_end(entry.model_id)

    def resident_bytes(self) -> int:
        return sum(
            e.resident_bytes() for e in list(self.entries.values())
        )

    def ensure_resident(self, entry: ServedModel,
                        deadline: Optional[float] = None) -> None:
        """Return once the entry's tables are on device.

        The winning request thread stages in (budget eviction first,
        then manifest-verified reads + device assembly with NO lock
        held, then the flip under the device lock); every concurrent
        request to the same model queues here — bounded by its own
        deadline — and is answered from the newly resident tables.
        A cold model therefore costs its callers latency, never a
        5xx."""
        self.touch(entry)
        if entry.resident:
            return
        with self._mu:
            self.cold_hits += 1
        if deadline is None:
            ok = entry.stage_mu.acquire()
        else:
            ok = entry.stage_mu.acquire(
                timeout=max(0.0, deadline - time.monotonic())
            )
        if not ok:
            raise DeadlineExceeded("deadline waiting for model stage-in")
        try:
            if not entry.resident:
                self._stage_in(entry)
        finally:
            entry.stage_mu.release()

    def _stage_in(self, entry: ServedModel) -> None:
        """Bring an evicted model's tables back from its committed host
        snapshot. Caller holds ``entry.stage_mu`` (NOT the device
        lock — staging reads disk and assembles device arrays while
        other models keep serving)."""
        src = entry.source_dir
        if src is None:
            raise ValueError(
                f"model {entry.model_id!r} has no committed snapshot "
                "to stage in from"
            )
        t0 = time.monotonic()
        self._make_room(entry)
        engine = entry.model.engine
        staged = engine.stage_tables(os.path.join(src, "matrix"))
        with self._server._lock:
            engine.adopt_tables(staged)
        dt = time.monotonic() - t0
        with self._mu:
            entry.cost_bytes = entry.resident_bytes()
            entry.stage_ins += 1
            self.stage_ins += 1
            self.stage_in_seconds += dt
        logger.info(
            "staged model %r back in from %s (%.2fs, %d bytes)",
            entry.model_id, src, dt, entry.cost_bytes,
        )

    def _make_room(self, entry: Optional[ServedModel]) -> None:
        """Evict LRU unpinned models until ``entry`` (or, with None,
        the current residency) fits the budget. With nothing evictable
        left the catalog runs over budget rather than failing requests
        — the budget is a target, pins are a guarantee."""
        budget = self.budget_bytes
        if not budget:
            return
        need = max(0, entry.cost_bytes) if entry is not None else 0
        while True:
            with self._mu:
                used = sum(
                    e.resident_bytes() for e in self.entries.values()
                )
                if used + need <= budget:
                    return
                victim = None
                for e in self.entries.values():  # LRU iteration order
                    if e is entry or not e.resident:
                        continue
                    if e.pins == 0 and e.evictable:
                        victim = e
                        break
            if victim is None:
                logger.warning(
                    "model-memory budget exceeded (%d resident + %d "
                    "needed > %d) with nothing evictable — running "
                    "over budget", used, need, budget,
                )
                return
            self.evict(victim)

    def evict(self, entry: ServedModel) -> bool:
        """Stage one model's tables out of device memory. The bytes
        are already safe on disk (the committed snapshot in
        ``source_dir``), so eviction is pure release — pending async
        saves are drained first inside ``release_tables``."""
        with self._mu:
            if entry.pins or not entry.evictable or not entry.resident:
                return False
        engine = entry.model.engine
        with self._server._lock:
            with self._mu:
                if entry.pins:  # pinned in the race window
                    return False
                entry.cost_bytes = (
                    entry.resident_bytes() or entry.cost_bytes
                )
            engine.release_tables()
        with self._mu:
            entry.evictions += 1
            self.evictions += 1
        logger.info(
            "staged model %r out (%d bytes freed; snapshot %s)",
            entry.model_id, entry.cost_bytes, entry.source_dir,
        )
        return True

    def enforce_budget(self) -> None:
        """Re-establish the budget after a load/reload grew residency."""
        self._make_room(None)

    # -- exposition ------------------------------------------------------

    def snapshot(self) -> dict:
        """Catalog block for /metrics: membership, residency vs budget,
        LRU churn counters, and the process-level program-sharing
        proof (builds vs shared-hit counts)."""
        from glint_word2vec_tpu.parallel.engine import (
            query_program_builds,
        )

        with self._mu:
            entries = list(self.entries.values())
            doc = {
                "models": len(entries),
                "default_model": self.default_id,
                "budget_bytes": self.budget_bytes,
                "evictions_total": self.evictions,
                "stage_ins_total": self.stage_ins,
                "stage_in_seconds_total": round(
                    self.stage_in_seconds, 3
                ),
                "cold_hits_total": self.cold_hits,
            }
        doc["resident_models"] = sum(1 for e in entries if e.resident)
        doc["resident_bytes"] = sum(e.resident_bytes() for e in entries)
        doc["query_program_builds"] = query_program_builds()
        shared = 0
        for e in entries:
            eng = getattr(e.model, "engine", None)
            shared += int(getattr(eng, "shared_program_hits", 0) or 0)
        doc["shared_program_hits"] = shared
        return doc


class ModelServer:
    """Holds one loaded model and serves its query surface over HTTP.

    ``max_batch`` caps (and shape-quantizes, rounded up to a power of
    two) the coalesced device dispatch; ``warmup=True`` compiles the
    whole serving shape family — Q buckets 1..max_batch, the
    ``warm_ks`` top-k buckets, and the (``warm_sentence_rows`` x
    ``warm_sentence_lens``) sentence-transform grid — BEFORE the port
    binds, so no real request inside the family ever pays a jit
    compile (a /transform of more than max(warm_sentence_rows)
    sentences per MAX_QUERY_ROWS chunk still compiles its row bucket
    lazily). Per-endpoint latency histograms, the
    coalesced-batch-size distribution, and compile counters are served
    on ``/metrics`` (and summarized on ``/healthz``).
    """

    #: Lock-free by design: ``_ann_live`` is a single bool flag —
    #: written at boot (no request threads yet) and under the device
    #: lock on hot-swap, read by request threads where a stale read
    #: only routes one request to the other (equally correct) path.
    _ATOMIC_ATTRS = frozenset({"_ann_live"})

    def __init__(
        self,
        model,
        host: str = "127.0.0.1",
        port: int = 8801,
        *,
        max_batch: int = 64,
        warmup: bool = True,
        # k buckets 16 and 32: num < 16 rounds into the 16 bucket and
        # num in [16, 31] (fetching num+1) into the 32 bucket, so the
        # default num range AND generous clients stay compile-free;
        # num >= 32 pays one lazy compile per further pow2 bucket.
        warm_ks=(16, 32),
        warm_sentence_lens=(1, 2, 4, 8, 16, 32, 64),
        warm_sentence_rows=(1, 2, 4, 8, 16),
        cache_size: int = 65536,
        max_inflight: int = 256,
        request_deadline: Optional[float] = 30.0,
        degraded_after: Optional[float] = 5.0,
        ann: bool = False,
        ann_clusters: int = -1,
        ann_nprobe: int = 8,
        ann_iters: int = 6,
        ann_sample: int = 65536,
        ann_recall_gate: float = 0.95,
        ann_recall_sample: int = 64,
    ):
        self.model = model
        self._prev_switch: Optional[float] = None
        #: Fleet launch-generation handshake (PR 7 pattern, serving
        #: tier): the fleet supervisor exports ``GLINT_FLEET_GEN`` on
        #: every replica launch and this server echoes it on
        #: ``/healthz`` and in its ``--port-file``, so a probe answered
        #: by a stale pre-restart process (or a stale port file) can
        #: never count as the NEW replica being healthy/ready.
        self.fleet_generation = os.environ.get("GLINT_FLEET_GEN")
        # Device queries are jitted functions on shared tables; serialize
        # them (the reference's PS likewise processes a shard's requests
        # on its actor mailbox, one at a time). The synonym endpoints
        # additionally coalesce concurrent waiters into one batched
        # dispatch (_SynonymCoalescer). Tracked so the overload layer
        # can see how long the device has been continuously busy.
        self._lock = _TrackedLock()
        self.metrics = ServingMetrics()
        # -- SLO burn rates + anomaly flight recorder (ISSUE 18) -------
        #: Per-endpoint availability/latency objectives over the device
        #: paths; ServingMetrics.observe feeds it and its snapshot rides
        #: /metrics under "slo" (rendered as glint_slo_*).
        self.metrics.slo = SloEngine.default_serving(_DEVICE_PATHS)
        self._shed_burst = ShedBurstDetector()
        #: Optional postmortem bundle writer — installed by
        #: :meth:`enable_flight_recorder`; None keeps every trigger
        #: path a no-op.
        self.flight: Optional[FlightRecorder] = None
        # -- overload protection (ISSUE 7) -----------------------------
        #: Admission high-water mark: device-touching requests past this
        #: many in flight shed with 429 + Retry-After instead of
        #: queueing without bound (the _pending list and the handler
        #: thread pool both used to grow arbitrarily under overload).
        self.max_inflight = max(0, int(max_inflight))
        #: Per-request deadline (seconds; None/0 disables): a request
        #: that cannot reach the device in time answers 504 without
        #: occupying a dispatch slot.
        self.request_deadline = (
            float(request_deadline) if request_deadline else None
        )
        #: Device-lock hold time (seconds; None/0 disables) past which
        #: the server enters degraded cache-only mode: cache hits are
        #: served, everything needing the device sheds with 429.
        self.degraded_after = (
            float(degraded_after) if degraded_after else None
        )
        self._inflight = 0
        self._inflight_mu = threading.Lock()
        self._degraded_flag = False
        self._coalescer = _SynonymCoalescer(
            model, self._lock, max_batch=max_batch, metrics=self.metrics,
            cache_size=cache_size,
        )
        self.max_batch = self._coalescer.max_batch
        self.cache_size = max(0, int(cache_size))
        #: Serving warm family parameters, reused verbatim by
        #: ``add_model`` so every catalog entry warms the SAME shape
        #: family — same-(V, d) models then share every compiled
        #: program through the process-level memo.
        self._warm_params = (
            tuple(warm_ks),
            tuple(warm_sentence_lens),
            tuple(warm_sentence_rows),
        )
        self._do_warmup = bool(warmup)
        # -- model catalog (ISSUE 20) ----------------------------------
        self.catalog = ModelCatalog(self)
        _default_entry = ServedModel(
            DEFAULT_MODEL_ID, model, self._coalescer, self.metrics
        )
        #: The default model is permanently pinned: the back-compat
        #: single-model surface must never stage out under budget
        #: pressure.
        _default_entry.pins = 1
        self.catalog.install(_default_entry, default=True)
        # -- approximate top-k (ISSUE 12) ------------------------------
        #: Whether the two-stage device index serves default /synonyms
        #: traffic. Only the base word-level family (the batching
        #: population) qualifies; per-request ``exact=true`` always
        #: escapes to the exact masked GEMM, and the measured recall
        #: gate can hold the approximate path back entirely.
        self.ann = bool(ann) and self._coalescer.can_batch
        self.ann_recall_gate = float(ann_recall_gate)
        self.ann_recall_sample = max(1, int(ann_recall_sample))
        self._ann_live = False
        if self.ann:
            eng = model.engine
            conf = eng.configure_ann(
                clusters=ann_clusters, nprobe=ann_nprobe,
                iters=ann_iters, sample=ann_sample,
            )
            self._coalescer.ann_nprobe = conf["nprobe"]
            if eng.ann_index is None:
                t0 = time.time()
                eng.adopt_ann(eng.ann_build())
                logger.info(
                    "ANN index built in %.1fs (%d clusters x %d slots)",
                    time.time() - t0, conf["clusters"], conf["slots"],
                )
        if warmup:
            self._warmup(
                warm_ks, warm_sentence_lens, warm_sentence_rows
            )
        if self.ann:
            # Recall gate AFTER warmup: the check rides the warmed
            # exact + approximate programs, so it proves the index AND
            # costs zero compiles. A failing gate keeps the exact path
            # serving (counted on /metrics as gate fallbacks) — a fast
            # wrong answer is not an answer.
            self._gate_index(self.model.engine, self.metrics.generation)
        # Shapes compiled from here on are serving-path misses the
        # /metrics "post_warmup" counter (and the CI smoke) watches.
        self.metrics.warmup_compiles = self._query_compiles()
        server = self

        class Handler(BaseHTTPRequestHandler):
            # Keep-alive: reconnecting per request dominated measured
            # latency at high concurrency on the closed-loop bench.
            protocol_version = "HTTP/1.1"
            # Responses go out as two small writes (header buffer, then
            # body); without TCP_NODELAY, Nagle holds the body segment
            # until the client ACKs the headers — a delayed-ACK 40ms
            # stall that was the entire >1-client p95 (SERVING_BENCH).
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):  # route to logging, not stderr
                logger.debug("serve: " + fmt, *args)

            def _send(self, code: int, obj, headers=None) -> None:
                tr = getattr(self, "_trace", None) or obs_events.NULL_TRACE
                with tr.phase("req.serialize"):
                    body = json.dumps(obj).encode()
                    self._status = code
                    self.send_response(code)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    for k, v in (headers or {}).items():
                        self.send_header(k, v)
                    self.end_headers()
                    self.wfile.write(body)

            def _send_text(self, code: int, text: str) -> None:
                body = text.encode()
                self._status = code
                self.send_response(code)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8",
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                t0 = time.perf_counter()
                self._status = 500
                # No request trace on GETs (probe/scrape traffic), and a
                # finished trace from an earlier POST on this keep-alive
                # connection must not collect this response's spans.
                self._trace = None
                # Parsed path: routing and metric keys must not vary with
                # the query string (?format=... would otherwise mint a
                # fresh latency histogram per variant).
                url = urlparse(self.path)
                mid, path = split_model_path(
                    url.path, self.headers.get("X-Glint-Model")
                )
                try:
                    entry = server._entry(mid)
                except KeyError:
                    self._send(404, {"error": f"unknown model {mid!r}"})
                    server._observe_request(
                        server.catalog.default, path,
                        time.perf_counter() - t0, 404,
                    )
                    return
                try:
                    if path == "/healthz":
                        m = entry.model
                        compiles = server._query_compiles(entry)
                        degraded = server._degraded()
                        doc = {
                            # Degraded is still alive-but-impaired: 200
                            # with the flag (a 5xx here would make the
                            # fleet LB pull a server that is shedding
                            # exactly as designed).
                            "status": (
                                "degraded" if degraded else "ok"
                            ),
                            "model": entry.model_id,
                            "family": type(m).__name__,
                            "vocab_size": m.vocab.size,
                            "dim": m.vector_size,
                            "max_batch": server.max_batch,
                            "compiles": compiles,
                            "post_warmup_compiles": compiles
                            - entry.metrics.warmup_compiles,
                            "max_inflight": server.max_inflight,
                            "request_deadline_seconds":
                                server.request_deadline,
                            "degraded_after_seconds":
                                server.degraded_after,
                            "ann_enabled": server._ann_live,
                            "ann_recall_gate_ok":
                                server.metrics.index_recall_gate_ok,
                            "generation":
                                entry.metrics.generation,
                            "fleet_generation":
                                server.fleet_generation,
                            "resident": entry.resident,
                        }
                        if mid is None and len(server.catalog.entries) > 1:
                            doc["models"] = server._models_summary()
                        self._send(200, doc)
                    elif path == "/metrics":
                        # Scoped /m/<id>/metrics answers ONE model's
                        # block; the bare path keeps the default
                        # model's snapshot at the root (back-compat)
                        # with per-model + catalog blocks folded in.
                        if mid is not None:
                            snap = server._entry_snapshot(entry)
                        else:
                            snap = server._metrics_doc()
                        fmt = parse_qs(url.query).get("format", ["json"])[0]
                        if fmt == "prometheus":
                            self._send_text(200, serving_to_prometheus(snap))
                        else:
                            self._send(200, snap)
                    elif path == "/models":
                        self._send(200, server._models_doc())
                    elif path == "/trace":
                        # Flight-recorder scrape: the last N seconds of
                        # this process's span ring plus the clock anchor,
                        # so the balancer's postmortem bundle can rebase
                        # every replica onto one timeline.
                        rec = obs_events.get_recorder()
                        try:
                            secs = float(parse_qs(url.query).get(
                                "seconds", ["30"]
                            )[0])
                        except ValueError:
                            secs = 30.0
                        if rec is None:
                            self._send(200, {"events": [], "anchor": None})
                        else:
                            self._send(200, {
                                "events": rec.recent_events(secs),
                                "anchor": {"wall_t0": rec.wall_t0,
                                           "mono_t0": rec.mono_t0},
                            })
                    else:
                        self._send(404, {"error": f"no route {path}"})
                finally:
                    server._observe_request(
                        entry, path, time.perf_counter() - t0,
                        self._status,
                    )

            def do_POST(self):
                t0 = time.perf_counter()
                self._status = 500
                # Same parsed-path rule as do_GET: routing and metric
                # keys must not vary with the query string.
                mid, path = split_model_path(
                    urlparse(self.path).path,
                    self.headers.get("X-Glint-Model"),
                )
                # Distributed tracing (ISSUE 18): adopt the propagated
                # trace id (the balancer's X-Glint-Trace) or mint one at
                # the edge. Phase spans buffer on the trace and flush
                # into the ring only if the tail sampler keeps the
                # request (always: errors/sheds/slow; 1-in-N otherwise).
                tr = obs_events.request_trace(
                    self.headers.get(obs_events.TRACE_HEADER)
                )
                self._trace = tr
                try:
                    entry = server._entry(mid)
                except KeyError:
                    self._send(404, {"error": f"unknown model {mid!r}"})
                    tr.finish(404)
                    server._observe_request(
                        server.catalog.default, path,
                        time.perf_counter() - t0, 404,
                    )
                    return
                try:
                    with tr.phase("req.accept", path=path):
                        self._handle_post(path, entry)
                finally:
                    kept = tr.finish(self._status)
                    server._observe_request(
                        entry, path, time.perf_counter() - t0,
                        self._status,
                        trace_id=tr.trace_id if kept else None,
                    )

            def _handle_post(self, path, entry):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                except (ValueError, json.JSONDecodeError) as e:
                    return self._send(400, {"error": f"bad request: {e}"})
                if path in _DEVICE_PATHS:
                    # Admission bound: past the high-water mark the
                    # request sheds NOW — cheaper for everyone than
                    # joining a queue whose wait already exceeds any
                    # reasonable client timeout.
                    with self._trace.phase("req.admission") as adm:
                        admitted = server._admit()
                        adm.update(admitted=admitted)
                    if not admitted:
                        server._record_shed("admission", entry)
                        return self._send(
                            429,
                            {"error": "server overloaded "
                                      "(admission queue full)"},
                            headers={"Retry-After": "1"},
                        )
                    try:
                        return self._handle_device(path, req, entry)
                    finally:
                        server._release_slot()
                out = None
                if path == "/reload":
                    # Admin hot-swap of THIS entry's model: explicit
                    # generation dir, or an immediate poll of its
                    # watched publish dir. Not a _DEVICE_PATHS member —
                    # an overloaded server must still be swappable
                    # (staging runs lock-free; the flip queues behind
                    # in-flight dispatches only).
                    if "dir" in req:
                        gen_dir = str(req["dir"])
                        gen = req.get("generation") or os.path.basename(
                            os.path.normpath(gen_dir)
                        )
                        # Serialize against the entry's watcher poll
                        # thread — an explicit reload racing a pointer
                        # poll must not stage/adopt the same generation
                        # twice.
                        mu = (
                            entry.watcher._poll_mu
                            if entry.watcher is not None
                            else contextlib.nullcontext()
                        )
                        with mu:
                            try:
                                server.reload_generation(
                                    gen_dir, generation=gen,
                                    model_id=entry.model_id,
                                )
                            except OSError as e:
                                if os.path.isdir(gen_dir):
                                    # The dir EXISTS but a read inside
                                    # it failed: transient storage
                                    # trouble, answered 503 so a fleet
                                    # rollout coordinator retries
                                    # instead of branding the
                                    # generation failed (the
                                    # SnapshotWatcher classification,
                                    # preserved across the HTTP
                                    # boundary).
                                    entry.metrics.record_watch_error()
                                    return self._send(
                                        503,
                                        {"error": "transient staging "
                                                  f"error: {e}"},
                                        headers={"Retry-After": "1"},
                                    )
                                entry.metrics.record_swap(gen, ok=False)
                                return self._send(400, {"error": str(e)})
                            except Exception as e:
                                entry.metrics.record_swap(gen, ok=False)
                                return self._send(400, {"error": str(e)})
                            if entry.watcher is not None:
                                entry.watcher.current = gen
                        return self._send(
                            200, {"status": "reloaded", "generation": gen,
                                  "model": entry.model_id}
                        )
                    if entry.watcher is None:
                        return self._send(
                            400,
                            {"error": "no watched publish dir for "
                                      f"model {entry.model_id!r}; "
                                      'pass {"dir": ...}'},
                        )
                    gen = entry.watcher.poll_once()
                    if gen is None:
                        return self._send(
                            200,
                            {"status": "unchanged",
                             "generation": entry.watcher.current,
                             "model": entry.model_id},
                        )
                    return self._send(
                        200, {"status": "reloaded", "generation": gen,
                              "model": entry.model_id}
                    )
                if path == "/models/pin":
                    # Pin/hold admin surface: the fleet's rollout
                    # coordinator and autoscaler pin the model they are
                    # rolling/warming so the LRU can never stage it out
                    # from under a held generation or a warm spare.
                    target = req.get("model", entry.model_id)
                    try:
                        if bool(req.get("pinned", True)):
                            server.catalog.pin(target)
                        else:
                            server.catalog.unpin(target)
                        pins = server.catalog.get(target).pins
                    except KeyError:
                        return self._send(
                            404, {"error": f"unknown model {target!r}"}
                        )
                    return self._send(
                        200, {"model": target or DEFAULT_MODEL_ID,
                              "pins": pins}
                    )
                if path == "/shutdown":
                    with server._lock:
                        out = server._dispatch(path, req)
                    self._send(200, out)
                    threading.Thread(
                        target=server.stop, daemon=True
                    ).start()
                    return
                self._send(404, {"error": f"no route {path}"})

            def _handle_device(self, path, req, entry):
                """One admitted device-touching request: degraded-mode
                gate, per-request deadline, residency (the LRU
                stage-in rendezvous), then dispatch."""
                if server._degraded():
                    # Cache-only mode: the device is wedged — serve
                    # what needs no dispatch, shed the rest. 429 (not
                    # 5xx): the condition is load/availability, the
                    # client should back off and retry.
                    if path == "/synonyms":
                        try:
                            num = int(req.get("num", 10))
                        except (TypeError, ValueError) as e:
                            # Same 400 contract as the normal path — a
                            # malformed num must not change behavior
                            # just because the server is impaired.
                            return self._send(
                                400, {"error": f"bad num: {e}"}
                            )
                        hit = entry.coalescer.cache_lookup(
                            req.get("word"), num,
                            exact=bool(req.get("exact", False)),
                        )
                        if hit is not None:
                            entry.metrics.record_cache(True)
                            return self._send(
                                200, [[w, float(s)] for w, s in hit]
                            )
                    server._record_shed("degraded", entry)
                    return self._send(
                        429,
                        {"error": "degraded cache-only mode "
                                  "(device busy)"},
                        headers={"Retry-After": "1"},
                    )
                deadline = (
                    time.monotonic() + server.request_deadline
                    if server.request_deadline else None
                )
                # Deadline propagation (ISSUE 19): a balancer forwards
                # the client's REMAINING budget as X-Glint-Deadline-Ms;
                # it can only tighten the replica's own deadline, never
                # extend it.
                hdr = self.headers.get("X-Glint-Deadline-Ms")
                if hdr is not None:
                    try:
                        budget = max(0.0, float(hdr)) / 1e3
                    except (TypeError, ValueError):
                        budget = None
                    if budget is not None:
                        remote = time.monotonic() + budget
                        deadline = (
                            remote if deadline is None
                            else min(deadline, remote)
                        )
                try:
                    # LRU rendezvous: a cold model stages back in OFF
                    # the request path (the winning thread stages, the
                    # rest queue bounded by their deadlines) before
                    # any dispatch below touches its tables.
                    server.catalog.ensure_resident(
                        entry, deadline=deadline
                    )
                    if path == "/synonyms":
                        out = [
                            [w, float(s)]
                            for w, s in entry.coalescer.query(
                                word=req["word"],
                                num=int(req.get("num", 10)),
                                deadline=deadline,
                                exact=bool(req.get("exact", False)),
                                trace=self._trace,
                            )
                        ]
                    elif path == "/synonyms_vector":
                        out = [
                            [w, float(s)]
                            for w, s in entry.coalescer.query(
                                vector=req["vector"],
                                num=int(req.get("num", 10)),
                                deadline=deadline,
                                exact=bool(req.get("exact", False)),
                                trace=self._trace,
                            )
                        ]
                    else:
                        with self._trace.phase("req.queue"):
                            if deadline is None:
                                acquired = server._lock.acquire()
                            else:
                                acquired = server._lock.acquire(
                                    timeout=deadline - time.monotonic()
                                )
                        if not acquired:
                            raise DeadlineExceeded(
                                "deadline waiting for device"
                            )
                        try:
                            with self._trace.phase(
                                "req.query", mode="exact"
                            ):
                                out = server._dispatch(
                                    path, req, entry.model
                                )
                        finally:
                            server._lock.release()
                except DeadlineExceeded as e:
                    entry.metrics.record_deadline()
                    return self._send(504, {"error": str(e)})
                except KeyError as e:
                    return self._send(
                        404, {"error": e.args[0] if e.args else str(e)}
                    )
                except ValueError as e:
                    return self._send(400, {"error": str(e)})
                if out is None:
                    return self._send(404, {"error": f"no route {path}"})
                self._send(200, out)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None
        # Mode suppliers LAST: no request thread exists yet, and the
        # coalescer must never see ann before the gate ran.
        self._coalescer.ann_active = lambda: self._ann_live
        self._coalescer.gate_failing = (
            lambda: self.ann and not self._ann_live
        )

    # -- model catalog (ISSUE 20) ---------------------------------------

    @property
    def watcher(self) -> Optional[SnapshotWatcher]:
        """The DEFAULT model's publish watcher (back-compat alias —
        each catalog entry owns its own watcher)."""
        return self.catalog.default.watcher

    @watcher.setter
    def watcher(self, w: Optional[SnapshotWatcher]) -> None:
        self.catalog.default.watcher = w

    def _entry(self, model_id: Optional[str]) -> ServedModel:
        """Catalog entry for a request's model id (None = default);
        KeyError -> the handler's 404."""
        return self.catalog.get(model_id)

    def add_model(self, model_id: str, model=None,
                  model_dir: Optional[str] = None, *,
                  warmup: Optional[bool] = None,
                  generation: Optional[str] = None) -> ServedModel:
        """Serve another model from this process (ISSUE 20).

        The new entry gets its OWN result cache, metrics, and SLO
        engine, but shares the device lock, the admission layer, and —
        decisively — the process-level shape-keyed program memo: the
        warmup below re-walks the exact bucket family the default
        model compiled, so a same-(V, d) model costs ZERO new XLA
        programs (``query_program_builds()`` is the proof the bench
        gates assert on). The ANN lifecycle stays a default-model
        feature; catalog models serve the exact path."""
        from glint_word2vec_tpu import load_model
        from glint_word2vec_tpu.streaming.publish import _GEN_RE

        if model is None:
            if model_dir is None:
                raise ValueError("add_model needs model or model_dir")
            model = load_model(model_dir)
        metrics = ServingMetrics()
        metrics.slo = SloEngine.default_serving(_DEVICE_PATHS)
        coalescer = _SynonymCoalescer(
            model, self._lock, max_batch=self.max_batch,
            metrics=metrics, cache_size=self.cache_size,
        )
        entry = ServedModel(
            model_id, model, coalescer, metrics, source_dir=model_dir
        )
        if generation is None and model_dir is not None:
            base = os.path.basename(os.path.normpath(model_dir))
            if _GEN_RE.match(base):
                generation = base
        if generation is not None:
            metrics.generation = generation
        self.catalog.install(entry)
        do_warm = self._do_warmup if warmup is None else bool(warmup)
        if do_warm and coalescer.can_batch:
            warm_ks, warm_lens, warm_rows = self._warm_params
            q_buckets = [
                1 << i for i in range(self.max_batch.bit_length())
            ]
            model.engine.warmup(
                q_buckets, warm_ks,
                sentence_lens=warm_lens, sentence_rows=warm_rows,
            )
        metrics.warmup_compiles = self._query_compiles(entry)
        self.catalog.enforce_budget()
        logger.info(
            "added model %r (%d words, dim %d, resident %s)",
            model_id, model.vocab.size, model.vector_size,
            entry.resident,
        )
        return entry

    def _models_summary(self) -> dict:
        """Per-model overview for /healthz and GET /models."""
        out = {}
        for mid, e in list(self.catalog.entries.items()):
            compiles = self._query_compiles(e)
            out[mid] = {
                "family": type(e.model).__name__,
                "vocab_size": e.model.vocab.size,
                "dim": e.model.vector_size,
                "resident": e.resident,
                "pinned": e.pins > 0,
                "generation": e.metrics.generation,
                "post_warmup_compiles": compiles
                - e.metrics.warmup_compiles,
            }
        return out

    def _models_doc(self) -> dict:
        return {
            "default": self.catalog.default_id,
            "models": self._models_summary(),
            "catalog": self.catalog.snapshot(),
        }

    def _entry_snapshot(self, entry: ServedModel) -> dict:
        """One model's full metrics snapshot + its residency state."""
        is_default = entry is self.catalog.default
        snap = entry.metrics.snapshot(
            self._query_compiles(entry),
            checkpoint=self._checkpoint_stats(entry),
            index_staleness=(
                self._index_staleness(entry) if is_default else None
            ),
        )
        snap["model_id"] = entry.model_id
        snap["resident"] = entry.resident
        # Integer twin of "resident" so the merged fleet view can fold
        # it additively (resident replica count per model) and the
        # Prometheus renderer maps ONE key in both shapes.
        snap["resident_replicas"] = 1 if entry.resident else 0
        snap["pinned"] = entry.pins > 0
        snap["resident_bytes"] = entry.resident_bytes()
        snap["stage_ins_total"] = entry.stage_ins
        snap["evictions_total"] = entry.evictions
        return snap

    def _metrics_doc(self) -> dict:
        """The top-level /metrics document: the default model's
        snapshot at the root (every pre-catalog consumer keeps
        parsing), plus per-model blocks and the catalog block."""
        doc = self._entry_snapshot(self.catalog.default)
        doc["models"] = {
            mid: self._entry_snapshot(e)
            for mid, e in list(self.catalog.entries.items())
        }
        doc["catalog"] = self.catalog.snapshot()
        return doc

    # -- approximate index lifecycle (ISSUE 12) ------------------------

    def _gate_index(self, engine, generation, *, index=None, syn0=None,
                    norms=None, queryable=None):
        """Measure recall@10 of the approximate path against the exact
        path on the SAME tables (live, or a staged generation's) and
        record the refresh on /metrics. For the LIVE index this also
        flips ``_ann_live``; for a staged one the caller adopts the
        verdict together with the tables. Returns (recall, gate_ok)."""
        eng_conf = engine._ann_conf or {}
        recall = engine.ann_recall_at_k(
            10, sample=self.ann_recall_sample, index=index, syn0=syn0,
            norms=norms, queryable=queryable, q_chunk=self.max_batch,
        )
        ok = recall >= self.ann_recall_gate
        stats = (
            engine.ann_stats() if index is None
            else {**index.stats(), "enabled": True}
        )
        self.metrics.record_index_refresh(
            stats, recall, ok, self.ann_recall_gate,
            eng_conf.get("nprobe", 0),
        )
        if index is None:
            self._ann_live = ok
        if not ok:
            logger.warning(
                "ANN recall gate FAILED (%.3f < %.3f)%s: exact path "
                "keeps serving",
                recall, self.ann_recall_gate,
                f" for {generation}" if generation else "",
            )
        else:
            logger.info(
                "ANN recall gate ok: %.3f >= %.3f", recall,
                self.ann_recall_gate,
            )
        return recall, ok

    def _index_staleness(
        self, entry: Optional[ServedModel] = None
    ) -> Optional[int]:
        """Table versions the live index is behind (None = no index)."""
        model = (entry or self.catalog.default).model
        eng = getattr(model, "engine", None)
        idx = getattr(eng, "ann_index", None)
        if eng is None or idx is None:
            return None
        return max(0, eng.table_version - idx.table_version)

    # -- hot-swap (ISSUE 10) ------------------------------------------

    def watch(self, watch_dir: str, poll_seconds: float = 1.0,
              current: Optional[str] = None,
              model_id: Optional[str] = None) -> SnapshotWatcher:
        """Follow a publish directory for ONE model (None = default):
        every new committed generation is staged off the request path
        and flipped into that model only. ``current`` names the
        generation already loaded at startup so the first poll doesn't
        re-load it."""
        entry = self._entry(model_id)
        w = SnapshotWatcher(
            self, watch_dir, poll_seconds, model_id=model_id
        )
        w.current = current
        if current is not None:
            entry.metrics.generation = current
        entry.watcher = w
        w.start()
        logger.info(
            "watching %s for published generations of model %r "
            "(poll %.2fs)", watch_dir, entry.model_id, poll_seconds,
        )
        return w

    def reload_generation(self, gen_dir: str,
                          generation: Optional[str] = None,
                          model_id: Optional[str] = None) -> None:
        """Hot-swap ONE model's served tables (None = the default) to
        a committed generation directory (a model dir: ``matrix/`` +
        ``words.txt``). Other catalog entries are untouched — their
        caches, generations, and swap counters never move.

        Staging — manifest verification, disk reads, building the
        re-sharded device arrays, and (with the index enabled)
        training the new generation's centroids, packing its member
        layout, and measuring its recall gate — runs on the calling
        thread with NO lock held, concurrent with live dispatches
        against the old tables. The flip is a few attribute
        assignments + one ``table_version`` tick under the device
        lock: in-flight dispatches drain first (no response mixes
        generations — the index flips WITH the tables, so a coarse
        probe can never rank one generation's members against
        another's vectors), the synonym result cache empties
        wholesale, and the same-shape tables AND index reuse every
        warmed compiled program (zero post-warmup compiles — the PR 2
        contract, preserved across swaps on both paths)."""
        from glint_word2vec_tpu.corpus.vocab import saved_model_vocabulary
        from glint_word2vec_tpu.models.word2vec import Word2VecModel

        entry = self._entry(model_id)
        faults.fire("serving.reload")
        if type(entry.model) is not Word2VecModel:
            raise ValueError(
                f"hot-swap supports the base word-level family only "
                f"(serving a {type(entry.model).__name__})"
            )
        # Pinned for the duration: the LRU must never stage out the
        # generation being swapped in (the rollout-hold guarantee).
        self.catalog.pin(model_id)
        try:
            engine = entry.model.engine
            staged = engine.stage_tables(os.path.join(gen_dir, "matrix"))
            meta = staged["meta"]
            vocab = saved_model_vocabulary(
                gen_dir,
                np.load(os.path.join(gen_dir, "matrix", "counts.npy")),
                int(meta["vocab_size"]) + int(
                    meta.get("extra_rows_assigned", 0)
                ),
            )
            staged_ann = None
            staged_ok = False
            if self.ann and entry is self.catalog.default:
                # Refresh the coarse index against the STAGED tables —
                # new centroids, fresh member packing, and the recall
                # gate all run off the request path; only the flip
                # below is held.
                staged_q = int(meta["vocab_size"]) + int(
                    meta.get("extra_rows_assigned", 0)
                )
                staged_norms = engine._norms(staged["syn0"])
                staged_ann = engine.ann_build(
                    staged["syn0"], staged_norms, staged_q
                )
                _, staged_ok = self._gate_index(
                    engine, generation, index=staged_ann,
                    syn0=staged["syn0"], norms=staged_norms,
                    queryable=staged_q,
                )
            with self._lock:
                engine.adopt_tables(staged)
                entry.model.vocab = vocab
                if staged_ann is not None:
                    engine.adopt_ann(staged_ann)
                    self._ann_live = staged_ok
            entry.metrics.record_swap(generation, ok=True)
            entry.source_dir = gen_dir
        finally:
            self.catalog.unpin(model_id)
        self.catalog.enforce_budget()
        logger.info(
            "hot-swapped %r to %s (%d words, table_version %d%s)",
            entry.model_id, generation or gen_dir, len(vocab.words),
            engine.table_version,
            ", index refreshed" if staged_ann is not None else "",
        )

    # -- overload protection ------------------------------------------

    def _admit(self) -> bool:
        """Claim one in-flight slot for a device-touching request;
        False = past the high-water mark, shed with 429."""
        if not self.max_inflight:
            return True
        with self._inflight_mu:
            if self._inflight >= self.max_inflight:
                return False
            self._inflight += 1
            self.metrics.record_inflight(self._inflight)
            return True

    def _release_slot(self) -> None:
        if not self.max_inflight:
            return
        with self._inflight_mu:
            self._inflight -= 1

    def _degraded(self) -> bool:
        """Whether the server is in degraded cache-only mode: the
        device lock has been continuously held past ``degraded_after``
        (a wedged or pathologically slow dispatch). Tracks entry
        transitions for the ``degraded_entered`` counter; exits
        automatically the moment the lock frees."""
        if self.degraded_after is None:
            return False
        d = self._lock.held_for() > self.degraded_after
        with self._inflight_mu:
            if d and not self._degraded_flag:
                self._degraded_flag = True
                self.metrics.record_degraded_entered()
                logger.warning(
                    "entering degraded cache-only mode: device lock "
                    "held > %.1fs", self.degraded_after,
                )
            elif not d:
                self._degraded_flag = False
        return d

    # -- SLO + anomaly flight recorder (ISSUE 18) ---------------------

    def _observe_request(self, entry: ServedModel, path: str,
                         seconds: float, status: int,
                         trace_id: Optional[str] = None) -> None:
        """Single funnel for per-request accounting, keyed to the
        request's MODEL: the latency histogram + SLO observation (with
        the exemplar trace id when the tail sampler kept the trace),
        then the SLO fast-burn flight-recorder trigger (throttled
        inside the engine)."""
        entry.metrics.observe(
            path, seconds, status=status, trace_id=trace_id
        )
        fl, slo = self.flight, entry.metrics.slo
        if fl is not None and slo is not None:
            for ep in slo.fast_burn_transitions():
                fl.trigger("slo_fast_burn", endpoint=ep)

    def _record_shed(self, reason: str,
                     entry: Optional[ServedModel] = None) -> None:
        """Count one shed on the request's model and fire the flight
        recorder on the burst EDGE (one bundle per burst, not one per
        shed)."""
        (entry or self.catalog.default).metrics.record_shed(reason)
        if self._shed_burst.note() and self.flight is not None:
            self.flight.trigger("shed_burst", reason=reason)

    def enable_flight_recorder(
        self, out_dir: str, *, window_seconds: float = 30.0,
        min_interval_seconds: float = 60.0,
    ) -> FlightRecorder:
        """Install the anomaly flight recorder: on a shed burst or an
        SLO fast-burn edge it bundles this process's recent span ring
        and full metrics snapshot into ``out_dir`` for postmortem."""
        fl = FlightRecorder(
            out_dir, window_seconds=window_seconds,
            min_interval_seconds=min_interval_seconds,
        )
        fl.add_source("spans", self._flight_spans)
        fl.add_source("metrics", self._flight_metrics)
        self.flight = fl
        return fl

    def _flight_spans(self, window_seconds: float) -> dict:
        rec = obs_events.get_recorder()
        if rec is None:
            return {"events": [], "anchor": None}
        return {
            "events": rec.recent_events(window_seconds),
            "anchor": {"wall_t0": rec.wall_t0, "mono_t0": rec.mono_t0},
        }

    def _flight_metrics(self, window_seconds: float) -> dict:
        return self._metrics_doc()

    # -- warmup / compile accounting ----------------------------------

    def _checkpoint_stats(
        self, entry: Optional[ServedModel] = None
    ) -> dict:
        """Checkpoint telemetry of the served engine (ISSUE 5): a model
        served straight out of a training process reports its snapshot
        pipeline; a freshly-loaded model reports Nones. Never raises —
        /metrics must stay up regardless."""
        model = (entry or self.catalog.default).model
        eng = getattr(model, "engine", None)
        stats = getattr(eng, "checkpoint_stats", None)
        if stats is None:
            return {}
        try:
            return stats()
        except Exception:
            return {}

    def _query_compiles(
        self, entry: Optional[ServedModel] = None
    ) -> int:
        """Total query-op shapes compiled across one model's engines
        (the training engine plus FastText's lazily-built composed query
        engine, when it exists). Per-engine first-seen counts: a shape
        another model already built still counts here (that is the
        warmed-family contract each model asserts individually);
        process-level build counts live on the catalog snapshot."""
        model = (entry or self.catalog.default).model
        engines = [getattr(model, "engine", None)]
        qeng = getattr(model, "_qeng", None)
        if qeng is not None:
            engines.append(qeng)
        return sum(
            int(getattr(e, "query_compiles", 0) or 0)
            for e in engines
            if e is not None
        )

    def _warmup(
        self, warm_ks, warm_sentence_lens, warm_sentence_rows
    ) -> None:
        """Compile the serving shape family before the port binds (only
        the base word-level family — an overriding family keeps its own
        dispatch shapes and its own single-query path)."""
        if not self._coalescer.can_batch:
            return
        q_buckets = [1 << i for i in range(self.max_batch.bit_length())]
        t0 = time.time()
        n = self.model.engine.warmup(
            q_buckets,
            warm_ks,
            sentence_lens=warm_sentence_lens,
            sentence_rows=warm_sentence_rows,
        )
        if self.ann and self.model.engine.ann_index is not None:
            # The approximate dispatch family (coarse score + bucketed
            # rerank + the promotion-path assignment program) warms
            # with the exact family, BEFORE the port binds — the
            # zero-post-warmup-compiles contract covers both paths
            # (ISSUE 12 satellite).
            n += self.model.engine.warmup_ann(
                q_buckets=q_buckets, k_buckets=warm_ks,
            )
        logger.info(
            "serving warmup: %d shapes compiled in %.1fs "
            "(Q buckets %s, k buckets %s%s)",
            n, time.time() - t0, q_buckets, tuple(warm_ks),
            ", +ann" if self.ann else "",
        )

    # -- request dispatch ---------------------------------------------

    def _dispatch(self, path: str, req: dict, model=None):
        if path != "/shutdown":
            faults.fire("serving.dispatch")
        m = model if model is not None else self.model
        if path == "/analogy":
            return [
                [w, float(s)]
                for w, s in m.analogy(
                    req.get("positive", []),
                    req.get("negative", []),
                    int(req.get("num", 10)),
                )
            ]
        if path == "/vector":
            return [float(x) for x in m.transform(req["word"])]
        if path == "/transform":
            vecs = m.transform_sentences(req["sentences"])
            return [[float(x) for x in v] for v in np.asarray(vecs)]
        if path == "/shutdown":
            return {"status": "shutting down"}
        return None

    # -- lifecycle -----------------------------------------------------

    def _tighten_gil_switch(self) -> None:
        # The serving process is a convoy of short GIL-holding sections
        # (HTTP parse, JSON, event wakeups) across one handler thread
        # per connection; at CPython's default 5ms switch interval each
        # round of N coalesced responses can pay N preemption quanta of
        # pure scheduling latency. 1ms keeps the handoff tight — worth
        # ~5x on p95 at 16 clients on a 2-core host (SERVING_BENCH).
        # Process-global, so taken only once serving actually starts
        # and restored by stop().
        if self._prev_switch is None:
            self._prev_switch = sys.getswitchinterval()
            sys.setswitchinterval(0.001)

    def serve_forever(self) -> None:
        logger.info("serving model on %s:%d", self.host, self.port)
        self._tighten_gil_switch()
        self._httpd.serve_forever()

    def start_background(self) -> None:
        self._tighten_gil_switch()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        for e in list(self.catalog.entries.values()):
            if e.watcher is not None:
                e.watcher.stop()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._prev_switch is not None:
            sys.setswitchinterval(self._prev_switch)
            self._prev_switch = None


def serve_model_dir(
    model_dir: Optional[str],
    host: str = "127.0.0.1",
    port: int = 8801,
    *,
    max_batch: int = 64,
    warmup: bool = True,
    cache_size: int = 65536,
    max_inflight: int = 256,
    request_deadline: Optional[float] = 30.0,
    degraded_after: Optional[float] = 5.0,
    watch_dir: Optional[str] = None,
    watch_poll: float = 1.0,
    ann: bool = False,
    ann_clusters: int = -1,
    ann_nprobe: int = 8,
    ann_iters: int = 6,
    ann_sample: int = 65536,
    ann_recall_gate: float = 0.95,
    ann_recall_sample: int = 64,
    port_file: Optional[str] = None,
    trace_log: Optional[str] = None,
    flight_dir: Optional[str] = None,
    models: Optional[dict] = None,
    model_memory_budget=None,
    watch_models: Optional[str] = None,
) -> None:
    """Load a saved model (any family) and serve it until killed.

    ``watch_dir`` follows a streaming trainer's publish directory:
    ``model_dir=None`` then boots from its newest committed generation
    (waiting for the first one to appear), and every later generation
    hot-swaps in under load. ``port_file`` writes the bound
    ``{"host", "port"}`` atomically once the server is warmed and
    listening — the fleet launcher's (and CI's) readiness barrier for
    ``--port 0`` ephemeral replicas. ``trace_log`` installs a
    process-wide event recorder with a size-rotated JSONL sink (the
    per-replica half of distributed request tracing: ``cli
    trace-merge`` stitches these across processes); ``flight_dir``
    arms the anomaly flight recorder.

    Multi-model (ISSUE 20): ``models`` maps extra model ids to model
    dirs served from this same process; ``model_memory_budget``
    ("512mb", "2gb", or bytes) bounds their combined device residency
    with LRU stage-out; ``watch_models`` names a catalog root whose
    ``<id>/LATEST.json`` subdirectories each get their own model +
    per-model SnapshotWatcher (one trainer's publish rolls only its
    model)."""
    from glint_word2vec_tpu import load_model

    if trace_log:
        obs_events.set_recorder(
            obs_events.EventRecorder(jsonl_path=trace_log)
        )
    current = None
    model = None
    if model_dir is None:
        if watch_dir is None:
            raise ValueError("model_dir or watch_dir required")
        from glint_word2vec_tpu.streaming.publish import resolve_latest

        while True:
            gen_dir = resolve_latest(watch_dir)
            if gen_dir is None:
                logger.info(
                    "waiting for a first committed generation in %s",
                    watch_dir,
                )
                time.sleep(max(0.05, watch_poll))
                continue
            try:
                model = load_model(gen_dir)
            except Exception as e:
                # Retention can prune this generation while we read it
                # (a fast publish cadence and a slow cold load): chase
                # the pointer instead of dying at boot. An unchanged
                # pointer to a still-present dir is real corruption.
                if (
                    resolve_latest(watch_dir) != gen_dir
                    or not os.path.isdir(gen_dir)
                ):
                    logger.warning(
                        "boot load of %s failed (%s) — generation "
                        "pruned mid-read; chasing the pointer",
                        gen_dir, e,
                    )
                    time.sleep(max(0.05, watch_poll))
                    continue
                raise
            model_dir = gen_dir
            current = os.path.basename(gen_dir)
            break
    elif watch_dir is not None:
        # An explicit --model that names a generation inside the
        # watched dir is already loaded: seed the watcher with it so
        # the first poll doesn't redundantly re-stage and hot-swap the
        # very tables being served (spurious swap count + cache flush).
        md = os.path.abspath(model_dir)
        if os.path.dirname(md) == os.path.abspath(watch_dir):
            current = os.path.basename(md)
    if model is None:
        model = load_model(model_dir)
    if current is None and model_dir is not None:
        # Booting straight from a published generation dir (the fleet
        # supervisor's coordinated relaunch path): stamp the served
        # generation so the merged fleet view doesn't read "mixed"
        # forever just because this process never hot-swapped.
        from glint_word2vec_tpu.streaming.publish import _GEN_RE

        base = os.path.basename(os.path.normpath(model_dir))
        if _GEN_RE.match(base):
            current = base
    server = ModelServer(
        model, host=host, port=port,
        max_batch=max_batch, warmup=warmup, cache_size=cache_size,
        max_inflight=max_inflight, request_deadline=request_deadline,
        degraded_after=degraded_after,
        ann=ann, ann_clusters=ann_clusters, ann_nprobe=ann_nprobe,
        ann_iters=ann_iters, ann_sample=ann_sample,
        ann_recall_gate=ann_recall_gate,
        ann_recall_sample=ann_recall_sample,
    )
    if flight_dir:
        server.enable_flight_recorder(flight_dir)
    if model_memory_budget is not None:
        server.catalog.budget_bytes = parse_memory_budget(
            model_memory_budget
        )
    # Stamp the default model's snapshot source so the LRU could stage
    # it back in were it ever unpinned (it is pinned by default).
    server.catalog.default.source_dir = model_dir
    for mid in sorted(models or {}):
        server.add_model(mid, model_dir=(models or {})[mid])
    if watch_dir is not None:
        server.watch(watch_dir, poll_seconds=watch_poll, current=current)
    elif current is not None:
        server.metrics.generation = current
    if watch_models:
        from glint_word2vec_tpu.streaming.publish import (
            discover_model_publish_dirs,
            resolve_latest as _resolve_latest,
        )

        for mid, pub in sorted(
            discover_model_publish_dirs(watch_models).items()
        ):
            if mid == DEFAULT_MODEL_ID:
                w_mid = None
            elif mid in server.catalog.entries:
                w_mid = mid
            else:
                gen_dir = _resolve_latest(pub)
                if gen_dir is None:
                    logger.info(
                        "watch-models: %r has a pointer but no "
                        "committed generation — skipped", mid,
                    )
                    continue
                server.add_model(mid, model_dir=gen_dir)
                w_mid = mid
            entry = server._entry(w_mid)
            if entry.watcher is not None:
                continue  # --watch-checkpoint already covers it
            # Seed the watcher with the generation already loaded so
            # its first poll doesn't redundantly re-stage it.
            cur = None
            src = entry.source_dir
            if src is not None and os.path.dirname(
                os.path.abspath(src)
            ) == os.path.abspath(pub):
                cur = os.path.basename(os.path.normpath(src))
            server.watch(
                pub, poll_seconds=watch_poll, current=cur,
                model_id=w_mid,
            )
    if port_file:
        from glint_word2vec_tpu.utils import atomic_write_json

        atomic_write_json(
            port_file,
            {
                "host": server.host,
                "port": server.port,
                # Launch-generation handshake: the fleet supervisor
                # refuses a port file whose generation is not the one
                # it just launched (a stale file from the previous
                # incarnation must never be adopted as readiness).
                "fleet_generation": server.fleet_generation,
            },
        )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.stop()
