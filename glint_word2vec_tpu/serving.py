"""Persistent model serving: the separate-PS-cluster deployment, restated.

The reference's second deployment topology keeps a Glint parameter-server
cluster alive independently of any one training/serving app
(README.md:45-57: `glint.Main` launched standalone; trainers and
transformers connect by host and come and go; the cluster survives
`model.stop()` unless a client passes ``terminateOtherClients=true``,
mllib:664-667). The TPU-native restatement: the model lives in one serving
process's device memory, exposed over HTTP; client apps (trainers, batch
jobs, notebooks) query it without loading the tables themselves, and their
lifecycles don't affect it.

Endpoints (JSON in/out, stdlib-only server):

  GET  /healthz            -> {"status": "ok", "vocab_size": V, "dim": d, ...}
  POST /synonyms           {"word": w, "num": k}
  POST /synonyms_vector    {"vector": [...], "num": k}
  POST /analogy            {"positive": [...], "negative": [...], "num": k}
  POST /vector             {"word": w}            (strict OOV -> 404)
  POST /transform          {"sentences": [[w, ...], ...]}  (OOV dropped)
  POST /shutdown           stops the server (the terminateOtherClients
                           analogue: an explicit, remote, cross-client kill)

Start from the CLI:  glint-word2vec-tpu serve --model DIR --port 8801
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)


class _SynonymCoalescer:
    """Leader-elected micro-batching for the synonym endpoints.

    Device queries are serialized by the server lock, so under N
    concurrent clients each /synonyms request used to wait for N-1
    single-query dispatches (QPS flat in N). Here every waiting request
    lands in a pending list; whichever thread next wins the device lock
    becomes leader, drains the list, answers ALL of them with ONE
    ``engine.pull`` + ONE ``find_synonyms_batch`` dispatch (the batch
    top-k the reference lacks — it loops findSynonyms, ml:375-420), and
    wakes the waiters. Exclusion semantics match find_synonyms exactly
    (fetch num+1, drop the query word, truncate).

    Only the base word-level family batches: a subclass overriding
    ``find_synonyms``/``transform`` (FastText serves OOV words through
    subwords) keeps its own semantics via the single-query path.
    """

    def __init__(self, model, device_lock):
        from glint_word2vec_tpu.models.word2vec import Word2VecModel

        self.model = model
        self.device_lock = device_lock
        self._mu = threading.Lock()
        self._pending: list = []
        self.can_batch = (
            isinstance(model, Word2VecModel)
            and type(model).find_synonyms is Word2VecModel.find_synonyms
            and type(model).transform is Word2VecModel.transform
        )

    def query(self, word=None, vector=None, num: int = 10):
        if not self.can_batch:
            # Overriding families define their own semantics end to end
            # (FastText OOV-by-subwords, its own num validation).
            with self.device_lock:
                if word is not None:
                    return self.model.find_synonyms(word, num)
                return self.model.find_synonyms_vector(vector, num)
        if num <= 0:
            # Exact single-query behavior for the base family.
            # find_synonyms(w, num): transform(w) runs FIRST (OOV ->
            # KeyError -> 404), then find_synonyms_vector(vec, num+1)
            # raises unless num+1 > 0 — so num=0 with a known word is []
            # (truncation) and num<0 is a 400. The bare vector endpoint
            # always raises on num<=0.
            if word is not None:
                if word not in self.model.vocab.word_index:
                    raise KeyError(f"word {word!r} not in vocabulary")
                if num == 0:
                    return []
            raise ValueError("num must be > 0")
        req = {
            "word": word, "vector": vector, "num": int(num),
            "event": threading.Event(), "result": None, "error": None,
        }
        with self._mu:
            self._pending.append(req)
        # Leaders set every batched event BEFORE releasing the device
        # lock, so a waiter whose result is already in hand must not
        # queue behind the next leader's whole dispatch (lock convoy —
        # it showed up as a 7x p95 inflation at 16 clients).
        if not req["event"].is_set():
            with self.device_lock:
                if not req["event"].is_set():
                    with self._mu:
                        batch, self._pending = self._pending, []
                    if batch:
                        self._process(batch)
        req["event"].wait()
        if req["error"] is not None:
            raise req["error"]
        return req["result"]

    def _process(self, batch) -> None:
        m = self.model
        live = []
        for r in batch:
            # Validation failures must fail ONLY their own request: an
            # exception escaping here would strand every co-batched
            # waiter on an event that never fires.
            try:
                if r["word"] is not None:
                    i = m.vocab.word_index.get(r["word"])
                    if i is None:
                        raise KeyError(
                            f"word {r['word']!r} not in vocabulary"
                        )
                    r["idx"] = i
                else:
                    v = np.asarray(r["vector"], dtype=np.float32)
                    if v.shape != (m.vector_size,):
                        raise ValueError(
                            f"vector must have shape ({m.vector_size},), "
                            f"got {v.shape}"
                        )
                    r["vec"] = v
            except KeyError as e:
                r["error"] = e
                r["event"].set()
                continue
            except Exception as e:
                # Anything np.asarray can throw on garbage (TypeError,
                # ragged-list ValueError) is a bad request, not a 500.
                r["error"] = ValueError(f"bad vector: {e}")
                r["event"].set()
                continue
            live.append(r)
        try:
            if not live:
                return
            word_rows = [r for r in live if "idx" in r]
            if word_rows:
                pulled = np.asarray(
                    m.engine.pull(
                        np.asarray([r["idx"] for r in word_rows], np.int32)
                    ),
                    np.float32,
                )
                for r, v in zip(word_rows, pulled):
                    r["vec"] = v
            k = max(
                r["num"] + (1 if r["word"] is not None else 0) for r in live
            )
            hits = m.find_synonyms_batch(
                np.stack([r["vec"] for r in live]), min(k, m.vocab.size)
            )
            for r, hs in zip(live, hits):
                if r["word"] is not None:
                    hs = [(w, s) for w, s in hs if w != r["word"]]
                r["result"] = hs[: r["num"]]
        except Exception as e:  # pragma: no cover - device failure path
            for r in live:
                if r["error"] is None and r["result"] is None:
                    r["error"] = e
        finally:
            for r in live:
                r["event"].set()


class ModelServer:
    """Holds one loaded model and serves its query surface over HTTP."""

    def __init__(self, model, host: str = "127.0.0.1", port: int = 8801):
        self.model = model
        # Device queries are jitted functions on shared tables; serialize
        # them (the reference's PS likewise processes a shard's requests
        # on its actor mailbox, one at a time). The synonym endpoints
        # additionally coalesce concurrent waiters into one batched
        # dispatch (_SynonymCoalescer).
        self._lock = threading.Lock()
        self._coalescer = _SynonymCoalescer(model, self._lock)
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route to logging, not stderr
                logger.debug("serve: " + fmt, *args)

            def _send(self, code: int, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    m = server.model
                    self._send(
                        200,
                        {
                            "status": "ok",
                            "family": type(m).__name__,
                            "vocab_size": m.vocab.size,
                            "dim": m.vector_size,
                        },
                    )
                else:
                    self._send(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                except (ValueError, json.JSONDecodeError) as e:
                    return self._send(400, {"error": f"bad request: {e}"})
                try:
                    if self.path == "/synonyms":
                        out = [
                            [w, float(s)]
                            for w, s in server._coalescer.query(
                                word=req["word"],
                                num=int(req.get("num", 10)),
                            )
                        ]
                    elif self.path == "/synonyms_vector":
                        out = [
                            [w, float(s)]
                            for w, s in server._coalescer.query(
                                vector=req["vector"],
                                num=int(req.get("num", 10)),
                            )
                        ]
                    else:
                        with server._lock:
                            out = server._dispatch(self.path, req)
                except KeyError as e:
                    return self._send(
                        404, {"error": e.args[0] if e.args else str(e)}
                    )
                except ValueError as e:
                    return self._send(400, {"error": str(e)})
                if out is None:
                    return self._send(404, {"error": f"no route {self.path}"})
                self._send(200, out)
                if self.path == "/shutdown":
                    threading.Thread(target=server.stop, daemon=True).start()

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    # -- request dispatch ---------------------------------------------

    def _dispatch(self, path: str, req: dict):
        m = self.model
        if path == "/analogy":
            return [
                [w, float(s)]
                for w, s in m.analogy(
                    req.get("positive", []),
                    req.get("negative", []),
                    int(req.get("num", 10)),
                )
            ]
        if path == "/vector":
            return [float(x) for x in m.transform(req["word"])]
        if path == "/transform":
            vecs = m.transform_sentences(req["sentences"])
            return [[float(x) for x in v] for v in np.asarray(vecs)]
        if path == "/shutdown":
            return {"status": "shutting down"}
        return None

    # -- lifecycle -----------------------------------------------------

    def serve_forever(self) -> None:
        logger.info("serving model on %s:%d", self.host, self.port)
        self._httpd.serve_forever()

    def start_background(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def serve_model_dir(
    model_dir: str, host: str = "127.0.0.1", port: int = 8801
) -> None:
    """Load a saved model (any family) and serve it until killed."""
    from glint_word2vec_tpu import load_model

    server = ModelServer(load_model(model_dir), host=host, port=port)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.stop()
