"""Self-healing horizontal serving: N supervised replicas behind one
breaker-aware load balancer, with rolling generation rollout and a
shadow-canary promotion gate.

PR 12 put N replica processes behind one round-robin proxy; this module
adds the robustness half (ISSUE 14) — the serving tier's PR 7:

* :class:`LoadBalancer` — the stdlib raw-socket proxy, now with a
  per-replica :class:`ReplicaBreaker` (closed / open / half-open)
  driven by an active health prober AND the data plane's own
  connection verdicts: K consecutive failures eject a replica from
  rotation (so a bouncing replica costs zero client latency instead of
  a timeout per round-robin turn), a cooldown half-opens it for prober
  trials, and M consecutive successes readmit it. Overload sheds
  (429/503) still retry onto the next replica and relay honest
  backpressure on exhaustion.

* :class:`FleetSupervisor` — the PR 7 supervisor machinery on the
  serving tier: launches the replica subprocesses, watches liveness
  two ways (``waitpid`` for crashes; sustained probe failure for
  hangs, with the ``GLINT_FLEET_GEN`` generation handshake so a stale
  pre-restart process can never answer for the new one), and
  relaunches dead or hung replicas from the fleet's current model
  directory under capped exponential backoff and a per-replica restart
  budget. A replica out of budget is left down and counted; the fleet
  serves from the survivors.

* :class:`RolloutCoordinator` — when ``LATEST.json`` moves, replicas
  are swapped ONE AT A TIME: drain via breaker hold, ``POST /reload``,
  wait healthy + warm (the swap added zero post-warmup compiles),
  readmit, next — fleet capacity never drops below N-1, and a
  generation that fails to stage halts the rollout with the old
  generation still serving everywhere else.

* Shadow-canary promotion gate (ROADMAP item 5's loop, closed): before
  the rollout proceeds, the candidate generation is staged on ONE held
  replica which never sees live traffic; a sampled slice of live
  queries is mirrored to it and scored for top-k agreement against the
  live fleet, alongside operator-defined probe queries
  (vienna/berlin-style synonym + capital-of analogy checks,
  QUALITY.json-style). Regression means automatic hold-back: the
  canary is restored to the live generation, the candidate is counted,
  exposed on ``/metrics``, and left on disk for postmortem.

Fault points ``fleet.replica_probe`` / ``fleet.rollout_step`` (and
``serving.reload`` on the replica side) drill every window;
``scripts/fleet_drill.py`` records FLEET_BENCH.json.

Replicas are plain ``serve`` processes: nothing here is in their code
path, so a balancer crash leaves N independently addressable servers.
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import random
import socket
import subprocess
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from glint_word2vec_tpu.obs import events as obs_events
from glint_word2vec_tpu.obs.slo import FlightRecorder
from glint_word2vec_tpu.parallel.supervisor import (
    capped_backoff,
    terminate_process,
)
from glint_word2vec_tpu.utils import faults

logger = logging.getLogger(__name__)


def _read_request(sock, buf: bytearray):
    """Read one HTTP/1.1 request off a keep-alive socket: returns
    (method, path, lowercase-header dict, body) or None on a clean
    close between requests. Raises on transport errors or malformed
    framing. Content-Length framing only — the serving stack (and
    every client of it) never chunks."""
    while True:
        head_end = buf.find(b"\r\n\r\n")
        if head_end >= 0:
            break
        chunk = sock.recv(65536)
        if not chunk:
            if buf:
                raise ConnectionError("client closed mid-request")
            return None
        buf += chunk
    head = bytes(buf[:head_end]).decode("latin-1")
    lines = head.split("\r\n")
    parts = lines[0].split(None, 2)
    if len(parts) < 3:
        raise ValueError(f"malformed request line: {lines[0]!r}")
    method, path = parts[0], parts[1]
    headers = {}
    for line in lines[1:]:
        k, _, v = line.partition(":")
        headers[k.strip().lower()] = v.strip()
    clen = int(headers.get("content-length", 0))
    body_end = head_end + 4 + clen
    while len(buf) < body_end:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("client closed mid-body")
        buf += chunk
    body = bytes(buf[head_end + 4 : body_end])
    del buf[:body_end]
    return method, path, headers, body

#: Statuses that mean "this replica cannot take the request right now,
#: another one might": bounded admission / degraded mode (429), plus
#: 503 for a replica mid-restart behind a stale port. 404/400/504 are
#: NOT retried — they are answers about the request, not the replica.
_SHED_STATUSES = frozenset((429, 503))


class ReplicaBreaker:
    """Per-replica circuit breaker: closed / open / half-open.

    Fed by BOTH failure signals — the active health prober's verdicts
    and the data plane's own connection errors. ``fail_threshold``
    consecutive failures open the breaker (the replica is ejected from
    rotation, so clients stop paying its timeouts); after
    ``open_seconds`` the prober half-opens it with trial probes, and
    ``success_threshold`` consecutive successes re-close it. A
    half-open trial failure re-opens immediately.

    Separately from the state machine, an **administrative hold**
    (:meth:`hold` / :meth:`release`) takes the replica out of client
    rotation regardless of health — the rollout coordinator's drain
    seam, and what keeps a canary staging a CANDIDATE generation from
    ever serving live traffic.
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, *, fail_threshold: int = 3,
                 success_threshold: int = 2,
                 open_seconds: float = 2.0):
        self.fail_threshold = max(1, int(fail_threshold))
        self.success_threshold = max(1, int(success_threshold))
        self.open_seconds = float(open_seconds)
        self._mu = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0          # consecutive (closed-state) failures
        self._trial_successes = 0   # consecutive half-open successes
        self._opened_at: Optional[float] = None
        self._failing_since: Optional[float] = None
        self._held = 0
        self._opened_total = 0
        self._reopened_total = 0
        self._closed_total = 0
        self._probe_failures = 0
        self._probe_successes = 0
        #: Invoked on every CLOSED -> OPEN transition (a genuinely
        #: healthy replica just got ejected), OUTSIDE ``_mu`` — the
        #: flight recorder's breaker-trip snapshot hook scrapes every
        #: replica and must never run under the breaker lock. Cooldown
        #: refreshes and half-open re-opens do not re-fire.
        self.on_open: Optional[Callable[[], None]] = None

    def _fire_on_open(self) -> None:
        cb = self.on_open
        if cb is not None:
            try:
                cb()
            except Exception:  # pragma: no cover - defensive
                logger.exception("breaker on_open hook failed")

    def record_failure(self, probe: bool = False) -> None:
        """One failed probe or data-plane connection attempt."""
        opened = False
        with self._mu:
            if probe:
                self._probe_failures += 1
            if self._failing_since is None:
                self._failing_since = time.monotonic()
            if self._state == self.HALF_OPEN:
                # A failed trial re-opens immediately: the replica is
                # still bouncing, restart its cooldown.
                self._state = self.OPEN
                self._opened_at = time.monotonic()
                self._trial_successes = 0
                self._reopened_total += 1
            elif self._state == self.CLOSED:
                self._failures += 1
                if self._failures >= self.fail_threshold:
                    self._state = self.OPEN
                    self._opened_at = time.monotonic()
                    self._opened_total += 1
                    opened = True
        if opened:
            self._fire_on_open()

    def record_success(self, probe: bool = False) -> None:
        """One healthy probe answer or successful proxied exchange."""
        with self._mu:
            if probe:
                self._probe_successes += 1
            self._failing_since = None
            if self._state == self.HALF_OPEN:
                self._trial_successes += 1
                if self._trial_successes >= self.success_threshold:
                    self._state = self.CLOSED
                    self._failures = 0
                    self._trial_successes = 0
                    self._opened_at = None
                    self._closed_total += 1
            elif self._state == self.CLOSED:
                self._failures = 0

    def maybe_half_open(self) -> bool:
        """Prober seam: move open -> half-open once the cooldown
        elapsed. Returns True when the replica should receive a trial
        probe (it is half-open), False while still cooling (no traffic,
        no probes) or not open at all."""
        with self._mu:
            if (self._state == self.OPEN and self._opened_at is not None
                    and time.monotonic() - self._opened_at
                    >= self.open_seconds):
                self._state = self.HALF_OPEN
                self._trial_successes = 0
            return self._state == self.HALF_OPEN

    def force_open(self) -> None:
        """Supervisor seam: the replica process is KNOWN dead or
        restarting — eject immediately and keep refreshing the cooldown
        so no trial traffic flows until the supervisor readmits it."""
        opened = False
        with self._mu:
            if self._state == self.CLOSED:
                self._opened_total += 1
                opened = True
            self._state = self.OPEN
            self._opened_at = time.monotonic()
            self._trial_successes = 0
        if opened:
            self._fire_on_open()

    def trial(self) -> None:
        """Supervisor seam: a relaunched replica adopted a fresh
        address — go straight to half-open so it earns readmission
        through ``success_threshold`` probe successes (the PR 7
        don't-trust-a-fresh-worker pattern)."""
        with self._mu:
            self._state = self.HALF_OPEN
            self._trial_successes = 0
            self._failures = 0
            self._failing_since = None

    def hold(self) -> None:
        """Administrative ejection (rollout drain / canary staging)."""
        with self._mu:
            self._held += 1

    def release(self) -> None:
        with self._mu:
            self._held = max(0, self._held - 1)

    def clear_holds(self) -> None:
        """Supervisor seam, called when a RELAUNCHED replica's fresh
        address is adopted: any hold belonged to its previous
        incarnation (a rollout drain or canary staging that died under
        it) and the new process boots from the fleet's promoted
        generation — leaving the hold would park the replica serving
        nothing forever."""
        with self._mu:
            self._held = 0

    def held(self) -> bool:
        with self._mu:
            return self._held > 0

    def eligible(self) -> bool:
        """Whether client traffic may route here: closed and not
        administratively held."""
        with self._mu:
            return self._state == self.CLOSED and self._held == 0

    def state(self) -> str:
        with self._mu:
            return self._state

    def failing_for(self) -> float:
        """Seconds of CONTINUOUS failure (0.0 while healthy) — the
        fleet supervisor's hung-replica signal."""
        with self._mu:
            fs = self._failing_since
            return 0.0 if fs is None else time.monotonic() - fs

    def snapshot(self) -> dict:
        with self._mu:
            fs = self._failing_since
            return {
                "state": self._state,
                "held": self._held > 0,
                "consecutive_failures": self._failures,
                "trial_successes": self._trial_successes,
                "opened_total": self._opened_total,
                "reopened_total": self._reopened_total,
                "closed_total": self._closed_total,
                "probe_failures_total": self._probe_failures,
                "probe_successes_total": self._probe_successes,
                "failing_seconds": (
                    round(time.monotonic() - fs, 2)
                    if fs is not None else 0.0
                ),
            }


class _ReplicaConn:
    """One persistent keep-alive socket to a replica with a minimal
    HTTP/1.1 reader — the balancer's per-request cost IS the fleet's
    overhead floor, so the proxy hop skips ``http.client`` entirely.
    Owned by exactly one handler thread (per-thread pools), so no
    locking. The replica always answers Content-Length-framed JSON
    (serving.py's ``_send``)."""

    __slots__ = ("host", "port", "timeout", "addr_version", "_sock",
                 "_buf", "_sent", "_prefix")

    def __init__(self, host: str, port: int, timeout: float,
                 addr_version: int = 0):
        self.host, self.port, self.timeout = host, port, timeout
        #: Balancer address-table version this connection was built
        #: against: a supervisor relaunch bumps it, and the pool drops
        #: conns whose version is stale (a relaunched replica lives on
        #: a fresh ephemeral port).
        self.addr_version = addr_version
        self._sock = None
        self._buf = bytearray()
        self._sent = False
        self._prefix = (
            f"Host: {host}:{port}\r\n"
            "Content-Type: application/json\r\n"
            "Content-Length: "
        )

    def _connect(self):
        s = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )
        # NODELAY: requests/responses are small multi-segment writes;
        # Nagle + delayed ACK turns each proxied call into a ~40ms
        # stall otherwise (the PR 2 serving-side fix, outbound twin).
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s
        self._buf.clear()
        return s

    def roundtrip(self, method: str, path: str, body: bytes,
                  retryable: Optional[bool] = None,
                  trace_id: Optional[str] = None):
        """One request/response exchange; returns (status, body,
        header-dict with lowercase keys). Raises on any transport
        error (caller drops the connection and tries the next
        replica). ``trace_id`` propagates the balancer's request trace
        to the replica (the ``X-Glint-Trace`` wire header — ISSUE 18).

        A stale keep-alive socket after a replica bounce fails in one
        of two places: the send (nothing reached a handler — always
        safe to retry on a fresh connection) or the receive AFTER a
        locally-"successful" send into a dead socket's buffer. The
        recv-side retry is taken exactly once and only for idempotent
        requests (GETs by default; override with ``retryable``) — a
        bounced replica then costs the client nothing instead of a
        surfaced transport error."""
        if retryable is None:
            retryable = method == "GET"
        trace_hdr = (
            f"{obs_events.TRACE_HEADER}: {trace_id}\r\n"
            if trace_id else ""
        )
        req = (
            f"{method} {path} HTTP/1.1\r\n{trace_hdr}{self._prefix}"
            f"{len(body)}\r\n\r\n"
        ).encode("latin-1") + body
        try:
            return self._exchange(req)
        except OSError:
            if self._sent and not retryable:
                raise
            self.close()
            self._connect()
            return self._exchange(req)

    def _exchange(self, req: bytes):
        sock = self._sock or self._connect()
        self._sent = False
        sock.sendall(req)
        self._sent = True
        buf = self._buf
        while True:
            head_end = buf.find(b"\r\n\r\n")
            if head_end >= 0:
                break
            chunk = sock.recv(65536)
            if not chunk:
                raise ConnectionError("replica closed mid-response")
            buf += chunk
        head = bytes(buf[:head_end]).decode("latin-1")
        lines = head.split("\r\n")
        status = int(lines[0].split(None, 2)[1])
        headers = {}
        clen = 0
        for line in lines[1:]:
            k, _, v = line.partition(":")
            k = k.strip().lower()
            v = v.strip()
            headers[k] = v
            if k == "content-length":
                clen = int(v)
        body_end = head_end + 4 + clen
        while len(buf) < body_end:
            chunk = sock.recv(65536)
            if not chunk:
                raise ConnectionError("replica closed mid-body")
            buf += chunk
        rbody = bytes(buf[head_end + 4 : body_end])
        del buf[:body_end]
        return status, rbody, headers

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None


class LoadBalancer:
    """Round-robin HTTP proxy over serving replicas with per-replica
    circuit breakers, overload-aware retry, and a merged fleet
    exposition.

    Routes:
      GET  /healthz   fleet health: replicas up/total (200 while >= 1 up)
      GET  /metrics   merged fleet snapshot (JSON; ?format=prometheus
                      renders the merged serving exposition + the
                      glint_fleet_* balancer/breaker/rollout families)
      POST /shutdown  fan-out shutdown to every replica, then stop
      anything else   proxied to a replica (round robin over CLOSED
                      breakers; sheds retried on the next replica,
                      exhaustion relays the shed; open breakers are a
                      last resort, held replicas never serve)
    """

    #: Same-replica retries for a connection-refused inside a KNOWN
    #: restart window (the supervisor may land the relaunched
    #: replica's fresh address mid-retry).
    RESTART_RETRIES = 3
    RESTART_RETRY_BASE = 0.1

    #: ``replicas`` entries are replaced wholesale (one atomic tuple
    #: store) by ``set_replica_address`` under the lock; the hot-path
    #: readers take a single indexed load of an immutable tuple, where
    #: a stale read only means one more attempt against the old
    #: address — the retry/breaker machinery absorbs it. ``doc_extra``
    #: and ``on_shutdown`` are installed once by the fleet supervisor
    #: before the data plane starts.
    _ATOMIC_ATTRS = frozenset(
        {"replicas", "doc_extra", "on_shutdown", "flight"}
    )

    def __init__(self, replica_urls: List[str], host: str = "127.0.0.1",
                 port: int = 0, *, scrape_timeout: float = 2.0,
                 proxy_timeout: float = 60.0,
                 breaker_failures: int = 3,
                 breaker_successes: int = 2,
                 breaker_open_seconds: float = 2.0,
                 probe_interval: float = 0.5,
                 probe_timeout: float = 2.0):
        self.replicas = [self._parse(u) for u in replica_urls]
        if not self.replicas:
            raise ValueError("at least one replica url required")
        self.scrape_timeout = float(scrape_timeout)
        self.proxy_timeout = float(proxy_timeout)
        self.probe_interval = max(0.02, float(probe_interval))
        self.probe_timeout = float(probe_timeout)
        self._mu = threading.Lock()
        self._rr = 0
        self._proxied = [0] * len(self.replicas)
        self._errors = [0] * len(self.replicas)
        self._shed_retries = 0
        self._exhausted = 0
        self._breaker_skips = 0
        self._restart_retries = 0
        self._addr_version = [0] * len(self.replicas)
        self._expected_gen: List[Optional[str]] = [None] * len(self.replicas)
        self._restarting = [False] * len(self.replicas)
        #: Shadow-mirror state (canary evaluations): None when off;
        #: else {"paths", "every", "seen", "queue", "dropped"} guarded
        #: by ``_mu`` — the coordinator drains the bounded queue.
        self._mirror: Optional[dict] = None
        self.breakers = [
            ReplicaBreaker(
                fail_threshold=breaker_failures,
                success_threshold=breaker_successes,
                open_seconds=breaker_open_seconds,
            )
            for _ in self.replicas
        ]
        #: Extra top-level blocks merged into ``metrics_doc`` (the
        #: fleet supervisor's restart/rollout/canary accounting).
        self.doc_extra: Optional[Callable[[], dict]] = None
        #: Invoked at the START of a POST /shutdown, before replicas
        #: are told to exit — the supervisor's don't-restart-the-dead
        #: flag must be up before the first replica goes down.
        self.on_shutdown: Optional[Callable[[], None]] = None
        #: Armed by :meth:`enable_flight_recorder`: the fleet-wide
        #: anomaly bundle writer, triggered by breaker CLOSED -> OPEN
        #: transitions.
        self.flight: Optional[FlightRecorder] = None
        self._local = threading.local()
        # Data plane: a thread-per-connection raw-socket loop with a
        # minimal HTTP/1.1 parser instead of ThreadingHTTPServer. The
        # balancer's per-request GIL time is the FLEET's throughput
        # ceiling — BaseHTTPRequestHandler's readline/email parsing and
        # per-response date formatting alone cost more than a whole
        # warmed ANN dispatch, and at N replicas the proxy must stay
        # the cheapest stage in the chain.
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(128)
        self.host, self.port = self._listener.getsockname()[:2]
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._prober: Optional[threading.Thread] = None
        self._prev_switch: Optional[float] = None

    # -- data plane ----------------------------------------------------

    _STATUS_LINE = {
        code: f"HTTP/1.1 {code} {reason}\r\n".encode("latin-1")
        for code, reason in (
            (200, "OK"), (400, "Bad Request"), (404, "Not Found"),
            (429, "Too Many Requests"), (500, "Internal Server Error"),
            (503, "Service Unavailable"), (504, "Gateway Timeout"),
        )
    }

    def _respond(self, sock, code: int, body: bytes, ctype: str,
                 retry_after: Optional[str] = None) -> None:
        head = self._STATUS_LINE.get(
            code, f"HTTP/1.1 {code} X\r\n".encode("latin-1")
        )
        extra = (
            f"Retry-After: {retry_after}\r\n" if retry_after else ""
        )
        sock.sendall(
            head
            + (
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(body)}\r\n{extra}\r\n"
            ).encode("latin-1")
            + body
        )

    def _respond_json(self, sock, code: int, obj,
                      retry_after: Optional[str] = None) -> None:
        self._respond(
            sock, code, json.dumps(obj).encode(), "application/json",
            retry_after,
        )

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed by stop()
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True,
                name="glint-fleet-conn",
            ).start()

    def _serve_conn(self, sock) -> None:
        """One client connection: parse requests with the minimal
        framed reader, route control paths locally, proxy the rest.
        Keep-alive by default (HTTP/1.1); 'Connection: close' honored."""
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        buf = bytearray()
        try:
            while not self._stop.is_set():
                req = _read_request(sock, buf)
                if req is None:
                    return  # client closed between requests
                method, path, headers, body = req
                self._route(sock, method, path, headers, body)
                if headers.get("connection", "").lower() == "close":
                    return
        except (OSError, ValueError, ConnectionError):
            pass  # torn client connection / malformed request
        finally:
            sock.close()
            pool = getattr(self._local, "conns", None)
            if pool:
                for c in pool.values():
                    c.close()
                pool.clear()

    def _route(self, sock, method: str, path: str, headers: dict,
               body: bytes) -> None:
        url = urlparse(path)
        if method == "GET" and url.path == "/healthz":
            up, total, states = self.health()
            return self._respond_json(sock, 200 if up else 503, {
                "status": "ok" if up == total else (
                    "degraded" if up else "down"
                ),
                "replicas": total,
                "replicas_up": up,
                "replica_states": states,
            })
        if method == "GET" and url.path == "/metrics":
            doc = self.metrics_doc()
            fmt = parse_qs(url.query).get("format", ["json"])[0]
            if fmt == "prometheus":
                from glint_word2vec_tpu.obs.prometheus import (
                    fleet_to_prometheus,
                    serving_to_prometheus,
                )

                text = fleet_to_prometheus(doc)
                if doc.get("fleet"):
                    text += serving_to_prometheus(doc["fleet"])
                return self._respond(
                    sock, 200, text.encode(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            return self._respond_json(sock, 200, doc)
        if method == "POST" and url.path == "/shutdown":
            if self.on_shutdown is not None:
                self.on_shutdown()
            results = self.shutdown_fleet()
            self._respond_json(sock, 200, {
                "status": "shutting down fleet",
                "replicas": results,
            })
            threading.Thread(target=self.stop, daemon=True).start()
            return
        # Distributed tracing (ISSUE 18): adopt the client's trace id
        # or mint one at the fleet edge; the balancer hop's root span
        # wraps the whole proxy exchange, and the id rides the wire
        # header so the replica's spans stitch to ours in trace-merge.
        tr = obs_events.request_trace(
            headers.get(obs_events.TRACE_HEADER.lower())
        )
        with tr.phase("req.accept", path=url.path, hop="balancer"):
            status, rbody, rheaders = self.forward(
                method, path, body, trace=tr
            )
        tr.finish(status)
        self._respond(
            sock, status, rbody,
            rheaders.get("content-type") or "application/json",
            rheaders.get("retry-after"),
        )

    @staticmethod
    def _parse(url: str):
        u = urlparse(url if "//" in url else f"http://{url}")
        return (u.hostname, int(u.port))

    # -- replica address table (supervisor seam) -----------------------

    def set_replica_address(self, i: int, host: str, port: int,
                            generation: Optional[str] = None) -> None:
        """Point replica slot ``i`` at a (re)launched process. Bumps
        the address version so every handler thread's cached
        keep-alive connection to the old incarnation is dropped on its
        next use; ``generation`` arms the /healthz handshake the
        prober verifies."""
        with self._mu:
            self.replicas[i] = (host, int(port))
            self._addr_version[i] += 1
            self._expected_gen[i] = generation

    def set_restarting(self, i: int, flag: bool) -> None:
        """Mark a replica as inside a known restart window: a
        connection-refused there is retried with jittered backoff
        (the address may land mid-retry) instead of counting as a
        dead-replica degrade."""
        with self._mu:
            self._restarting[i] = flag

    def is_restarting(self, i: int) -> bool:
        with self._mu:
            return self._restarting[i]

    def stopped(self) -> bool:
        return self._stop.is_set()

    # -- request forwarding --------------------------------------------

    def _conn(self, i: int) -> "_ReplicaConn":
        with self._mu:
            host, port = self.replicas[i]
            ver = self._addr_version[i]
        pool = getattr(self._local, "conns", None)
        if pool is None:
            pool = self._local.conns = {}
        c = pool.get(i)
        if c is not None and c.addr_version != ver:
            c.close()
            c = None
        if c is None:
            c = pool[i] = _ReplicaConn(
                host, port, self.proxy_timeout, addr_version=ver
            )
        return c

    def _drop_conn(self, i: int) -> None:
        pool = getattr(self._local, "conns", None)
        if pool and i in pool:
            try:
                pool.pop(i).close()
            except Exception:
                pass

    def _next_start(self) -> int:
        with self._mu:
            self._rr += 1
            return self._rr

    def _attempt(self, i: int, method: str, path: str, body: bytes,
                 trace_id: Optional[str] = None):
        """One replica attempt; (status, body, headers) or None on
        connection failure (breaker and error accounting applied). A
        connection-refused inside a known restart window retries the
        SAME slot with jittered backoff — the supervisor may land the
        relaunched replica's fresh address mid-retry, and a bounce
        must not read as a dead-replica degrade."""
        for attempt in range(self.RESTART_RETRIES + 1):
            try:
                return self._conn(i).roundtrip(
                    method, path, body, trace_id=trace_id
                )
            except ConnectionRefusedError:
                self._drop_conn(i)
                if (not self.is_restarting(i)
                        or attempt >= self.RESTART_RETRIES):
                    break
                with self._mu:
                    self._restart_retries += 1
                time.sleep(
                    self.RESTART_RETRY_BASE * (attempt + 1)
                    * (0.5 + random.random())
                )
            except Exception:
                self._drop_conn(i)
                break
        with self._mu:
            self._errors[i] += 1
        self.breakers[i].record_failure()
        return None

    def forward(self, method: str, path: str, body: bytes, trace=None):
        """Send one request to the fleet: round-robin start over
        CLOSED breakers, advance on connection failure or a shed
        status (429/503), at most one attempt per replica. Returns
        (status, body, headers). When every replica sheds, the LAST
        shed response is relayed — its Retry-After included — so the
        client sees the fleet's own backpressure, not an invented
        error. ``trace`` (a ``RequestTrace``) records one ``req.hop``
        phase span per replica attempt and propagates its id to the
        replica over the wire header.

        Open/half-open breakers are skipped (each skip is a timeout a
        client did not pay) and only attempted as a last resort when
        no closed replica answered. Administratively HELD replicas are
        never attempted: a hold means a rollout drain or a canary
        serving a CANDIDATE generation that must not touch live
        traffic."""
        tr = trace if trace is not None else obs_events.NULL_TRACE
        n = len(self.replicas)
        start = self._next_start()
        order = [(start + j) % n for j in range(n)]
        eligible = [i for i in order if self.breakers[i].eligible()]
        fallback = [
            i for i in order
            if not self.breakers[i].eligible()
            and not self.breakers[i].held()
        ]
        if len(eligible) < n:
            with self._mu:
                self._breaker_skips += n - len(eligible)
        last_shed = None
        attempted = 0
        for i in eligible + fallback:
            with tr.phase("req.hop", replica=i) as hop:
                got = self._attempt(
                    i, method, path, body,
                    trace_id=tr.trace_id or None,
                )
                hop.update(
                    outcome="conn_error" if got is None else int(got[0])
                )
            attempted += 1
            if got is None:
                continue
            status, rbody, rheaders = got
            # ANY HTTP answer proves the process is alive — a shed is
            # backpressure, not breakage.
            self.breakers[i].record_success()
            if status in _SHED_STATUSES:
                last_shed = got
                with self._mu:
                    self._shed_retries += 1
                continue
            with self._mu:
                self._proxied[i] += 1
            self._maybe_mirror(method, path, body, status, rbody)
            return got
        with self._mu:
            self._exhausted += 1
        if last_shed is not None:
            return last_shed
        return (
            503,
            json.dumps({
                "error": f"no replica reachable ({attempted} tried)"
            }).encode(),
            {"Content-Type": "application/json", "Retry-After": "1"},
        )

    # -- shadow mirroring (canary evaluations) -------------------------

    def start_mirror(self, paths, every: int,
                     max_queue: int = 256) -> None:
        """Begin sampling live POST traffic on ``paths``: every
        ``every``-th successful response is queued as (path, body,
        status, response-body) for the canary scorer to drain. The
        queue is bounded; overflow is dropped and counted — mirroring
        must never apply backpressure to live clients."""
        with self._mu:
            self._mirror = {
                "paths": frozenset(paths),
                "every": max(1, int(every)),
                "seen": 0,
                "queue": deque(),
                "max_queue": max(1, int(max_queue)),
                "dropped": 0,
            }

    def drain_mirror(self, limit: int = 16) -> List[tuple]:
        with self._mu:
            m = self._mirror
            if m is None:
                return []
            out = []
            while m["queue"] and len(out) < limit:
                out.append(m["queue"].popleft())
            return out

    def stop_mirror(self) -> None:
        with self._mu:
            self._mirror = None

    def _maybe_mirror(self, method: str, path: str, body: bytes,
                      status: int, rbody: bytes) -> None:
        if method != "POST":
            return
        with self._mu:
            m = self._mirror
            if m is None or urlparse(path).path not in m["paths"]:
                return
            m["seen"] += 1
            if m["seen"] % m["every"]:
                return
            if len(m["queue"]) >= m["max_queue"]:
                m["dropped"] += 1
                return
            m["queue"].append((path, body, status, rbody))

    # -- active health probing -----------------------------------------

    def start_prober(self) -> None:
        """Start the active health prober: every ``probe_interval``
        each replica's ``/healthz`` is probed (2s default timeout) and
        the verdict feeds its breaker — K consecutive failures eject,
        a cooldown half-opens, M trial successes readmit. Replicas
        inside an open breaker's cooldown get NO probes (and no
        traffic)."""
        if self._prober is not None:
            return
        self._prober = threading.Thread(
            target=self._probe_loop, daemon=True,
            name="glint-fleet-prober",
        )
        self._prober.start()

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval):
            for i in range(len(self.replicas)):
                b = self.breakers[i]
                if b.state() == ReplicaBreaker.OPEN \
                        and not b.maybe_half_open():
                    continue  # cooling down: no probes either
                self.probe_replica(i)

    def probe_replica(self, i: int) -> bool:
        """One active /healthz probe of replica ``i``; feeds the
        breaker and returns the verdict. A probe is healthy only when
        the replica answers 200 AND — when the supervisor armed a
        launch generation — echoes the expected ``fleet_generation``
        (the PR 7 handshake: a stale pre-restart process must never
        answer for the new one)."""
        b = self.breakers[i]
        ok = False
        try:
            faults.fire("fleet.replica_probe")
            status, h = self._get_json(
                i, "/healthz", timeout=self.probe_timeout
            )
            with self._mu:
                expected = self._expected_gen[i]
            ok = status == 200
            if ok and expected is not None:
                ok = str(h.get("fleet_generation")) == str(expected)
        except Exception:
            ok = False
        if ok:
            b.record_success(probe=True)
        else:
            b.record_failure(probe=True)
        return ok

    # -- fleet views ---------------------------------------------------

    def _get_json(self, i: int, path: str,
                  timeout: Optional[float] = None):
        with self._mu:
            host, port = self.replicas[i]
        conn = http.client.HTTPConnection(
            host, port,
            timeout=self.scrape_timeout if timeout is None else timeout,
        )
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read().decode())
        finally:
            conn.close()

    def health(self):
        """(up, total, per-replica state) from each replica's
        /healthz; a dead replica reports "unreachable"."""
        states = []
        up = 0
        for i in range(len(self.replicas)):
            try:
                status, h = self._get_json(i, "/healthz")
                state = h.get("status", f"http {status}")
                if status == 200:
                    up += 1
            except Exception:
                state = "unreachable"
            states.append({
                "url": self.replica_url(i), "state": state,
                "breaker": self.breakers[i].state(),
            })
        return up, len(self.replicas), states

    def replica_url(self, i: int) -> str:
        with self._mu:
            host, port = self.replicas[i]
        return f"http://{host}:{port}"

    def balancer_stats(self) -> dict:
        with self._mu:
            return {
                "shed_retries_total": self._shed_retries,
                "exhausted_total": self._exhausted,
                "proxied_total": int(sum(self._proxied)),
                "proxy_errors_total": int(sum(self._errors)),
                "breaker_skips_total": self._breaker_skips,
                "restart_retries_total": self._restart_retries,
            }

    def metrics_doc(self) -> dict:
        """The merged fleet document: per-replica snapshots (scraped
        now, failures reported not fatal) with breaker state, the PR 8
        exact merge as ``fleet``, the balancer's own counters, and —
        when a fleet supervisor is attached — its restart/rollout/
        canary blocks."""
        from glint_word2vec_tpu.obs.aggregate import (
            merge_serving_snapshots,
        )

        replicas = []
        snaps = []
        with self._mu:
            proxied = list(self._proxied)
            errors = list(self._errors)
            restarting = list(self._restarting)
        for i in range(len(self.replicas)):
            entry: Dict[str, object] = {
                "url": self.replica_url(i),
                "proxied_total": proxied[i],
                "proxy_errors_total": errors[i],
                "breaker": self.breakers[i].snapshot(),
                "restarting": restarting[i],
            }
            try:
                _, snap = self._get_json(i, "/metrics")
                entry["up"] = True
                entry["snapshot"] = snap
                snaps.append(snap)
            except Exception as e:
                entry["up"] = False
                entry["scrape_error"] = str(e)
            replicas.append(entry)
        doc = {
            "replicas": replicas,
            "fleet": merge_serving_snapshots(snaps),
            "balancer": self.balancer_stats(),
        }
        extra = self.doc_extra() if self.doc_extra is not None else None
        if extra:
            doc.update(extra)
        return doc

    def shutdown_fleet(self) -> List[dict]:
        """POST /shutdown to every replica (best effort)."""
        results = []
        for i in range(len(self.replicas)):
            with self._mu:
                host, port = self.replicas[i]
            try:
                conn = http.client.HTTPConnection(
                    host, port, timeout=self.scrape_timeout
                )
                try:
                    conn.request(
                        "POST", "/shutdown", body=b"{}",
                        headers={"Content-Type": "application/json"},
                    )
                    resp = conn.getresponse()
                    resp.read()
                    results.append({
                        "url": self.replica_url(i),
                        "status": resp.status,
                    })
                finally:
                    conn.close()
            except Exception as e:
                results.append({
                    "url": self.replica_url(i), "error": str(e),
                })
        return results

    # -- anomaly flight recorder (ISSUE 18) ----------------------------

    def enable_flight_recorder(
        self, out_dir: str, *, window_seconds: float = 30.0,
        min_interval_seconds: float = 60.0,
    ) -> FlightRecorder:
        """Arm the fleet-wide anomaly flight recorder: a breaker's
        CLOSED -> OPEN transition (a healthy replica just got ejected)
        snapshots the last ``window_seconds`` of spans and metrics from
        the balancer AND every reachable replica into a postmortem
        bundle under ``out_dir``. Bundles are rate-limited; the
        recorder never raises into the data plane."""
        fl = FlightRecorder(
            out_dir, window_seconds=window_seconds,
            min_interval_seconds=min_interval_seconds,
        )
        fl.add_source("balancer", self._flight_balancer)
        fl.add_source("replica_spans", self._flight_replica_spans)
        fl.add_source("replica_metrics", self._flight_replica_metrics)
        self.flight = fl
        for i, b in enumerate(self.breakers):
            b.on_open = (
                lambda i=i: fl.trigger("breaker_open", replica=i)
            )
        return fl

    def _flight_balancer(self, window_seconds: float) -> dict:
        doc: Dict[str, object] = {
            "balancer": self.balancer_stats(),
            "breakers": [b.snapshot() for b in self.breakers],
        }
        rec = obs_events.get_recorder()
        if rec is not None:
            doc["spans"] = rec.recent_events(window_seconds)
            doc["anchor"] = {
                "wall_t0": rec.wall_t0, "mono_t0": rec.mono_t0,
            }
        return doc

    def _flight_replica_spans(self, window_seconds: float) -> dict:
        """Every replica's recent span window (its /trace route): the
        bundle shows what the whole fleet was doing when the anomaly
        fired, not just the process that noticed it."""
        out = {}
        for i in range(len(self.replicas)):
            try:
                _, doc = self._get_json(
                    i, f"/trace?seconds={window_seconds:g}"
                )
                out[f"replica_{i}"] = {
                    "url": self.replica_url(i), "trace": doc,
                }
            except Exception as e:
                out[f"replica_{i}"] = {
                    "url": self.replica_url(i), "error": str(e),
                }
        return out

    def _flight_replica_metrics(self, window_seconds: float) -> dict:
        out = {}
        for i in range(len(self.replicas)):
            try:
                _, snap = self._get_json(i, "/metrics")
                out[f"replica_{i}"] = {
                    "url": self.replica_url(i), "snapshot": snap,
                }
            except Exception as e:
                out[f"replica_{i}"] = {
                    "url": self.replica_url(i), "error": str(e),
                }
        return out

    # -- lifecycle -----------------------------------------------------

    def _tighten_gil_switch(self) -> None:
        # One handler thread per client connection, each a chain of
        # short GIL-holding sections (parse, forward, relay): at the
        # default 5ms switch interval the convoy adds whole scheduling
        # quanta per proxied call (the same effect serving.py tightens
        # for). Restored by stop().
        if self._prev_switch is None:
            self._prev_switch = sys.getswitchinterval()
            sys.setswitchinterval(0.001)

    def serve_forever(self) -> None:
        logger.info(
            "fleet balancer on %s:%d over %d replica(s)",
            self.host, self.port, len(self.replicas),
        )
        self._tighten_gil_switch()
        self._accept_loop()

    def start_background(self) -> None:
        self._tighten_gil_switch()
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="glint-fleet-lb",
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # Waking a thread blocked in accept() needs more than close():
        # on Linux, closing the fd from another thread leaves the
        # accept blocked forever. shutdown() wakes it with EINVAL; the
        # best-effort self-connect covers platforms where it doesn't.
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            socket.create_connection(
                (self.host, self.port), timeout=1
            ).close()
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:  # pragma: no cover - already closed
            pass
        if self._prev_switch is not None:
            sys.setswitchinterval(self._prev_switch)
            self._prev_switch = None


# ----------------------------------------------------------------------
# Rolling rollout + shadow-canary promotion gate
# ----------------------------------------------------------------------


def _topk_overlap(a, b, k: int) -> Optional[float]:
    """Agreement score between two /synonyms-or-/analogy JSON answers:
    |intersection| / max(|a|, |b|) over the top-k words. None when
    either side is not a scoreable hit list."""
    try:
        wa = [x[0] for x in a][: max(1, int(k))]
        wb = [x[0] for x in b][: max(1, int(k))]
    except (TypeError, IndexError):
        return None
    if not wa and not wb:
        return None
    sa, sb = set(wa), set(wb)
    return len(sa & sb) / max(len(sa), len(sb), 1)


class CanaryConfig:
    """Knobs for the shadow-canary promotion gate.

    ``probes`` are operator-defined deterministic checks — each a
    ``{"path": "/synonyms"|"/analogy", "body": {...}}`` request posted
    to BOTH the live fleet and the canary and scored for top-k
    agreement (the vienna/berlin + capital-of analogy gates of
    QUALITY.json, restated as live-vs-candidate agreement so no
    expected-answer labels are needed). Mirrored live traffic — every
    ``mirror_every``-th request on ``mirror_paths`` — adds organic
    samples until ``min_scores`` are collected or ``mirror_seconds``
    elapse. The mean agreement must clear ``agreement_gate`` or the
    candidate is held back. Choose probe words stable across
    generations: a live-404/canary-404 pair is unscorable (skipped),
    a one-sided 404 scores 0.
    """

    def __init__(self, *, mirror_paths=("/synonyms", "/analogy"),
                 mirror_every: int = 4, min_scores: int = 8,
                 mirror_seconds: float = 10.0,
                 agreement_gate: float = 0.6, top_k: int = 10,
                 probes: Optional[List[dict]] = None):
        self.mirror_paths = tuple(mirror_paths)
        self.mirror_every = max(1, int(mirror_every))
        self.min_scores = max(0, int(min_scores))
        self.mirror_seconds = float(mirror_seconds)
        self.agreement_gate = float(agreement_gate)
        self.top_k = max(1, int(top_k))
        self.probes = list(probes or [])


class RolloutCoordinator:
    """Orders fleet-wide generation rollouts, one replica at a time.

    Follows ``LATEST.json`` the way the serving ``SnapshotWatcher``
    does, but instead of letting every replica swap simultaneously it
    drives the sequence: (canary gate, when configured) then for each
    replica — breaker hold, drain, ``POST /reload`` with the explicit
    generation dir, wait healthy + warm (the swap added zero
    post-warmup compiles), readmit. Fleet capacity never drops below
    N-1 replicas.

    Failure taxonomy:
      * replica unavailable (dead / mid-restart / not yet readmitted):
        the rollout HALTS — the old generation keeps serving on every
        un-swapped replica — and is retried on a later poll once the
        fleet is whole again;
      * staging failure (replica answered /reload with an error): the
        generation is marked failed and NOT retried until the pointer
        moves (the SnapshotWatcher contract, fleet-wide);
      * canary regression: the candidate is held back — canary
        restored to the live generation, counted, left on disk.
    """

    def __init__(self, lb: LoadBalancer, watch_dir: str, *,
                 poll_seconds: float = 1.0,
                 current: Optional[str] = None,
                 current_dir: Optional[str] = None,
                 canary: Optional[CanaryConfig] = None,
                 step_timeout: float = 600.0,
                 drain_seconds: float = 0.25,
                 replica_ok: Optional[Callable[[int], bool]] = None,
                 on_generation=None):
        self.lb = lb
        self.watch_dir = watch_dir
        self.poll_seconds = max(0.05, float(poll_seconds))
        self.canary = canary
        self.step_timeout = float(step_timeout)
        self.drain_seconds = float(drain_seconds)
        self._replica_ok = replica_ok or (lambda i: True)
        self.on_generation = on_generation
        self._mu = threading.Lock()
        #: Generation name the whole fleet serves (None when booted
        #: from a plain --model dir outside the publish protocol).
        self.current = current
        #: Model directory replicas (re)launch from — the previous
        #: generation the canary is restored to on hold-back.
        self.current_dir = current_dir
        self._failed: Optional[str] = None
        self._held_back: Optional[str] = None
        self._in_progress = False
        self._phase = "idle"
        self._stats = {
            "rollouts_started_total": 0,
            "rollouts_completed_total": 0,
            "rollouts_halted_total": 0,
            "rollout_steps_total": 0,
            "generations_failed_total": 0,
            "watch_errors_total": 0,
            "canary": {
                "evaluations_total": 0,
                "holdbacks_total": 0,
                "last_agreement": None,
                "last_scored": 0,
                "last_generation": None,
                "last_verdict": None,
                "agreement_gate": (
                    canary.agreement_gate if canary is not None else None
                ),
            },
        }
        self._poll_mu = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- pointer following ---------------------------------------------

    def poll_once(self) -> Optional[str]:
        """One pointer check; returns the generation name when a full
        rollout completed, else None. Never raises."""
        with self._poll_mu:
            return self._poll_once_locked()

    def _poll_once_locked(self) -> Optional[str]:
        from glint_word2vec_tpu.streaming.publish import read_latest

        try:
            latest = read_latest(self.watch_dir, raise_errors=True)
        except (OSError, ValueError) as e:
            with self._mu:
                self._stats["watch_errors_total"] += 1
            logger.warning(
                "rollout coordinator: transient pointer read error: %s "
                "(retrying next poll)", e,
            )
            return None
        if latest is None:
            return None
        gen = str(latest["generation"])
        with self._mu:
            if gen in (self.current, self._failed, self._held_back):
                return None
        gen_dir = os.path.join(self.watch_dir, gen)
        try:
            return self._rollout(gen, gen_dir)
        except Exception as e:  # pragma: no cover - defensive
            logger.error("rollout of %s failed unexpectedly: %s", gen, e)
            return self._halt(gen, f"unexpected error: {e}")

    def _rollout(self, gen: str, gen_dir: str) -> Optional[str]:
        lb = self.lb
        n = len(lb.replicas)
        ok_idx = [i for i in range(n) if self._replica_ok(i)]
        with self._mu:
            self._stats["rollouts_started_total"] += 1
            self._in_progress = True
            self._phase = "starting"
        # A hot-swap arriving while a replica is mid-restart WAITS: the
        # rollout needs the whole (non-written-off) fleet serving, so
        # it halts and retries once the supervisor readmits the
        # replica — never racing a relaunch with a reload.
        not_ready = [i for i in ok_idx if not lb.breakers[i].eligible()]
        if not ok_idx or not_ready:
            return self._halt(
                gen,
                f"replicas not serving: {not_ready or 'all written off'}",
            )
        completed: List[int] = []
        if self.canary is not None and len(ok_idx) < 2:
            if len(lb.replicas) >= 2:
                # Configured for canarying but degraded below a live
                # pair: never roll an unvetted candidate onto the only
                # serving replica — wait for the supervisor to restore
                # a peer, then evaluate properly.
                return self._halt(
                    gen, "canary gate needs >= 2 serving replicas "
                    f"(only {len(ok_idx)} left)",
                )
            # A deliberately single-replica fleet cannot canary (there
            # is no live side to hold out) — proceed, loudly.
            logger.warning(
                "single-replica fleet: canary gate impossible, "
                "rolling %s without evaluation", gen,
            )
        if self.canary is not None and len(ok_idx) >= 2:
            verdict = self._canary_phase(ok_idx[0], gen, gen_dir)
            if verdict == "held_back":
                with self._mu:
                    self._held_back = gen
                    self._stats["canary"]["holdbacks_total"] += 1
                    self._in_progress = False
                    self._phase = "held_back"
                    cur = self.current
                logger.error(
                    "canary HELD BACK %s: live generation %s keeps "
                    "serving everywhere; candidate left on disk at %s",
                    gen, cur, gen_dir,
                )
                return None
            if verdict == "stage_failed":
                return self._stage_failed(gen)
            if verdict != "pass":
                return self._halt(gen, f"canary: {verdict}")
            completed.append(ok_idx[0])
        for i in ok_idx:
            if i in completed:
                continue
            try:
                faults.fire("fleet.rollout_step")
            except Exception as e:
                return self._halt(gen, f"rollout step fault: {e}")
            with self._mu:
                self._stats["rollout_steps_total"] += 1
                self._phase = "rolling"
            if not self._replica_ok(i) or not lb.breakers[i].eligible():
                # Replica killed mid-rollout: halt — the old generation
                # keeps serving on every un-swapped replica, and the
                # next poll retries once the fleet is whole.
                return self._halt(gen, f"replica {i} unavailable")
            # Hold only when a SERVING peer can absorb the drained
            # traffic: written-off replicas don't count, so the sole
            # survivor of a degraded fleet is never held (its reload
            # stages off the request path anyway).
            res = self._swap_replica(
                i, gen, gen_dir, hold=len(ok_idx) > 1
            )
            if res == "stage_failed":
                return self._stage_failed(gen)
            if res != "ok":
                return self._halt(gen, f"replica {i}: {res}")
        with self._mu:
            self.current = gen
            self.current_dir = gen_dir
            self._stats["rollouts_completed_total"] += 1
            self._in_progress = False
            self._phase = "idle"
        if self.on_generation is not None:
            self.on_generation(gen, gen_dir)
        logger.info(
            "rollout complete: fleet promoted to %s (%d replicas)",
            gen, len(ok_idx),
        )
        return gen

    def _halt(self, gen: str, reason: str) -> None:
        """Transient abort: retried on a later poll (the pointer still
        names the generation)."""
        with self._mu:
            self._stats["rollouts_halted_total"] += 1
            self._in_progress = False
            self._phase = "halted"
            cur = self.current
        logger.warning(
            "rollout of %s HALTED: %s — old generation %s still "
            "serving on un-swapped replicas; retrying on a later poll",
            gen, reason, cur,
        )
        return None

    def _stage_failed(self, gen: str) -> None:
        """Permanent (until the pointer moves): the candidate failed
        staging on a replica."""
        with self._mu:
            self._failed = gen
            self._stats["generations_failed_total"] += 1
            self._in_progress = False
            self._phase = "failed"
            cur = self.current
        logger.error(
            "rollout of %s ABORTED: staging failed; generation marked "
            "failed (not retried until the pointer moves); %s keeps "
            "serving", gen, cur,
        )
        return None

    # -- per-replica swap ----------------------------------------------

    def _post_replica(self, i: int, path: str, payload,
                      timeout: Optional[float] = None,
                      shadow: bool = False):
        """Direct POST to one replica (NOT through the balancer's
        rotation): the rollout/canary control channel."""
        with self.lb._mu:
            host, port = self.lb.replicas[i]
        body = (
            payload if isinstance(payload, (bytes, bytearray))
            else json.dumps(payload).encode()
        )
        headers = {"Content-Type": "application/json"}
        if shadow:
            # Tag control/scoring traffic so a replica's access view
            # (and the stub replicas in tests) can tell shadow traffic
            # from live traffic that must never reach a held canary.
            headers["X-Glint-Shadow"] = "1"
        conn = http.client.HTTPConnection(
            host, port,
            timeout=self.step_timeout if timeout is None else timeout,
        )
        try:
            conn.request("POST", path, body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            try:
                doc = json.loads(data.decode() or "null")
            except ValueError:
                doc = None
            return resp.status, doc
        finally:
            conn.close()

    def _replica_metrics(self, i: int) -> Tuple[Optional[str], int, bool]:
        """(generation, post_warmup_compiles, healthy) of one replica."""
        try:
            status, snap = self.lb._get_json(i, "/metrics")
            hstatus, _ = self.lb._get_json(i, "/healthz")
        except Exception:
            return None, -1, False
        if status != 200:
            return None, -1, False
        gen = (snap.get("hot_swap") or {}).get("generation")
        compiles = int((snap.get("compiles") or {}).get("post_warmup") or 0)
        return gen, compiles, hstatus == 200

    def _wait_replica_on(self, i: int, gen: str,
                         compiles_before: int) -> str:
        """Poll until the replica serves ``gen``, healthy, with NO
        post-warmup compiles added by the swap. Returns "ok" or a
        reason string."""
        deadline = time.monotonic() + self.step_timeout
        while time.monotonic() < deadline and not self._stop.is_set():
            rgen, compiles, healthy = self._replica_metrics(i)
            if rgen == gen and healthy:
                if compiles_before >= 0 and compiles > compiles_before:
                    return (
                        f"swap added {compiles - compiles_before} "
                        "post-warmup compiles"
                    )
                return "ok"
            time.sleep(0.1)
        return f"not healthy on {gen} within {self.step_timeout:.0f}s"

    def _swap_replica(self, i: int, gen: str, gen_dir: str,
                      hold: bool) -> str:
        """One rollout step: drain via breaker hold, reload, wait
        healthy + warm, readmit. Returns "ok", "stage_failed", or a
        transient reason. Single-replica fleets skip the hold — with
        no peer to absorb traffic, ejecting the only replica would
        drop availability to zero, and the reload stages off the
        request path anyway."""
        b = self.lb.breakers[i]
        _, compiles_before, _ = self._replica_metrics(i)
        if hold:
            b.hold()
            time.sleep(self.drain_seconds)  # in-flight requests drain
        try:
            try:
                status, resp = self._post_replica(
                    i, "/reload", {"dir": gen_dir, "generation": gen},
                    shadow=True,
                )
            except Exception as e:
                return f"unreachable during reload: {e}"
            if status == 503:
                # Transient staging trouble (storage hiccup on an
                # existing dir, answered 503 by the replica): halt and
                # retry the rollout on a later poll — branding the
                # generation failed is for REJECTED staging only.
                return f"transient staging error: {resp}"
            if status != 200:
                logger.error(
                    "replica %d rejected %s: http %d %s",
                    i, gen, status, resp,
                )
                return "stage_failed"
            return self._wait_replica_on(i, gen, compiles_before)
        finally:
            if hold:
                b.release()

    # -- shadow canary -------------------------------------------------

    def _score_probe(self, ci: int, probe: dict) -> Optional[float]:
        """One deterministic probe: POST the same body to the live
        fleet (the held canary is excluded from rotation by
        construction) and to the canary; score top-k agreement."""
        path = str(probe.get("path", "/synonyms"))
        body = json.dumps(probe.get("body", {})).encode()
        try:
            lstatus, lbody, _ = self.lb.forward("POST", path, body)
            cstatus, cdoc = self._post_replica(
                ci, path, body, timeout=30.0, shadow=True
            )
        except Exception:
            return None
        if lstatus in _SHED_STATUSES or cstatus in _SHED_STATUSES:
            # Backpressure is not a model answer: an overloaded-but-
            # healthy fleet must not hold back a good candidate.
            return None
        if lstatus != 200 and cstatus != 200:
            return None  # unscorable on both sides (e.g. shared OOV)
        if lstatus != 200 or cstatus != 200:
            return 0.0  # one-sided SEMANTIC failure is disagreement
        try:
            ldoc = json.loads(lbody)
        except ValueError:
            return None
        return _topk_overlap(ldoc, cdoc, self.canary.top_k)

    def _canary_phase(self, ci: int, gen: str, gen_dir: str) -> str:
        """Stage the candidate on ONE held replica, mirror a sampled
        slice of live traffic to it, score agreement, and decide.
        Returns "pass", "held_back", "stage_failed", or a transient
        reason. The held replica serves NO live traffic throughout —
        the candidate generation cannot reach a client until it
        passes."""
        lb = self.lb
        b = lb.breakers[ci]
        with self._mu:
            self._stats["canary"]["evaluations_total"] += 1
            self._phase = "canary"
        b.hold()
        mirroring = False
        restored = True
        try:
            _, compiles_before, _ = self._replica_metrics(ci)
            time.sleep(self.drain_seconds)
            # From the moment the reload is POSTed the replica may
            # have adopted the candidate (the handler swaps before
            # answering): pessimistically un-restored until a path
            # below proves the live generation is back.
            restored = False
            try:
                status, resp = self._post_replica(
                    ci, "/reload", {"dir": gen_dir, "generation": gen},
                    shadow=True,
                )
            except Exception as e:
                # The reload may have been APPLIED with the response
                # lost — restore before ever releasing the hold.
                restored = self._restore_canary(ci, gen)
                return f"canary unreachable during reload: {e}"
            if status == 503:
                # Transient staging trouble on the replica (storage
                # hiccup): the old tables stayed live — retry the
                # whole rollout on a later poll.
                restored = True
                return f"canary transient staging error: {resp}"
            if status != 200:
                logger.error(
                    "canary replica %d rejected %s: http %d %s",
                    ci, gen, status, resp,
                )
                restored = True  # staging rejected: old tables live
                return "stage_failed"
            warm = self._wait_replica_on(ci, gen, compiles_before)
            if warm != "ok":
                # The candidate IS live on the canary but never proved
                # healthy/warm: restore before releasing the hold.
                restored = self._restore_canary(ci, gen)
                return f"canary {warm}"
            scores: List[float] = []
            for probe in (self.canary.probes or []):
                s = self._score_probe(ci, probe)
                if s is not None:
                    scores.append(s)
            lb.start_mirror(
                self.canary.mirror_paths, self.canary.mirror_every
            )
            mirroring = True
            deadline = time.monotonic() + self.canary.mirror_seconds
            want = max(self.canary.min_scores, len(scores))
            while (len(scores) < want
                   and time.monotonic() < deadline
                   and not self._stop.is_set()):
                drained = lb.drain_mirror(16)
                if not drained:
                    time.sleep(0.05)
                    continue
                for path, body, lstatus, lbody in drained:
                    if lstatus != 200:
                        continue
                    try:
                        cstatus, cdoc = self._post_replica(
                            ci, urlparse(path).path, body,
                            timeout=30.0, shadow=True,
                        )
                        if cstatus in _SHED_STATUSES:
                            continue  # backpressure, not an answer
                        if cstatus != 200:
                            scores.append(0.0)
                            continue
                        s = _topk_overlap(
                            json.loads(lbody), cdoc, self.canary.top_k
                        )
                        if s is not None:
                            scores.append(s)
                    except Exception:
                        continue
            lb.stop_mirror()
            mirroring = False
            agreement = (
                sum(scores) / len(scores) if scores else None
            )
            ok = (
                agreement is None
                or agreement >= self.canary.agreement_gate
            )
            with self._mu:
                can = self._stats["canary"]
                can["last_agreement"] = (
                    round(agreement, 4) if agreement is not None else None
                )
                can["last_scored"] = len(scores)
                can["last_generation"] = gen
                can["last_verdict"] = "pass" if ok else "held_back"
            if agreement is None:
                logger.warning(
                    "canary for %s collected no scoreable responses "
                    "(no live traffic, no probes) — passing vacuously",
                    gen,
                )
            if ok:
                logger.info(
                    "canary PASSED for %s: agreement %.3f >= %.3f "
                    "over %d responses",
                    gen, agreement if agreement is not None else 1.0,
                    self.canary.agreement_gate, len(scores),
                )
                restored = True  # it now serves the PROMOTED generation
                return "pass"
            # Hold-back: restore the canary to the live generation so
            # the candidate never serves a client, then count it.
            restored = self._restore_canary(ci, gen)
            return "held_back"
        finally:
            if mirroring:
                lb.stop_mirror()
            if restored:
                b.release()
            # NOT restored: the canary still holds the regressed
            # candidate — it stays held (no live traffic) for the
            # operator; the README runbook documents recovery.

    def _restore_canary(self, ci: int, candidate: str) -> bool:
        """Reload the canary back to the live generation after a
        hold-back. Retried a few times; on total failure the replica
        is left HELD (serving nothing) rather than ever exposing the
        regressed candidate to clients."""
        with self._mu:
            prev_gen, prev_dir = self.current, self.current_dir
        if prev_dir is None:
            logger.error(
                "no previous generation dir to restore canary from "
                "(booted outside the publish protocol?) — replica "
                "stays held",
            )
            return False
        for _ in range(3):
            try:
                status, _ = self._post_replica(
                    ci, "/reload",
                    {"dir": prev_dir, "generation": prev_gen},
                    shadow=True,
                )
                if status == 200 and self._wait_replica_on(
                        ci, prev_gen, -1) == "ok":
                    logger.info(
                        "canary restored to %s after holding back %s",
                        prev_gen, candidate,
                    )
                    return True
            except Exception:
                pass
            time.sleep(0.5)
        logger.error(
            "canary restore to %s FAILED after holding back %s — "
            "replica left held out of rotation", prev_gen, candidate,
        )
        return False

    # -- observability -------------------------------------------------

    def stats(self) -> dict:
        with self._mu:
            out = {
                k: v for k, v in self._stats.items() if k != "canary"
            }
            out["canary"] = dict(self._stats["canary"])
            out["in_progress"] = self._in_progress
            out["phase"] = self._phase
            out["generation"] = self.current
            out["failed_generation"] = self._failed
            out["held_back_generation"] = self._held_back
            return out

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="glint-fleet-rollout",
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_seconds):
            self.poll_once()

    def stop(self) -> None:
        self._stop.set()


# ----------------------------------------------------------------------
# Fleet supervisor + launcher
# ----------------------------------------------------------------------


@dataclass
class _ReplicaSlot:
    """One supervised replica slot: the live process, its launch
    generation (the /healthz handshake value), and restart pacing."""

    index: int
    state: str = "starting"   # starting | up | backoff | failed | stopped
    proc: Optional[subprocess.Popen] = None
    launch_generation: int = -1
    port_file: str = ""
    host: Optional[str] = None
    port: Optional[int] = None
    restarts: int = 0
    relaunch_at: float = 0.0
    started_at: float = 0.0
    detect_t: Optional[float] = None
    last_reason: Optional[str] = None
    restart_records: List[dict] = field(default_factory=list)

    def gen_tag(self) -> str:
        return f"{self.index}.{self.launch_generation}"


class FleetSupervisor:
    """Self-healing serving fleet: supervised replicas behind a
    breaker-aware balancer, with coordinated rolling rollout.

    The PR 7 supervisor pattern on the serving tier: replica liveness
    is watched via ``waitpid`` (crash) AND the balancer's active
    prober (hang — a replica whose probes fail continuously for
    ``hang_kill_seconds`` while its process still runs is killed and
    treated as crashed). Dead replicas relaunch from the fleet's
    CURRENT model directory under capped exponential backoff and a
    per-replica ``max_restarts`` budget; a replica out of budget is
    left down (the balancer serves from the survivors) and counted on
    ``/metrics``. Every launch exports ``GLINT_FLEET_GEN``; the
    replica echoes it on ``/healthz`` and in its port file, so a stale
    process or port file can never be adopted as the new incarnation.

    With ``watch_dir`` (coordinated mode, the default), replicas do
    NOT watch the publish dir themselves — the
    :class:`RolloutCoordinator` orders every swap one replica at a
    time, gated by the shadow canary when configured. A relaunched
    replica boots from the fleet's current (promoted) generation, so
    a restart mid-rollout converges with the coordinator instead of
    racing it.
    """

    #: ``lb`` and ``coordinator`` are written exactly once (in run(),
    #: before the supervision loop and any metrics request can touch
    #: them) and read-only afterwards; lock-free reads see either None
    #: (ignored) or the final object.
    _ATOMIC_ATTRS = frozenset({"lb", "coordinator"})

    def __init__(
        self,
        model_dir: Optional[str],
        *,
        replicas: int = 2,
        host: str = "127.0.0.1",
        port: int = 8800,
        watch_dir: Optional[str] = None,
        watch_poll: float = 1.0,
        replica_flags: Optional[List[str]] = None,
        log_dir: Optional[str] = None,
        trace_dir: Optional[str] = None,
        ready_timeout: float = 900.0,
        port_file: Optional[str] = None,
        max_restarts: int = 3,
        backoff_base_seconds: float = 1.0,
        backoff_cap_seconds: float = 30.0,
        hang_kill_seconds: float = 10.0,
        poll_interval: float = 0.25,
        kill_grace_seconds: float = 5.0,
        probe_interval: float = 0.5,
        probe_timeout: float = 2.0,
        breaker_failures: int = 3,
        breaker_successes: int = 2,
        breaker_open_seconds: float = 2.0,
        canary: Optional[CanaryConfig] = None,
        rollout_step_timeout: float = 600.0,
        coordinated: bool = True,
        build_replica_argv: Optional[Callable[[int, str], List[str]]] = None,
        replica_env_first_launch: Optional[Dict[int, Dict[str, str]]] = None,
    ):
        if model_dir is None and watch_dir is None \
                and build_replica_argv is None:
            raise ValueError("model_dir or watch_dir required")
        self.model_dir = model_dir
        self.num_replicas = max(1, int(replicas))
        self.host, self.port = host, int(port)
        self.watch_dir = watch_dir
        self.watch_poll = float(watch_poll)
        self.replica_flags = list(replica_flags or [])
        self.log_dir = log_dir
        #: Distributed-tracing root (ISSUE 18): when set, the balancer
        #: records its spans to ``<trace_dir>/balancer.jsonl``, every
        #: replica gets ``--trace-log``/``--flight-dir`` flags pointing
        #: into it, and the balancer's fleet-wide flight recorder
        #: bundles into ``<trace_dir>/flight``. ``cli trace-merge``
        #: stitches the per-process JSONLs into one Perfetto timeline.
        self.trace_dir = trace_dir
        self.ready_timeout = float(ready_timeout)
        self.port_file = port_file
        self.max_restarts = int(max_restarts)
        self.backoff_base_seconds = float(backoff_base_seconds)
        self.backoff_cap_seconds = float(backoff_cap_seconds)
        self.hang_kill_seconds = float(hang_kill_seconds)
        self.poll_interval = float(poll_interval)
        self.kill_grace_seconds = float(kill_grace_seconds)
        self.probe_interval = float(probe_interval)
        self.probe_timeout = float(probe_timeout)
        self.breaker_failures = int(breaker_failures)
        self.breaker_successes = int(breaker_successes)
        self.breaker_open_seconds = float(breaker_open_seconds)
        self.canary = canary
        self.rollout_step_timeout = float(rollout_step_timeout)
        self.coordinated = bool(coordinated)
        self._build_replica_argv = build_replica_argv
        self.replica_env_first_launch = dict(replica_env_first_launch or {})
        self._mu = threading.Lock()
        self._slots = [
            _ReplicaSlot(index=i) for i in range(self.num_replicas)
        ]
        self._restarts_total = 0
        #: Model directory replicas (re)launch from; the rollout
        #: coordinator advances it on every promoted generation.
        self._current_model_dir = model_dir
        self._logs: List = []
        self._tmp: Optional[str] = None
        self._stop = threading.Event()
        #: Set once the balancer + prober (+ coordinator) are live —
        #: the test/readiness barrier.
        self.ready = threading.Event()
        self.lb: Optional[LoadBalancer] = None
        self.coordinator: Optional[RolloutCoordinator] = None

    # -- replica launch ------------------------------------------------

    def _default_replica_argv(self, index: int,
                              port_file: str) -> List[str]:
        argv = [
            sys.executable, "-m", "glint_word2vec_tpu.cli", "serve",
            "--host", "127.0.0.1", "--port", "0",
            "--port-file", port_file,
        ]
        with self._mu:
            model = self._current_model_dir
        if self.coordinated or self.watch_dir is None:
            # Coordinated mode: the replica serves ONE generation and
            # swaps only when the rollout coordinator orders it.
            argv += ["--model", model]
        else:
            # Legacy uncoordinated mode: every replica follows the
            # publish dir itself (simultaneous fleet-wide swaps).
            if model:
                argv += ["--model", model]
            argv += [
                "--watch-checkpoint", self.watch_dir,
                "--watch-poll", str(self.watch_poll),
            ]
        if self.trace_dir:
            argv += [
                "--trace-log",
                os.path.join(self.trace_dir, f"replica-{index}.jsonl"),
                "--flight-dir",
                os.path.join(self.trace_dir, "flight"),
            ]
        return argv + list(self.replica_flags)

    def _argv(self, index: int, port_file: str) -> List[str]:
        if self._build_replica_argv is not None:
            return self._build_replica_argv(index, port_file)
        return self._default_replica_argv(index, port_file)

    def _open_log(self, index: int):
        if not self.log_dir:
            return None
        os.makedirs(self.log_dir, exist_ok=True)
        # graftlint: ignore[atomic-persist] append-mode process log, not an artifact
        f = open(
            os.path.join(self.log_dir, f"replica-{index}.log"), "ab"
        )
        self._logs.append(f)
        return f

    def _launch(self, slot: _ReplicaSlot) -> None:
        slot.launch_generation += 1
        slot.port_file = os.path.join(
            self._tmp,
            f"replica-{slot.index}.{slot.launch_generation}.port",
        )
        try:
            os.remove(slot.port_file)
        except OSError:
            pass
        env = dict(os.environ)
        env["GLINT_FLEET_GEN"] = slot.gen_tag()
        if slot.launch_generation == 0:
            # The chaos seam (PR 7's rank_env_first_launch pattern): a
            # GLINT_FAULTS schedule armed here fires once and is NOT
            # re-armed on the relaunch.
            env.update(self.replica_env_first_launch.get(slot.index, {}))
        log = self._open_log(slot.index)
        if log is not None:
            log.write(
                f"\n===== launch generation {slot.launch_generation} "
                f"replica {slot.index} =====\n".encode()
            )
            log.flush()
        slot.proc = subprocess.Popen(
            self._argv(slot.index, slot.port_file),
            env=env, stdout=log, stderr=log and subprocess.STDOUT,
            start_new_session=True,
        )
        slot.state = "starting"
        slot.started_at = time.monotonic()
        logger.info(
            "fleet: replica %d launched (generation %s, pid %d)",
            slot.index, slot.gen_tag(), slot.proc.pid,
        )

    def _read_port_file(self, slot: _ReplicaSlot) -> Optional[dict]:
        """The replica's readiness file, generation-verified: a stale
        file from a previous incarnation is never adopted."""
        try:
            with open(slot.port_file) as f:
                info = json.load(f)
        except (OSError, ValueError):
            return None
        gen = info.get("fleet_generation")
        if gen is not None and str(gen) != slot.gen_tag():
            return None
        return info

    # -- supervision ---------------------------------------------------

    def _schedule_restart(self, slot: _ReplicaSlot, reason: str) -> None:
        now = time.monotonic()
        with self._mu:
            if slot.restarts >= self.max_restarts:
                slot.state = "failed"
                slot.last_reason = reason
                logger.error(
                    "fleet: replica %d FAILED (%s) with restart budget "
                    "%d exhausted — left down, fleet serves from the "
                    "survivors", slot.index, reason, self.max_restarts,
                )
                if self.lb is not None:
                    self.lb.set_restarting(slot.index, False)
                return
            backoff = capped_backoff(
                slot.restarts, self.backoff_base_seconds,
                self.backoff_cap_seconds,
            )
            slot.restarts += 1
            self._restarts_total += 1
            slot.state = "backoff"
            slot.relaunch_at = now + backoff
            slot.detect_t = now
            slot.last_reason = reason
            slot.restart_records.append({
                "reason": reason,
                "backoff_seconds": round(backoff, 3),
                "launch_generation": slot.launch_generation,
                "detect_to_ready_seconds": None,
            })
        logger.error(
            "fleet: replica %d DOWN (%s); restart %d/%d in %.1fs",
            slot.index, reason, slot.restarts, self.max_restarts,
            backoff,
        )

    def _adopt(self, slot: _ReplicaSlot, info: dict) -> None:
        """A (re)launched replica published its generation-verified
        port file: point the balancer at it and half-open its breaker
        so the prober readmits it after M successes."""
        slot.host = info.get("host", "127.0.0.1")
        slot.port = int(info["port"])
        self.lb.set_replica_address(
            slot.index, slot.host, slot.port,
            generation=slot.gen_tag(),
        )
        self.lb.set_restarting(slot.index, False)
        self.lb.breakers[slot.index].clear_holds()
        self.lb.breakers[slot.index].trial()
        with self._mu:
            slot.state = "up"
            if slot.detect_t is not None and slot.restart_records:
                slot.restart_records[-1]["detect_to_ready_seconds"] = (
                    round(time.monotonic() - slot.detect_t, 3)
                )
                slot.detect_t = None
        logger.info(
            "fleet: replica %d ready on %s:%d (generation %s)",
            slot.index, slot.host, slot.port, slot.gen_tag(),
        )

    def _sweep(self) -> None:
        """One supervision pass over every slot."""
        now = time.monotonic()
        for slot in self._slots:
            if slot.state in ("failed", "stopped"):
                if slot.state == "failed" and self.lb is not None:
                    # Keep the breaker firmly open: no trials against
                    # a written-off address.
                    self.lb.breakers[slot.index].force_open()
                continue
            rc = slot.proc.poll() if slot.proc is not None else None
            if rc is not None and slot.state in ("up", "starting"):
                if self._stop.is_set():
                    slot.state = "stopped"
                    continue
                self.lb.set_restarting(slot.index, True)
                self.lb.breakers[slot.index].force_open()
                self._schedule_restart(
                    slot,
                    f"exited rc={rc}" if rc >= 0
                    else f"killed by signal {-rc}",
                )
                continue
            if slot.state == "up":
                failing = self.lb.breakers[slot.index].failing_for()
                if failing > self.hang_kill_seconds:
                    # Hung: the process lives but probes have failed
                    # continuously past the budget — put it down and
                    # treat it as a crash.
                    logger.error(
                        "fleet: replica %d HUNG (probes failing for "
                        "%.1fs) — killing pid %d", slot.index, failing,
                        slot.proc.pid,
                    )
                    self.lb.set_restarting(slot.index, True)
                    self.lb.breakers[slot.index].force_open()
                    terminate_process(
                        slot.proc, grace_seconds=self.kill_grace_seconds
                    )
                    self._schedule_restart(
                        slot, f"hung ({failing:.1f}s of probe failures)"
                    )
                continue
            if slot.state == "backoff":
                self.lb.set_restarting(slot.index, True)
                self.lb.breakers[slot.index].force_open()
                if now >= slot.relaunch_at:
                    self._launch(slot)
                continue
            if slot.state == "starting":
                self.lb.set_restarting(slot.index, True)
                self.lb.breakers[slot.index].force_open()
                info = self._read_port_file(slot)
                if info is not None:
                    self._adopt(slot, info)
                elif now - slot.started_at > self.ready_timeout:
                    terminate_process(
                        slot.proc, grace_seconds=self.kill_grace_seconds
                    )
                    self._schedule_restart(
                        slot,
                        f"not ready within {self.ready_timeout:.0f}s",
                    )

    # -- observability -------------------------------------------------

    def _doc_extra(self) -> dict:
        with self._mu:
            states = [
                {
                    "replica": s.index,
                    "state": s.state,
                    "restarts": s.restarts,
                    "launch_generation": s.launch_generation,
                    "last_reason": s.last_reason,
                    "restart_records": list(s.restart_records[-8:]),
                }
                for s in self._slots
            ]
            sup = {
                "restarts_total": self._restarts_total,
                "replicas_failed": sum(
                    1 for s in self._slots if s.state == "failed"
                ),
                "max_restarts": self.max_restarts,
                "replica_states": states,
            }
        doc = {"supervisor": sup}
        if self.coordinator is not None:
            doc["rollout"] = self.coordinator.stats()
        return doc

    def report(self) -> dict:
        """Restart accounting in the shape the drill records."""
        return self._doc_extra()

    # -- main loop -----------------------------------------------------

    def _resolve_boot(self) -> Optional[str]:
        """The generation name the fleet boots from (None when booting
        a plain model dir outside the publish protocol). Blocks until
        a first committed generation exists when only ``watch_dir``
        was given."""
        from glint_word2vec_tpu.streaming.publish import resolve_latest

        if self.model_dir is not None:
            if self.watch_dir is not None:
                md = os.path.abspath(self.model_dir)
                if os.path.dirname(md) == os.path.abspath(self.watch_dir):
                    return os.path.basename(md)
            return None
        if self.watch_dir is None:
            return None  # custom build_replica_argv owns the boot
        while not self._stop.is_set():
            gen_dir = resolve_latest(self.watch_dir)
            if gen_dir is not None:
                with self._mu:
                    self._current_model_dir = gen_dir
                return os.path.basename(gen_dir)
            logger.info(
                "fleet: waiting for a first committed generation in %s",
                self.watch_dir,
            )
            time.sleep(max(0.5, self.watch_poll))
        return None

    def _wait_initial_ready(self) -> None:
        """Block until every replica published its generation-verified
        port file; a replica dying before that is a boot error (fail
        fast — the operator misconfigured the fleet)."""
        deadline = time.time() + self.ready_timeout
        for slot in self._slots:
            while True:
                if self._stop.is_set():
                    return  # stop() during boot: run() exits promptly
                info = self._read_port_file(slot)
                if info is not None:
                    slot.host = info.get("host", "127.0.0.1")
                    slot.port = int(info["port"])
                    slot.state = "up"
                    break
                if slot.proc.poll() is not None:
                    raise RuntimeError(
                        f"replica {slot.index} exited "
                        f"rc={slot.proc.returncode} before binding its "
                        "port"
                    )
                if time.time() > deadline:
                    raise TimeoutError(
                        f"replica {slot.index} not ready in "
                        f"{self.ready_timeout}s"
                    )
                time.sleep(0.1)

    def run(self) -> int:
        """Launch the fleet and supervise until shut down (POST
        /shutdown on the balancer, SIGINT, or stop()). Returns 0 on a
        clean shutdown."""
        import tempfile

        boot_gen: Optional[str] = None
        with tempfile.TemporaryDirectory(prefix="glint_fleet_") as tmp:
            self._tmp = tmp
            try:
                boot_gen = self._resolve_boot()
                if self._stop.is_set():
                    return 0
                if self.trace_dir:
                    # Before the first replica launch: the replicas'
                    # --trace-log sinks open inside this directory.
                    os.makedirs(self.trace_dir, exist_ok=True)
                    obs_events.set_recorder(obs_events.EventRecorder(
                        jsonl_path=os.path.join(
                            self.trace_dir, "balancer.jsonl"
                        ),
                    ))
                for slot in self._slots:
                    self._launch(slot)
                self._wait_initial_ready()
                if self._stop.is_set():
                    return 0
                urls = [
                    f"http://{s.host}:{s.port}" for s in self._slots
                ]
                self.lb = LoadBalancer(
                    urls, host=self.host, port=self.port,
                    breaker_failures=self.breaker_failures,
                    breaker_successes=self.breaker_successes,
                    breaker_open_seconds=self.breaker_open_seconds,
                    probe_interval=self.probe_interval,
                    probe_timeout=self.probe_timeout,
                )
                for slot in self._slots:
                    self.lb.set_replica_address(
                        slot.index, slot.host, slot.port,
                        generation=slot.gen_tag(),
                    )
                self.lb.doc_extra = self._doc_extra
                self.lb.on_shutdown = self._stop.set
                if self.trace_dir:
                    self.lb.enable_flight_recorder(
                        os.path.join(self.trace_dir, "flight")
                    )
                if self.port_file:
                    from glint_word2vec_tpu.utils import atomic_write_json

                    atomic_write_json(
                        self.port_file,
                        {"host": self.lb.host, "port": self.lb.port},
                    )
                self.lb.start_background()
                self.lb.start_prober()
                if self.coordinated and self.watch_dir is not None:
                    with self._mu:
                        cur_dir = self._current_model_dir
                    self.coordinator = RolloutCoordinator(
                        self.lb, self.watch_dir,
                        poll_seconds=self.watch_poll,
                        current=boot_gen,
                        current_dir=cur_dir,
                        canary=self.canary,
                        step_timeout=self.rollout_step_timeout,
                        replica_ok=self._replica_ok,
                        on_generation=self._on_generation,
                    )
                    self.coordinator.start()
                logger.info(
                    "fleet up: %d replicas (%s) behind %s:%d%s",
                    self.num_replicas, ", ".join(urls),
                    self.lb.host, self.lb.port,
                    f", serving {boot_gen}" if boot_gen else "",
                )
                self.ready.set()
                try:
                    while not self._stop.is_set() \
                            and not self.lb.stopped():
                        self._sweep()
                        time.sleep(self.poll_interval)
                except KeyboardInterrupt:
                    pass
                return 0
            finally:
                self._stop.set()
                self.ready.set()
                if self.coordinator is not None:
                    self.coordinator.stop()
                if self.lb is not None:
                    self.lb.stop()
                for slot in self._slots:
                    if slot.proc is not None:
                        terminate_process(
                            slot.proc,
                            grace_seconds=self.kill_grace_seconds,
                        )
                for f in self._logs:
                    try:
                        f.close()
                    except OSError:
                        pass
                self._logs = []
                self._tmp = None

    def _replica_ok(self, i: int) -> bool:
        with self._mu:
            return self._slots[i].state not in ("failed", "stopped")

    def _on_generation(self, gen: str, gen_dir: str) -> None:
        """Rollout coordinator promoted ``gen`` fleet-wide: relaunches
        from now on boot from it (a replica restarting mid-rollout
        converges instead of resurrecting an old generation)."""
        with self._mu:
            self._current_model_dir = gen_dir

    def stop(self) -> None:
        self._stop.set()


def serve_fleet(
    model_dir: Optional[str],
    *,
    replicas: int = 2,
    host: str = "127.0.0.1",
    port: int = 8800,
    watch_dir: Optional[str] = None,
    replica_flags: Optional[List[str]] = None,
    log_dir: Optional[str] = None,
    ready_timeout: float = 900.0,
    port_file: Optional[str] = None,
    **supervisor_kwargs,
) -> int:
    """Launch ``replicas`` supervised serving processes following one
    model (or one publish dir) and front them with a breaker-aware
    :class:`LoadBalancer` in this process until killed.

    Each replica binds an ephemeral port and signals readiness through
    its generation-stamped ``--port-file`` — written only after the
    full serving warmup (and ANN build + recall gate, when enabled),
    so the balancer's first request never lands on a cold replica.
    ``replica_flags`` pass through to every ``cli serve`` invocation
    verbatim. Dead or hung replicas are relaunched by the
    :class:`FleetSupervisor` under capped backoff and a restart
    budget; with ``watch_dir``, generation moves are rolled out one
    replica at a time behind the shadow-canary gate (see
    ``supervisor_kwargs``: ``canary``, ``max_restarts``, breaker and
    probe knobs, ...). Returns the exit code (0 on clean shutdown).
    """
    return FleetSupervisor(
        model_dir,
        replicas=replicas,
        host=host,
        port=port,
        watch_dir=watch_dir,
        replica_flags=replica_flags,
        log_dir=log_dir,
        ready_timeout=ready_timeout,
        port_file=port_file,
        **supervisor_kwargs,
    ).run()
