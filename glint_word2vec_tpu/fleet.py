"""Self-healing horizontal serving: N supervised replicas behind one
breaker-aware load balancer, with rolling generation rollout and a
shadow-canary promotion gate.

PR 12 put N replica processes behind one round-robin proxy; this module
adds the robustness half (ISSUE 14) — the serving tier's PR 7:

* :class:`LoadBalancer` — the stdlib raw-socket proxy, now with a
  per-replica :class:`ReplicaBreaker` (closed / open / half-open)
  driven by an active health prober AND the data plane's own
  connection verdicts: K consecutive failures eject a replica from
  rotation (so a bouncing replica costs zero client latency instead of
  a timeout per round-robin turn), a cooldown half-opens it for prober
  trials, and M consecutive successes readmit it. Overload sheds
  (429/503) still retry onto the next replica and relay honest
  backpressure on exhaustion.

* :class:`FleetSupervisor` — the PR 7 supervisor machinery on the
  serving tier: launches the replica subprocesses, watches liveness
  two ways (``waitpid`` for crashes; sustained probe failure for
  hangs, with the ``GLINT_FLEET_GEN`` generation handshake so a stale
  pre-restart process can never answer for the new one), and
  relaunches dead or hung replicas from the fleet's current model
  directory under capped exponential backoff and a per-replica restart
  budget. A replica out of budget is left down and counted; the fleet
  serves from the survivors.

* :class:`RolloutCoordinator` — when ``LATEST.json`` moves, replicas
  are swapped ONE AT A TIME: drain via breaker hold, ``POST /reload``,
  wait healthy + warm (the swap added zero post-warmup compiles),
  readmit, next — fleet capacity never drops below N-1, and a
  generation that fails to stage halts the rollout with the old
  generation still serving everywhere else.

* Shadow-canary promotion gate (ROADMAP item 5's loop, closed): before
  the rollout proceeds, the candidate generation is staged on ONE held
  replica which never sees live traffic; a sampled slice of live
  queries is mirrored to it and scored for top-k agreement against the
  live fleet, alongside operator-defined probe queries
  (vienna/berlin-style synonym + capital-of analogy checks,
  QUALITY.json-style). Regression means automatic hold-back: the
  canary is restored to the live generation, the candidate is counted,
  exposed on ``/metrics``, and left on disk for postmortem.

Fault points ``fleet.replica_probe`` / ``fleet.rollout_step`` (and
``serving.reload`` on the replica side) drill every window;
``scripts/fleet_drill.py`` records FLEET_BENCH.json.

Replicas are plain ``serve`` processes: nothing here is in their code
path, so a balancer crash leaves N independently addressable servers.
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import random
import socket
import subprocess
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from glint_word2vec_tpu.obs import events as obs_events
from glint_word2vec_tpu.obs.slo import FlightRecorder, SloEngine
from glint_word2vec_tpu.parallel.supervisor import (
    capped_backoff,
    terminate_process,
)
from glint_word2vec_tpu.utils import faults
from glint_word2vec_tpu.utils.metrics import LatencyHistogram

logger = logging.getLogger(__name__)

#: Device-dispatch paths the balancer tracks per-endpoint latency/SLO
#: state for on its OWN forward path (mirrors serving._DEVICE_PATHS —
#: bounded cardinality by construction). QoS admission applies to
#: these paths only; control routes are never shed.
_BALANCER_PATHS = (
    "/synonyms", "/synonyms_vector", "/analogy", "/vector", "/transform",
)


def _strip_model_prefix(path: str) -> str:
    """Endpoint path with any ``/m/<id>`` multi-model routing prefix
    removed (mirrors serving.split_model_path, kept device-free here):
    QoS admission and the balancer's per-endpoint histograms must
    treat ``/m/a/synonyms`` as ``/synonyms`` — same admission
    population, same bounded metric cardinality — while the full path
    (model prefix included) is what gets forwarded to the replica."""
    if path.startswith("/m/"):
        sep = path.find("/", 3)
        return path[sep:] if sep >= 0 else "/"
    return path

#: Client headers the balancer interprets (QoS admission) and forwards
#: to the replica verbatim: tenant identity, priority class, and the
#: remaining-deadline budget (milliseconds) the replica tightens its
#: own request deadline with.
_QOS_WIRE_HEADERS = (
    ("X-Glint-Tenant", "x-glint-tenant"),
    ("X-Glint-Priority", "x-glint-priority"),
    ("X-Glint-Deadline-Ms", "x-glint-deadline-ms"),
)


def _passthrough_headers(headers: dict) -> Optional[dict]:
    """QoS/deadline headers to forward replica-ward, wire-cased."""
    out = None
    for wire, low in _QOS_WIRE_HEADERS:
        v = headers.get(low)
        if v:
            if out is None:
                out = {}
            out[wire] = v
    return out


def _parse_retry_after(headers: dict) -> Optional[float]:
    """Seconds from a (lowercase-keyed) response header dict, or None.
    Only the delta-seconds form — everything in this stack emits it."""
    v = headers.get("retry-after") if headers else None
    if v is None:
        return None
    try:
        return max(0.0, float(v))
    except (TypeError, ValueError):
        return None


def _read_request(sock, buf: bytearray):
    """Read one HTTP/1.1 request off a keep-alive socket: returns
    (method, path, lowercase-header dict, body) or None on a clean
    close between requests. Raises on transport errors or malformed
    framing. Content-Length framing only — the serving stack (and
    every client of it) never chunks."""
    while True:
        head_end = buf.find(b"\r\n\r\n")
        if head_end >= 0:
            break
        chunk = sock.recv(65536)
        if not chunk:
            if buf:
                raise ConnectionError("client closed mid-request")
            return None
        buf += chunk
    head = bytes(buf[:head_end]).decode("latin-1")
    lines = head.split("\r\n")
    parts = lines[0].split(None, 2)
    if len(parts) < 3:
        raise ValueError(f"malformed request line: {lines[0]!r}")
    method, path = parts[0], parts[1]
    headers = {}
    for line in lines[1:]:
        k, _, v = line.partition(":")
        headers[k.strip().lower()] = v.strip()
    clen = int(headers.get("content-length", 0))
    body_end = head_end + 4 + clen
    while len(buf) < body_end:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("client closed mid-body")
        buf += chunk
    body = bytes(buf[head_end + 4 : body_end])
    del buf[:body_end]
    return method, path, headers, body

#: Statuses that mean "this replica cannot take the request right now,
#: another one might": bounded admission / degraded mode (429), plus
#: 503 for a replica mid-restart behind a stale port. 404/400/504 are
#: NOT retried — they are answers about the request, not the replica.
_SHED_STATUSES = frozenset((429, 503))


class ReplicaBreaker:
    """Per-replica circuit breaker: closed / open / half-open.

    Fed by BOTH failure signals — the active health prober's verdicts
    and the data plane's own connection errors. ``fail_threshold``
    consecutive failures open the breaker (the replica is ejected from
    rotation, so clients stop paying its timeouts); after
    ``open_seconds`` the prober half-opens it with trial probes, and
    ``success_threshold`` consecutive successes re-close it. A
    half-open trial failure re-opens immediately.

    Separately from the state machine, an **administrative hold**
    (:meth:`hold` / :meth:`release`) takes the replica out of client
    rotation regardless of health — the rollout coordinator's drain
    seam, and what keeps a canary staging a CANDIDATE generation from
    ever serving live traffic.
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, *, fail_threshold: int = 3,
                 success_threshold: int = 2,
                 open_seconds: float = 2.0):
        self.fail_threshold = max(1, int(fail_threshold))
        self.success_threshold = max(1, int(success_threshold))
        self.open_seconds = float(open_seconds)
        self._mu = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0          # consecutive (closed-state) failures
        self._trial_successes = 0   # consecutive half-open successes
        self._opened_at: Optional[float] = None
        self._failing_since: Optional[float] = None
        self._held = 0
        self._opened_total = 0
        self._reopened_total = 0
        self._closed_total = 0
        self._probe_failures = 0
        self._probe_successes = 0
        #: Invoked on every CLOSED -> OPEN transition (a genuinely
        #: healthy replica just got ejected), OUTSIDE ``_mu`` — the
        #: flight recorder's breaker-trip snapshot hook scrapes every
        #: replica and must never run under the breaker lock. Cooldown
        #: refreshes and half-open re-opens do not re-fire.
        self.on_open: Optional[Callable[[], None]] = None

    def _fire_on_open(self) -> None:
        cb = self.on_open
        if cb is not None:
            try:
                cb()
            except Exception:  # pragma: no cover - defensive
                logger.exception("breaker on_open hook failed")

    def record_failure(self, probe: bool = False) -> None:
        """One failed probe or data-plane connection attempt."""
        opened = False
        with self._mu:
            if probe:
                self._probe_failures += 1
            if self._failing_since is None:
                self._failing_since = time.monotonic()
            if self._state == self.HALF_OPEN:
                # A failed trial re-opens immediately: the replica is
                # still bouncing, restart its cooldown.
                self._state = self.OPEN
                self._opened_at = time.monotonic()
                self._trial_successes = 0
                self._reopened_total += 1
            elif self._state == self.CLOSED:
                self._failures += 1
                if self._failures >= self.fail_threshold:
                    self._state = self.OPEN
                    self._opened_at = time.monotonic()
                    self._opened_total += 1
                    opened = True
        if opened:
            self._fire_on_open()

    def record_success(self, probe: bool = False) -> None:
        """One healthy probe answer or successful proxied exchange."""
        with self._mu:
            if probe:
                self._probe_successes += 1
            self._failing_since = None
            if self._state == self.HALF_OPEN:
                self._trial_successes += 1
                if self._trial_successes >= self.success_threshold:
                    self._state = self.CLOSED
                    self._failures = 0
                    self._trial_successes = 0
                    self._opened_at = None
                    self._closed_total += 1
            elif self._state == self.CLOSED:
                self._failures = 0

    def maybe_half_open(self) -> bool:
        """Prober seam: move open -> half-open once the cooldown
        elapsed. Returns True when the replica should receive a trial
        probe (it is half-open), False while still cooling (no traffic,
        no probes) or not open at all."""
        with self._mu:
            if (self._state == self.OPEN and self._opened_at is not None
                    and time.monotonic() - self._opened_at
                    >= self.open_seconds):
                self._state = self.HALF_OPEN
                self._trial_successes = 0
            return self._state == self.HALF_OPEN

    def force_open(self) -> None:
        """Supervisor seam: the replica process is KNOWN dead or
        restarting — eject immediately and keep refreshing the cooldown
        so no trial traffic flows until the supervisor readmits it."""
        opened = False
        with self._mu:
            if self._state == self.CLOSED:
                self._opened_total += 1
                opened = True
            self._state = self.OPEN
            self._opened_at = time.monotonic()
            self._trial_successes = 0
        if opened:
            self._fire_on_open()

    def trial(self) -> None:
        """Supervisor seam: a relaunched replica adopted a fresh
        address — go straight to half-open so it earns readmission
        through ``success_threshold`` probe successes (the PR 7
        don't-trust-a-fresh-worker pattern)."""
        with self._mu:
            self._state = self.HALF_OPEN
            self._trial_successes = 0
            self._failures = 0
            self._failing_since = None

    def hold(self) -> None:
        """Administrative ejection (rollout drain / canary staging)."""
        with self._mu:
            self._held += 1

    def release(self) -> None:
        with self._mu:
            self._held = max(0, self._held - 1)

    def clear_holds(self) -> None:
        """Supervisor seam, called when a RELAUNCHED replica's fresh
        address is adopted: any hold belonged to its previous
        incarnation (a rollout drain or canary staging that died under
        it) and the new process boots from the fleet's promoted
        generation — leaving the hold would park the replica serving
        nothing forever."""
        with self._mu:
            self._held = 0

    def held(self) -> bool:
        with self._mu:
            return self._held > 0

    def eligible(self) -> bool:
        """Whether client traffic may route here: closed and not
        administratively held."""
        with self._mu:
            return self._state == self.CLOSED and self._held == 0

    def state(self) -> str:
        with self._mu:
            return self._state

    def failing_for(self) -> float:
        """Seconds of CONTINUOUS failure (0.0 while healthy) — the
        fleet supervisor's hung-replica signal."""
        with self._mu:
            fs = self._failing_since
            return 0.0 if fs is None else time.monotonic() - fs

    def snapshot(self) -> dict:
        with self._mu:
            fs = self._failing_since
            return {
                "state": self._state,
                "held": self._held > 0,
                "consecutive_failures": self._failures,
                "trial_successes": self._trial_successes,
                "opened_total": self._opened_total,
                "reopened_total": self._reopened_total,
                "closed_total": self._closed_total,
                "probe_failures_total": self._probe_failures,
                "probe_successes_total": self._probe_successes,
                "failing_seconds": (
                    round(time.monotonic() - fs, 2)
                    if fs is not None else 0.0
                ),
            }


class _ReplicaConn:
    """One persistent keep-alive socket to a replica with a minimal
    HTTP/1.1 reader — the balancer's per-request cost IS the fleet's
    overhead floor, so the proxy hop skips ``http.client`` entirely.
    Owned by exactly one handler thread (per-thread pools), so no
    locking. The replica always answers Content-Length-framed JSON
    (serving.py's ``_send``)."""

    __slots__ = ("host", "port", "timeout", "addr_version", "_sock",
                 "_buf", "_sent", "_prefix")

    def __init__(self, host: str, port: int, timeout: float,
                 addr_version: int = 0):
        self.host, self.port, self.timeout = host, port, timeout
        #: Balancer address-table version this connection was built
        #: against: a supervisor relaunch bumps it, and the pool drops
        #: conns whose version is stale (a relaunched replica lives on
        #: a fresh ephemeral port).
        self.addr_version = addr_version
        self._sock = None
        self._buf = bytearray()
        self._sent = False
        self._prefix = (
            f"Host: {host}:{port}\r\n"
            "Content-Type: application/json\r\n"
            "Content-Length: "
        )

    def _connect(self):
        s = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )
        # NODELAY: requests/responses are small multi-segment writes;
        # Nagle + delayed ACK turns each proxied call into a ~40ms
        # stall otherwise (the PR 2 serving-side fix, outbound twin).
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s
        self._buf.clear()
        return s

    def roundtrip(self, method: str, path: str, body: bytes,
                  retryable: Optional[bool] = None,
                  trace_id: Optional[str] = None,
                  extra_headers: Optional[dict] = None):
        """One request/response exchange; returns (status, body,
        header-dict with lowercase keys). Raises on any transport
        error (caller drops the connection and tries the next
        replica). ``trace_id`` propagates the balancer's request trace
        to the replica (the ``X-Glint-Trace`` wire header — ISSUE 18).

        A stale keep-alive socket after a replica bounce fails in one
        of two places: the send (nothing reached a handler — always
        safe to retry on a fresh connection) or the receive AFTER a
        locally-"successful" send into a dead socket's buffer. The
        recv-side retry is taken exactly once and only for idempotent
        requests (GETs by default; override with ``retryable``) — a
        bounced replica then costs the client nothing instead of a
        surfaced transport error."""
        if retryable is None:
            retryable = method == "GET"
        trace_hdr = (
            f"{obs_events.TRACE_HEADER}: {trace_id}\r\n"
            if trace_id else ""
        )
        if extra_headers:
            trace_hdr += "".join(
                f"{k}: {v}\r\n" for k, v in extra_headers.items()
            )
        req = (
            f"{method} {path} HTTP/1.1\r\n{trace_hdr}{self._prefix}"
            f"{len(body)}\r\n\r\n"
        ).encode("latin-1") + body
        try:
            return self._exchange(req)
        except OSError:
            if self._sent and not retryable:
                raise
            self.close()
            self._connect()
            return self._exchange(req)

    def _exchange(self, req: bytes):
        sock = self._sock or self._connect()
        self._sent = False
        sock.sendall(req)
        self._sent = True
        buf = self._buf
        while True:
            head_end = buf.find(b"\r\n\r\n")
            if head_end >= 0:
                break
            chunk = sock.recv(65536)
            if not chunk:
                raise ConnectionError("replica closed mid-response")
            buf += chunk
        head = bytes(buf[:head_end]).decode("latin-1")
        lines = head.split("\r\n")
        status = int(lines[0].split(None, 2)[1])
        headers = {}
        clen = 0
        for line in lines[1:]:
            k, _, v = line.partition(":")
            k = k.strip().lower()
            v = v.strip()
            headers[k] = v
            if k == "content-length":
                clen = int(v)
        body_end = head_end + 4 + clen
        while len(buf) < body_end:
            chunk = sock.recv(65536)
            if not chunk:
                raise ConnectionError("replica closed mid-body")
            buf += chunk
        rbody = bytes(buf[head_end + 4 : body_end])
        del buf[:body_end]
        return status, rbody, headers

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None


class _BalancerMetrics:
    """Per-shard forward-path observability: one log-spaced latency
    histogram + error/count pair per device path, and a small
    :class:`SloEngine` over the same objectives the replicas use.

    Produces a SERVING-SHAPED snapshot (``endpoints`` + ``slo`` blocks
    only) so balancer shards fold through
    :func:`~glint_word2vec_tpu.obs.aggregate.merge_serving_snapshots`
    exactly like replicas — bucket-exact histogram merge, SLO window
    counts summed before burn re-derivation, no second code path."""

    def __init__(self, paths=_BALANCER_PATHS):
        self._mu = threading.Lock()
        self._paths = frozenset(paths)
        self._hists: Dict[str, LatencyHistogram] = {}
        self._errors: Dict[str, int] = {}
        self.slo = SloEngine.default_serving(paths)
        # p95 cache for the deadline-aware shed check: recomputed at
        # most every _P95_TTL seconds — quantile() walks 65 buckets,
        # too hot for every admission.
        self._p95: Dict[str, Tuple[float, float]] = {}

    _P95_TTL = 0.5

    def observe(self, path: str, seconds: float, status: int) -> None:
        if path not in self._paths:
            return
        with self._mu:
            h = self._hists.get(path)
            if h is None:
                h = self._hists[path] = LatencyHistogram()
                self._errors[path] = 0
            h.record(seconds)
            if int(status) >= 500:
                self._errors[path] += 1
        self.slo.observe(path, seconds, status)

    def p95_ms(self, path: str) -> Optional[float]:
        """Current p95 for ``path`` in ms (cached ~0.5s); None before
        any traffic — a deadline cannot be judged infeasible against
        nothing."""
        now = time.monotonic()
        with self._mu:
            cached = self._p95.get(path)
            if cached is not None and now - cached[0] < self._P95_TTL:
                return cached[1]
            h = self._hists.get(path)
            if h is None or h.n == 0:
                return None
            val = h.quantile(0.95) * 1e3
            self._p95[path] = (now, val)
            return val

    def snapshot(self) -> dict:
        with self._mu:
            endpoints = {}
            for path, h in self._hists.items():
                endpoints[path] = {
                    "count": h.n,
                    "errors": self._errors[path],
                    "p50_ms": round(h.quantile(0.50) * 1e3, 3),
                    "p95_ms": round(h.quantile(0.95) * 1e3, 3),
                    "p99_ms": round(h.quantile(0.99) * 1e3, 3),
                    "mean_ms": round(h.total / max(h.n, 1) * 1e3, 3),
                    "max_ms": round(h.max * 1e3, 3),
                    "hist": h.state(),
                }
        return {"endpoints": endpoints, "slo": self.slo.snapshot()}


@dataclass
class QosConfig:
    """QoS admission knobs for the balancer's device paths. Everything
    defaults to OFF — a fleet without QoS flags behaves exactly as
    before (deadline headers still propagate to replicas).

    ``tenant_rate``/``tenant_burst``: per-tenant token bucket (req/s,
    burst tokens) keyed on ``X-Glint-Tenant`` (the ``default`` bucket
    otherwise); ``bulk_max_inflight`` caps concurrently-forwarded
    requests in the ``bulk`` priority class (``X-Glint-Priority:
    bulk``; anything else is ``interactive``). Deadline-aware shedding
    is armed by the REQUEST (``X-Glint-Deadline-Ms``): a budget that
    cannot cover the balancer's current p95 for the path is shed
    immediately with Retry-After instead of occupying a replica slot
    to time out."""

    tenant_rate: Optional[float] = None
    tenant_burst: Optional[float] = None
    bulk_max_inflight: Optional[int] = None
    #: Distinct tenant buckets tracked; overflow tenants share the
    #: ``other`` bucket (bounded cardinality on /metrics too).
    max_tenants: int = 32


class _TokenBucket:
    __slots__ = ("rate", "burst", "tokens", "t")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self.tokens = self.burst
        self.t = now

    def take(self, now: float) -> bool:
        self.tokens = min(
            self.burst, self.tokens + (now - self.t) * self.rate
        )
        self.t = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class _QosDecision:
    """One admission verdict: ``shed`` is None on admit, else
    (status, body-obj, retry-after string); an admitted bulk request
    holds a bulk-inflight slot until :meth:`QosGate.release`."""

    __slots__ = ("shed", "cls", "tenant", "bulk_slot")

    def __init__(self, shed, cls, tenant, bulk_slot=False):
        self.shed = shed
        self.cls = cls
        self.tenant = tenant
        self.bulk_slot = bulk_slot


class QosGate:
    """Admission control at the fleet edge: deadline feasibility, then
    per-tenant token buckets, then the bulk-class inflight cap. Sheds
    are 429 + Retry-After — honest backpressure in the same shape the
    replicas' bounded admission emits, so clients need one retry
    policy."""

    def __init__(self, config: QosConfig,
                 p95_ms: Callable[[str], Optional[float]],
                 now_fn: Callable[[], float] = time.monotonic):
        self.config = config
        self._p95 = p95_ms
        self._now = now_fn
        self._mu = threading.Lock()
        self._buckets: Dict[str, _TokenBucket] = {}
        self._admitted = {"interactive": 0, "bulk": 0}
        self._shed = {"tenant_quota": 0, "bulk_inflight": 0, "deadline": 0}
        self._tenant_shed: Dict[str, int] = {}
        self._bulk_inflight = 0
        self._bulk_inflight_peak = 0

    def _tenant_key(self, tenant: str) -> str:
        if tenant in self._buckets or tenant in self._tenant_shed:
            return tenant
        tracked = set(self._buckets) | set(self._tenant_shed)
        if len(tracked) >= self.config.max_tenants:
            return "other"
        return tenant

    def _count_shed(self, reason: str, tenant: str) -> None:
        self._shed[reason] += 1
        self._tenant_shed[tenant] = self._tenant_shed.get(tenant, 0) + 1

    def admit(self, path: str, headers: dict) -> _QosDecision:
        cfg = self.config
        tenant = headers.get("x-glint-tenant") or "default"
        cls = (
            "bulk"
            if headers.get("x-glint-priority", "").lower() == "bulk"
            else "interactive"
        )
        now = self._now()
        with self._mu:
            tenant = self._tenant_key(tenant)
            # Deadline feasibility first: an expired-or-infeasible
            # budget is shed before it spends a quota token — the
            # client pays nothing for a request that could only 504.
            budget_ms = _parse_deadline_ms(headers)
            if budget_ms is not None:
                p95 = self._p95(path)
                if budget_ms <= 0.0 or (
                        p95 is not None and budget_ms < p95):
                    self._count_shed("deadline", tenant)
                    return _QosDecision((
                        429,
                        {
                            "error": "deadline infeasible",
                            "deadline_ms": budget_ms,
                            "p95_ms": p95,
                        },
                        "1",
                    ), cls, tenant)
            if cfg.tenant_rate:
                b = self._buckets.get(tenant)
                if b is None:
                    burst = (
                        cfg.tenant_burst
                        if cfg.tenant_burst is not None
                        else 2.0 * cfg.tenant_rate
                    )
                    b = self._buckets[tenant] = _TokenBucket(
                        cfg.tenant_rate, burst, now
                    )
                if not b.take(now):
                    self._count_shed("tenant_quota", tenant)
                    retry = max(1.0 / cfg.tenant_rate, 0.05)
                    return _QosDecision((
                        429,
                        {"error": "tenant quota exceeded",
                         "tenant": tenant},
                        f"{retry:g}",
                    ), cls, tenant)
            if cls == "bulk" and cfg.bulk_max_inflight:
                if self._bulk_inflight >= cfg.bulk_max_inflight:
                    self._count_shed("bulk_inflight", tenant)
                    return _QosDecision((
                        429,
                        {"error": "bulk class at capacity",
                         "max_inflight": cfg.bulk_max_inflight},
                        "0.1",
                    ), cls, tenant)
                self._bulk_inflight += 1
                if self._bulk_inflight > self._bulk_inflight_peak:
                    self._bulk_inflight_peak = self._bulk_inflight
                self._admitted[cls] += 1
                return _QosDecision(None, cls, tenant, bulk_slot=True)
            self._admitted[cls] += 1
            return _QosDecision(None, cls, tenant)

    def release(self, decision: _QosDecision) -> None:
        if decision.bulk_slot:
            with self._mu:
                self._bulk_inflight = max(0, self._bulk_inflight - 1)

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "admitted_total": dict(self._admitted),
                "shed_total": dict(self._shed),
                "per_tenant_shed_total": dict(self._tenant_shed),
                "bulk_inflight": self._bulk_inflight,
                "bulk_inflight_peak": self._bulk_inflight_peak,
            }


def _parse_deadline_ms(headers: dict) -> Optional[float]:
    v = headers.get("x-glint-deadline-ms")
    if v is None:
        return None
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


class LoadBalancer:
    """Round-robin HTTP proxy over serving replicas with per-replica
    circuit breakers, overload-aware retry, and a merged fleet
    exposition.

    Routes:
      GET  /healthz   fleet health: replicas up/total (200 while >= 1 up)
      GET  /metrics   merged fleet snapshot (JSON; ?format=prometheus
                      renders the merged serving exposition + the
                      glint_fleet_* balancer/breaker/rollout families)
      POST /shutdown  fan-out shutdown to every replica, then stop
      anything else   proxied to a replica (round robin over CLOSED
                      breakers; sheds retried on the next replica,
                      exhaustion relays the shed; open breakers are a
                      last resort, held replicas never serve)
    """

    #: Same-replica retries for a connection-refused inside a KNOWN
    #: restart window (the supervisor may land the relaunched
    #: replica's fresh address mid-retry).
    RESTART_RETRIES = 3
    RESTART_RETRY_BASE = 0.1

    #: When EVERY replica sheds, a replica-advertised Retry-After up to
    #: this many seconds is honored — back off max(jitter, Retry-After)
    #: then take ONE more full pass before relaying the shed. Larger
    #: values are relayed to the client immediately: parking a proxy
    #: thread for seconds would turn backpressure into queueing.
    RETRY_AFTER_CAP = 0.5

    #: ``replicas`` entries are replaced wholesale (one atomic tuple
    #: store) by ``set_replica_address`` under the lock; the hot-path
    #: readers take a single indexed load of an immutable tuple, where
    #: a stale read only means one more attempt against the old
    #: address — the retry/breaker machinery absorbs it. ``doc_extra``
    #: and ``on_shutdown`` are installed once by the fleet supervisor
    #: before the data plane starts.
    _ATOMIC_ATTRS = frozenset(
        {"replicas", "doc_extra", "on_shutdown", "flight"}
    )

    def __init__(self, replica_urls: List[str], host: str = "127.0.0.1",
                 port: int = 0, *, scrape_timeout: float = 2.0,
                 proxy_timeout: float = 60.0,
                 breaker_failures: int = 3,
                 breaker_successes: int = 2,
                 breaker_open_seconds: float = 2.0,
                 probe_interval: float = 0.5,
                 probe_timeout: float = 2.0,
                 reuse_port: bool = False,
                 listen_fd: Optional[int] = None,
                 control: bool = False,
                 shard_id: int = 0,
                 proxy_control: Optional[Tuple[str, int]] = None,
                 qos: Optional[QosConfig] = None):
        self.replicas = [self._parse(u) for u in replica_urls]
        if not self.replicas:
            raise ValueError("at least one replica url required")
        self.scrape_timeout = float(scrape_timeout)
        self.proxy_timeout = float(proxy_timeout)
        self.probe_interval = max(0.02, float(probe_interval))
        self.probe_timeout = float(probe_timeout)
        #: Which data-plane process this balancer is (0 = the
        #: supervisor-resident shard; >= 1 = a ``fleet-shard``
        #: subprocess sharing the listen port).
        self.shard_id = int(shard_id)
        #: (host, port) of the supervisor shard's CONTROL listener:
        #: shard subprocesses proxy /metrics and /shutdown there — the
        #: shared data port is not per-process addressable, and only
        #: the supervisor can render the fleet-merged document or tear
        #: the whole fleet down.
        self.proxy_control = proxy_control
        self._mu = threading.Lock()
        self._rr = 0
        self._proxied = [0] * len(self.replicas)
        self._errors = [0] * len(self.replicas)
        self._shed_retries = 0
        self._exhausted = 0
        self._breaker_skips = 0
        self._restart_retries = 0
        self._retry_after_honored = 0
        #: Forward-path latency/SLO state for THIS shard (serving-
        #: shaped snapshot; shards fold via merge_serving_snapshots).
        self.metrics = _BalancerMetrics()
        self.qos = (
            QosGate(qos, self.metrics.p95_ms) if qos is not None else None
        )
        self._addr_version = [0] * len(self.replicas)
        self._expected_gen: List[Optional[str]] = [None] * len(self.replicas)
        self._restarting = [False] * len(self.replicas)
        #: Shadow-mirror state (canary evaluations): None when off;
        #: else {"paths", "every", "seen", "queue", "dropped"} guarded
        #: by ``_mu`` — the coordinator drains the bounded queue.
        self._mirror: Optional[dict] = None
        self.breakers = [
            ReplicaBreaker(
                fail_threshold=breaker_failures,
                success_threshold=breaker_successes,
                open_seconds=breaker_open_seconds,
            )
            for _ in self.replicas
        ]
        #: Extra top-level blocks merged into ``metrics_doc`` (the
        #: fleet supervisor's restart/rollout/canary accounting).
        self.doc_extra: Optional[Callable[[], dict]] = None
        #: Invoked at the START of a POST /shutdown, before replicas
        #: are told to exit — the supervisor's don't-restart-the-dead
        #: flag must be up before the first replica goes down.
        self.on_shutdown: Optional[Callable[[], None]] = None
        #: Armed by :meth:`enable_flight_recorder`: the fleet-wide
        #: anomaly bundle writer, triggered by breaker CLOSED -> OPEN
        #: transitions.
        self.flight: Optional[FlightRecorder] = None
        self._local = threading.local()
        # Data plane: a thread-per-connection raw-socket loop with a
        # minimal HTTP/1.1 parser instead of ThreadingHTTPServer. The
        # balancer's per-request GIL time is the FLEET's throughput
        # ceiling — BaseHTTPRequestHandler's readline/email parsing and
        # per-response date formatting alone cost more than a whole
        # warmed ANN dispatch, and at N replicas the proxy must stay
        # the cheapest stage in the chain.
        self._reuse_port = bool(reuse_port) and hasattr(
            socket, "SO_REUSEPORT"
        )
        if listen_fd is not None:
            # SO_REUSEPORT fallback: adopt the listening socket the
            # parent bound and passed down (pass_fds) — all shards then
            # accept from ONE shared queue instead of per-socket ones.
            self._listener = socket.socket(fileno=listen_fd)
        else:
            self._listener = socket.socket(
                socket.AF_INET, socket.SOCK_STREAM
            )
            self._listener.setsockopt(
                socket.SOL_SOCKET, socket.SO_REUSEADDR, 1
            )
            if self._reuse_port:
                self._listener.setsockopt(
                    socket.SOL_SOCKET, socket.SO_REUSEPORT, 1
                )
            self._listener.bind((host, port))
            self._listener.listen(128)
        self.host, self.port = self._listener.getsockname()[:2]
        self._shared_listener = self._reuse_port or listen_fd is not None
        if self._shared_listener:
            # stop() cannot rely on the self-connect nudge here: a
            # connect to a SHARED port may be delivered to a sibling
            # shard's accept queue. A bounded accept timeout makes the
            # loop re-check _stop on its own clock instead.
            self._listener.settimeout(1.0)
        #: Private per-process control listener (always 127.0.0.1,
        #: ephemeral): the supervisor addresses ONE shard through it —
        #: /_shard/snapshot, /_shard/control mirror ops, /_shard/stop —
        #: which the shared data port cannot do.
        self._control_listener = None
        if control:
            self._control_listener = socket.socket(
                socket.AF_INET, socket.SOCK_STREAM
            )
            self._control_listener.setsockopt(
                socket.SOL_SOCKET, socket.SO_REUSEADDR, 1
            )
            self._control_listener.bind(("127.0.0.1", 0))
            self._control_listener.listen(16)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._control_thread: Optional[threading.Thread] = None
        self._prober: Optional[threading.Thread] = None
        self._prev_switch: Optional[float] = None

    @property
    def control_addr(self) -> Optional[Tuple[str, int]]:
        if self._control_listener is None:
            return None
        return self._control_listener.getsockname()[:2]

    # -- data plane ----------------------------------------------------

    _STATUS_LINE = {
        code: f"HTTP/1.1 {code} {reason}\r\n".encode("latin-1")
        for code, reason in (
            (200, "OK"), (400, "Bad Request"), (404, "Not Found"),
            (429, "Too Many Requests"), (500, "Internal Server Error"),
            (503, "Service Unavailable"), (504, "Gateway Timeout"),
        )
    }

    def _respond(self, sock, code: int, body: bytes, ctype: str,
                 retry_after: Optional[str] = None) -> None:
        head = self._STATUS_LINE.get(
            code, f"HTTP/1.1 {code} X\r\n".encode("latin-1")
        )
        extra = (
            f"Retry-After: {retry_after}\r\n" if retry_after else ""
        )
        sock.sendall(
            head
            + (
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(body)}\r\n{extra}\r\n"
            ).encode("latin-1")
            + body
        )

    def _respond_json(self, sock, code: int, obj,
                      retry_after: Optional[str] = None) -> None:
        self._respond(
            sock, code, json.dumps(obj).encode(), "application/json",
            retry_after,
        )

    def _accept_loop(self) -> None:
        self._accept_on(self._listener)

    def _control_accept_loop(self) -> None:
        self._accept_on(self._control_listener)

    def _accept_on(self, listener) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = listener.accept()
            except socket.timeout:
                continue  # shared-port shard: bounded re-check of _stop
            except OSError:
                return  # listener closed by stop()
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True,
                name="glint-fleet-conn",
            ).start()

    def _serve_conn(self, sock) -> None:
        """One client connection: parse requests with the minimal
        framed reader, route control paths locally, proxy the rest.
        Keep-alive by default (HTTP/1.1); 'Connection: close' honored."""
        try:
            faults.fire("fleet.shard_accept")
        except Exception:
            sock.close()
            return
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        buf = bytearray()
        try:
            while not self._stop.is_set():
                req = _read_request(sock, buf)
                if req is None:
                    return  # client closed between requests
                method, path, headers, body = req
                self._route(sock, method, path, headers, body)
                if headers.get("connection", "").lower() == "close":
                    return
        except (OSError, ValueError, ConnectionError):
            pass  # torn client connection / malformed request
        finally:
            sock.close()
            pool = getattr(self._local, "conns", None)
            if pool:
                for c in pool.values():
                    c.close()
                pool.clear()

    def _route(self, sock, method: str, path: str, headers: dict,
               body: bytes) -> None:
        url = urlparse(path)
        if url.path.startswith("/_shard/"):
            return self._route_shard(sock, method, url.path, body)
        if self.proxy_control is not None and (
                (method == "GET" and url.path == "/metrics")
                or (method == "POST" and url.path == "/shutdown")):
            # Shard subprocess: the fleet-merged exposition and the
            # fleet teardown live on the supervisor shard — relay.
            return self._proxy_to_control(sock, method, path, body)
        if method == "GET" and url.path == "/healthz":
            up, total, states = self.health()
            return self._respond_json(sock, 200 if up else 503, {
                "status": "ok" if up == total else (
                    "degraded" if up else "down"
                ),
                "replicas": total,
                "replicas_up": up,
                "replica_states": states,
            })
        if method == "GET" and url.path == "/metrics":
            doc = self.metrics_doc()
            fmt = parse_qs(url.query).get("format", ["json"])[0]
            if fmt == "prometheus":
                from glint_word2vec_tpu.obs.prometheus import (
                    fleet_to_prometheus,
                    serving_to_prometheus,
                )

                text = fleet_to_prometheus(doc)
                if doc.get("fleet"):
                    text += serving_to_prometheus(doc["fleet"])
                return self._respond(
                    sock, 200, text.encode(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            return self._respond_json(sock, 200, doc)
        if method == "POST" and url.path == "/shutdown":
            if self.on_shutdown is not None:
                self.on_shutdown()
            results = self.shutdown_fleet()
            self._respond_json(sock, 200, {
                "status": "shutting down fleet",
                "replicas": results,
            })
            threading.Thread(target=self.stop, daemon=True).start()
            return
        # QoS admission (device paths only): deadline feasibility,
        # tenant quota, bulk-class cap — sheds answer here, before a
        # replica slot or proxy thread is occupied.
        t0 = time.monotonic()
        decision = None
        ep = _strip_model_prefix(url.path)
        if self.qos is not None and ep in _BALANCER_PATHS:
            decision = self.qos.admit(ep, headers)
            if decision.shed is not None:
                status, obj, retry_after = decision.shed
                self.metrics.observe(
                    ep, time.monotonic() - t0, status
                )
                return self._respond_json(
                    sock, status, obj, retry_after=retry_after
                )
        # Distributed tracing (ISSUE 18): adopt the client's trace id
        # or mint one at the fleet edge; the balancer hop's root span
        # wraps the whole proxy exchange, and the id rides the wire
        # header so the replica's spans stitch to ours in trace-merge.
        tr = obs_events.request_trace(
            headers.get(obs_events.TRACE_HEADER.lower())
        )
        try:
            with tr.phase("req.accept", path=url.path, hop="balancer"):
                status, rbody, rheaders = self.forward(
                    method, path, body, trace=tr,
                    extra_headers=_passthrough_headers(headers),
                )
        finally:
            if decision is not None:
                self.qos.release(decision)
        tr.finish(status)
        self.metrics.observe(ep, time.monotonic() - t0, status)
        self._respond(
            sock, status, rbody,
            rheaders.get("content-type") or "application/json",
            rheaders.get("retry-after"),
        )

    # -- shard control channel (multi-process data plane) --------------

    def _route_shard(self, sock, method: str, path: str,
                     body: bytes) -> None:
        """The per-shard control surface the supervisor drives over
        each shard's private control listener: snapshot (local
        counters only — never scrapes replicas), breaker/address
        mirror ops, and stop."""
        if method == "GET" and path == "/_shard/snapshot":
            return self._respond_json(sock, 200, self.shard_snapshot())
        if method == "POST" and path == "/_shard/control":
            try:
                op = json.loads(body.decode() or "{}")
                out = self.apply_control(op)
            except (ValueError, KeyError, IndexError, TypeError) as e:
                return self._respond_json(
                    sock, 400, {"ok": False, "error": str(e)}
                )
            return self._respond_json(sock, 200, out)
        if method == "POST" and path == "/_shard/stop":
            self._respond_json(sock, 200, {"ok": True, "stopping": True})
            threading.Thread(target=self.stop, daemon=True).start()
            return
        return self._respond_json(sock, 404, {"error": "not found"})

    def shard_snapshot(self) -> dict:
        """This shard's own data-plane state: balancer counters,
        breaker views, and the serving-shaped forward-path block the
        supervisor folds through ``merge_serving_snapshots``."""
        return {
            "shard": self.shard_id,
            "up": True,
            "stats": self.balancer_stats(),
            "breakers": [b.snapshot() for b in self.breakers],
            "serving": self.metrics.snapshot(),
        }

    def apply_control(self, op: dict) -> dict:
        """Apply one supervisor mirror op. The supervisor owns the
        single control plane; shards replicate its address-table and
        breaker decisions so every data plane routes consistently
        while breaker STATE (probe verdicts, trip counts) stays
        per-shard and lock-free."""
        kind = str(op.get("op") or "")
        i = int(op.get("i", -1))
        if not 0 <= i < len(self.replicas):
            raise IndexError(f"replica index {i} out of range")
        b = self.breakers[i]
        if kind == "set_address":
            self.set_replica_address(
                i, str(op["host"]), int(op["port"]),
                generation=op.get("generation"),
            )
        elif kind == "set_restarting":
            self.set_restarting(i, bool(op.get("flag")))
        elif kind == "hold":
            b.hold()
        elif kind == "release":
            b.release()
        elif kind == "clear_holds":
            b.clear_holds()
        elif kind == "trial":
            b.trial()
        elif kind == "force_open":
            b.force_open()
        else:
            raise ValueError(f"unknown control op {kind!r}")
        return {"ok": True, "op": kind, "i": i}

    def _proxy_to_control(self, sock, method: str, path: str,
                          body: bytes) -> None:
        host, port = self.proxy_control
        try:
            conn = http.client.HTTPConnection(host, port, timeout=30.0)
            try:
                conn.request(
                    method, path, body=body or None,
                    headers={"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                data = resp.read()
                ctype = resp.getheader(
                    "Content-Type", "application/json"
                )
                status = resp.status
            finally:
                conn.close()
        except OSError as e:
            return self._respond_json(
                sock, 503, {"error": f"control plane unreachable: {e}"},
                retry_after="1",
            )
        self._respond(sock, status, data, ctype)

    @staticmethod
    def _parse(url: str):
        u = urlparse(url if "//" in url else f"http://{url}")
        return (u.hostname, int(u.port))

    # -- replica address table (supervisor seam) -----------------------

    def set_replica_address(self, i: int, host: str, port: int,
                            generation: Optional[str] = None) -> None:
        """Point replica slot ``i`` at a (re)launched process. Bumps
        the address version so every handler thread's cached
        keep-alive connection to the old incarnation is dropped on its
        next use; ``generation`` arms the /healthz handshake the
        prober verifies."""
        with self._mu:
            self.replicas[i] = (host, int(port))
            self._addr_version[i] += 1
            self._expected_gen[i] = generation

    def set_restarting(self, i: int, flag: bool) -> None:
        """Mark a replica as inside a known restart window: a
        connection-refused there is retried with jittered backoff
        (the address may land mid-retry) instead of counting as a
        dead-replica degrade."""
        with self._mu:
            self._restarting[i] = flag

    def is_restarting(self, i: int) -> bool:
        with self._mu:
            return self._restarting[i]

    def stopped(self) -> bool:
        return self._stop.is_set()

    # -- request forwarding --------------------------------------------

    def _conn(self, i: int) -> "_ReplicaConn":
        with self._mu:
            host, port = self.replicas[i]
            ver = self._addr_version[i]
        pool = getattr(self._local, "conns", None)
        if pool is None:
            pool = self._local.conns = {}
        c = pool.get(i)
        if c is not None and c.addr_version != ver:
            c.close()
            c = None
        if c is None:
            c = pool[i] = _ReplicaConn(
                host, port, self.proxy_timeout, addr_version=ver
            )
        return c

    def _drop_conn(self, i: int) -> None:
        pool = getattr(self._local, "conns", None)
        if pool and i in pool:
            try:
                pool.pop(i).close()
            except Exception:
                pass

    def _next_start(self) -> int:
        with self._mu:
            self._rr += 1
            return self._rr

    def _attempt(self, i: int, method: str, path: str, body: bytes,
                 trace_id: Optional[str] = None,
                 extra_headers: Optional[dict] = None):
        """One replica attempt; (status, body, headers) or None on
        connection failure (breaker and error accounting applied). A
        connection-refused inside a known restart window retries the
        SAME slot with jittered backoff — the supervisor may land the
        relaunched replica's fresh address mid-retry, and a bounce
        must not read as a dead-replica degrade."""
        for attempt in range(self.RESTART_RETRIES + 1):
            try:
                return self._conn(i).roundtrip(
                    method, path, body, trace_id=trace_id,
                    extra_headers=extra_headers,
                )
            except ConnectionRefusedError:
                self._drop_conn(i)
                if (not self.is_restarting(i)
                        or attempt >= self.RESTART_RETRIES):
                    break
                with self._mu:
                    self._restart_retries += 1
                time.sleep(
                    self.RESTART_RETRY_BASE * (attempt + 1)
                    * (0.5 + random.random())
                )
            except Exception:
                self._drop_conn(i)
                break
        with self._mu:
            self._errors[i] += 1
        self.breakers[i].record_failure()
        return None

    def forward(self, method: str, path: str, body: bytes, trace=None,
                extra_headers: Optional[dict] = None):
        """Send one request to the fleet: round-robin start over
        CLOSED breakers, advance on connection failure or a shed
        status (429/503), at most one attempt per replica. Returns
        (status, body, headers). When every replica sheds, a
        replica-advertised Retry-After within :attr:`RETRY_AFTER_CAP`
        is HONORED — back off max(jitter, Retry-After), then one more
        full pass — before the LAST shed response is relayed with its
        Retry-After intact, so the client sees the fleet's own
        backpressure, not an invented error. ``trace`` (a
        ``RequestTrace``) records one ``req.hop`` phase span per
        replica attempt and propagates its id to the replica over the
        wire header; ``extra_headers`` ride to the replica verbatim
        (tenant/priority/deadline propagation).

        Open/half-open breakers are skipped (each skip is a timeout a
        client did not pay) and only attempted as a last resort when
        no closed replica answered. Administratively HELD replicas are
        never attempted: a hold means a rollout drain, a canary
        serving a CANDIDATE generation, or a warm spare the autoscaler
        has parked — none may touch live traffic."""
        tr = trace if trace is not None else obs_events.NULL_TRACE
        n = len(self.replicas)
        last_shed = None
        attempted = 0
        for round_no in range(2):
            start = self._next_start()
            order = [(start + j) % n for j in range(n)]
            eligible = [i for i in order if self.breakers[i].eligible()]
            fallback = [
                i for i in order
                if not self.breakers[i].eligible()
                and not self.breakers[i].held()
            ]
            if len(eligible) < n:
                with self._mu:
                    self._breaker_skips += n - len(eligible)
            for i in eligible + fallback:
                with tr.phase("req.hop", replica=i) as hop:
                    got = self._attempt(
                        i, method, path, body,
                        trace_id=tr.trace_id or None,
                        extra_headers=extra_headers,
                    )
                    hop.update(
                        outcome="conn_error" if got is None
                        else int(got[0])
                    )
                attempted += 1
                if got is None:
                    continue
                status, rbody, rheaders = got
                # ANY HTTP answer proves the process is alive — a shed
                # is backpressure, not breakage.
                self.breakers[i].record_success()
                if status in _SHED_STATUSES:
                    last_shed = got
                    with self._mu:
                        self._shed_retries += 1
                    continue
                with self._mu:
                    self._proxied[i] += 1
                self._maybe_mirror(method, path, body, status, rbody)
                return got
            if round_no == 0 and last_shed is not None \
                    and not self._stop.is_set():
                retry_after = _parse_retry_after(last_shed[2])
                if retry_after is not None \
                        and retry_after <= self.RETRY_AFTER_CAP:
                    with self._mu:
                        self._retry_after_honored += 1
                    jitter = (
                        self.RESTART_RETRY_BASE
                        * (0.5 + random.random())
                    )
                    time.sleep(max(retry_after, jitter))
                    continue
            break
        with self._mu:
            self._exhausted += 1
        if last_shed is not None:
            return last_shed
        return (
            503,
            json.dumps({
                "error": f"no replica reachable ({attempted} tried)"
            }).encode(),
            {"Content-Type": "application/json", "Retry-After": "1"},
        )

    # -- shadow mirroring (canary evaluations) -------------------------

    def start_mirror(self, paths, every: int,
                     max_queue: int = 256) -> None:
        """Begin sampling live POST traffic on ``paths``: every
        ``every``-th successful response is queued as (path, body,
        status, response-body) for the canary scorer to drain. The
        queue is bounded; overflow is dropped and counted — mirroring
        must never apply backpressure to live clients."""
        with self._mu:
            self._mirror = {
                "paths": frozenset(paths),
                "every": max(1, int(every)),
                "seen": 0,
                "queue": deque(),
                "max_queue": max(1, int(max_queue)),
                "dropped": 0,
            }

    def drain_mirror(self, limit: int = 16) -> List[tuple]:
        with self._mu:
            m = self._mirror
            if m is None:
                return []
            out = []
            while m["queue"] and len(out) < limit:
                out.append(m["queue"].popleft())
            return out

    def stop_mirror(self) -> None:
        with self._mu:
            self._mirror = None

    def _maybe_mirror(self, method: str, path: str, body: bytes,
                      status: int, rbody: bytes) -> None:
        if method != "POST":
            return
        with self._mu:
            m = self._mirror
            if m is None or urlparse(path).path not in m["paths"]:
                return
            m["seen"] += 1
            if m["seen"] % m["every"]:
                return
            if len(m["queue"]) >= m["max_queue"]:
                m["dropped"] += 1
                return
            m["queue"].append((path, body, status, rbody))

    # -- active health probing -----------------------------------------

    def start_prober(self) -> None:
        """Start the active health prober: every ``probe_interval``
        each replica's ``/healthz`` is probed (2s default timeout) and
        the verdict feeds its breaker — K consecutive failures eject,
        a cooldown half-opens, M trial successes readmit. Replicas
        inside an open breaker's cooldown get NO probes (and no
        traffic)."""
        if self._prober is not None:
            return
        self._prober = threading.Thread(
            target=self._probe_loop, daemon=True,
            name="glint-fleet-prober",
        )
        self._prober.start()

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval):
            for i in range(len(self.replicas)):
                b = self.breakers[i]
                if b.state() == ReplicaBreaker.OPEN \
                        and not b.maybe_half_open():
                    continue  # cooling down: no probes either
                self.probe_replica(i)

    def probe_replica(self, i: int) -> bool:
        """One active /healthz probe of replica ``i``; feeds the
        breaker and returns the verdict. A probe is healthy only when
        the replica answers 200 AND — when the supervisor armed a
        launch generation — echoes the expected ``fleet_generation``
        (the PR 7 handshake: a stale pre-restart process must never
        answer for the new one)."""
        b = self.breakers[i]
        ok = False
        try:
            faults.fire("fleet.replica_probe")
            status, h = self._get_json(
                i, "/healthz", timeout=self.probe_timeout
            )
            with self._mu:
                expected = self._expected_gen[i]
            ok = status == 200
            if ok and expected is not None:
                ok = str(h.get("fleet_generation")) == str(expected)
        except Exception:
            ok = False
        if ok:
            b.record_success(probe=True)
        else:
            b.record_failure(probe=True)
        return ok

    # -- fleet views ---------------------------------------------------

    def _get_json(self, i: int, path: str,
                  timeout: Optional[float] = None):
        with self._mu:
            host, port = self.replicas[i]
        conn = http.client.HTTPConnection(
            host, port,
            timeout=self.scrape_timeout if timeout is None else timeout,
        )
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read().decode())
        finally:
            conn.close()

    def health(self):
        """(up, total, per-replica state) from each replica's
        /healthz; a dead replica reports "unreachable"."""
        states = []
        up = 0
        for i in range(len(self.replicas)):
            try:
                status, h = self._get_json(i, "/healthz")
                state = h.get("status", f"http {status}")
                if status == 200:
                    up += 1
            except Exception:
                state = "unreachable"
            states.append({
                "url": self.replica_url(i), "state": state,
                "breaker": self.breakers[i].state(),
            })
        return up, len(self.replicas), states

    def replica_url(self, i: int) -> str:
        with self._mu:
            host, port = self.replicas[i]
        return f"http://{host}:{port}"

    def balancer_stats(self) -> dict:
        with self._mu:
            out = {
                "shed_retries_total": self._shed_retries,
                "exhausted_total": self._exhausted,
                "proxied_total": int(sum(self._proxied)),
                "proxy_errors_total": int(sum(self._errors)),
                "breaker_skips_total": self._breaker_skips,
                "restart_retries_total": self._restart_retries,
                "retry_after_honored_total": self._retry_after_honored,
            }
        if self.qos is not None:
            out["qos"] = self.qos.snapshot()
        return out

    def metrics_doc(self) -> dict:
        """The merged fleet document: per-replica snapshots (scraped
        now, failures reported not fatal) with breaker state, the PR 8
        exact merge as ``fleet``, the balancer's own counters, and —
        when a fleet supervisor is attached — its restart/rollout/
        canary blocks."""
        from glint_word2vec_tpu.obs.aggregate import (
            merge_serving_snapshots,
        )

        replicas = []
        snaps = []
        with self._mu:
            proxied = list(self._proxied)
            errors = list(self._errors)
            restarting = list(self._restarting)
        for i in range(len(self.replicas)):
            entry: Dict[str, object] = {
                "url": self.replica_url(i),
                "proxied_total": proxied[i],
                "proxy_errors_total": errors[i],
                "breaker": self.breakers[i].snapshot(),
                "restarting": restarting[i],
            }
            try:
                _, snap = self._get_json(i, "/metrics")
                entry["up"] = True
                entry["snapshot"] = snap
                snaps.append(snap)
            except Exception as e:
                entry["up"] = False
                entry["scrape_error"] = str(e)
            replicas.append(entry)
        doc = {
            "replicas": replicas,
            "fleet": merge_serving_snapshots(snaps),
            "balancer": self.balancer_stats(),
        }
        extra = self.doc_extra() if self.doc_extra is not None else None
        if extra:
            doc.update(extra)
        return doc

    def shutdown_fleet(self) -> List[dict]:
        """POST /shutdown to every replica (best effort)."""
        results = []
        for i in range(len(self.replicas)):
            with self._mu:
                host, port = self.replicas[i]
            try:
                conn = http.client.HTTPConnection(
                    host, port, timeout=self.scrape_timeout
                )
                try:
                    conn.request(
                        "POST", "/shutdown", body=b"{}",
                        headers={"Content-Type": "application/json"},
                    )
                    resp = conn.getresponse()
                    resp.read()
                    results.append({
                        "url": self.replica_url(i),
                        "status": resp.status,
                    })
                finally:
                    conn.close()
            except Exception as e:
                results.append({
                    "url": self.replica_url(i), "error": str(e),
                })
        return results

    # -- anomaly flight recorder (ISSUE 18) ----------------------------

    def enable_flight_recorder(
        self, out_dir: str, *, window_seconds: float = 30.0,
        min_interval_seconds: float = 60.0,
    ) -> FlightRecorder:
        """Arm the fleet-wide anomaly flight recorder: a breaker's
        CLOSED -> OPEN transition (a healthy replica just got ejected)
        snapshots the last ``window_seconds`` of spans and metrics from
        the balancer AND every reachable replica into a postmortem
        bundle under ``out_dir``. Bundles are rate-limited; the
        recorder never raises into the data plane."""
        fl = FlightRecorder(
            out_dir, window_seconds=window_seconds,
            min_interval_seconds=min_interval_seconds,
        )
        fl.add_source("balancer", self._flight_balancer)
        fl.add_source("replica_spans", self._flight_replica_spans)
        fl.add_source("replica_metrics", self._flight_replica_metrics)
        self.flight = fl
        for i, b in enumerate(self.breakers):
            b.on_open = (
                lambda i=i: fl.trigger("breaker_open", replica=i)
            )
        return fl

    def _flight_balancer(self, window_seconds: float) -> dict:
        doc: Dict[str, object] = {
            "balancer": self.balancer_stats(),
            "breakers": [b.snapshot() for b in self.breakers],
        }
        rec = obs_events.get_recorder()
        if rec is not None:
            doc["spans"] = rec.recent_events(window_seconds)
            doc["anchor"] = {
                "wall_t0": rec.wall_t0, "mono_t0": rec.mono_t0,
            }
        return doc

    def _flight_replica_spans(self, window_seconds: float) -> dict:
        """Every replica's recent span window (its /trace route): the
        bundle shows what the whole fleet was doing when the anomaly
        fired, not just the process that noticed it."""
        out = {}
        for i in range(len(self.replicas)):
            try:
                _, doc = self._get_json(
                    i, f"/trace?seconds={window_seconds:g}"
                )
                out[f"replica_{i}"] = {
                    "url": self.replica_url(i), "trace": doc,
                }
            except Exception as e:
                out[f"replica_{i}"] = {
                    "url": self.replica_url(i), "error": str(e),
                }
        return out

    def _flight_replica_metrics(self, window_seconds: float) -> dict:
        out = {}
        for i in range(len(self.replicas)):
            try:
                _, snap = self._get_json(i, "/metrics")
                out[f"replica_{i}"] = {
                    "url": self.replica_url(i), "snapshot": snap,
                }
            except Exception as e:
                out[f"replica_{i}"] = {
                    "url": self.replica_url(i), "error": str(e),
                }
        return out

    # -- lifecycle -----------------------------------------------------

    def _tighten_gil_switch(self) -> None:
        # One handler thread per client connection, each a chain of
        # short GIL-holding sections (parse, forward, relay): at the
        # default 5ms switch interval the convoy adds whole scheduling
        # quanta per proxied call (the same effect serving.py tightens
        # for). Restored by stop().
        if self._prev_switch is None:
            self._prev_switch = sys.getswitchinterval()
            sys.setswitchinterval(0.001)

    def _start_control(self) -> None:
        if self._control_listener is None or \
                self._control_thread is not None:
            return
        self._control_thread = threading.Thread(
            target=self._control_accept_loop, daemon=True,
            name="glint-fleet-control",
        )
        self._control_thread.start()

    def serve_forever(self) -> None:
        logger.info(
            "fleet balancer on %s:%d over %d replica(s)",
            self.host, self.port, len(self.replicas),
        )
        self._tighten_gil_switch()
        self._start_control()
        self._accept_loop()

    def start_background(self) -> None:
        self._tighten_gil_switch()
        self._start_control()
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="glint-fleet-lb",
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # Waking a thread blocked in accept() needs more than close():
        # on Linux, closing the fd from another thread leaves the
        # accept blocked forever. shutdown() wakes it with EINVAL; the
        # best-effort self-connect covers platforms where it doesn't —
        # EXCEPT on a shared (SO_REUSEPORT / inherited-fd) port, where
        # the kernel may deliver the nudge connection to a SIBLING
        # shard's queue; those accept loops run with a bounded accept
        # timeout instead and notice _stop on their own clock.
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        if not self._shared_listener:
            try:
                socket.create_connection(
                    (self.host, self.port), timeout=1
                ).close()
            except OSError:
                pass
        try:
            self._listener.close()
        except OSError:  # pragma: no cover - already closed
            pass
        if self._control_listener is not None:
            ctrl_addr = None
            try:
                ctrl_addr = self._control_listener.getsockname()[:2]
                self._control_listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            if ctrl_addr is not None:
                # The control listener is private (never shared), so
                # the self-connect nudge is reliable there.
                try:
                    socket.create_connection(
                        ctrl_addr, timeout=1
                    ).close()
                except OSError:
                    pass
            try:
                self._control_listener.close()
            except OSError:  # pragma: no cover - already closed
                pass
        if self._prev_switch is not None:
            sys.setswitchinterval(self._prev_switch)
            self._prev_switch = None


# ----------------------------------------------------------------------
# Rolling rollout + shadow-canary promotion gate
# ----------------------------------------------------------------------


def _topk_overlap(a, b, k: int) -> Optional[float]:
    """Agreement score between two /synonyms-or-/analogy JSON answers:
    |intersection| / max(|a|, |b|) over the top-k words. None when
    either side is not a scoreable hit list."""
    try:
        wa = [x[0] for x in a][: max(1, int(k))]
        wb = [x[0] for x in b][: max(1, int(k))]
    except (TypeError, IndexError):
        return None
    if not wa and not wb:
        return None
    sa, sb = set(wa), set(wb)
    return len(sa & sb) / max(len(sa), len(sb), 1)


class CanaryConfig:
    """Knobs for the shadow-canary promotion gate.

    ``probes`` are operator-defined deterministic checks — each a
    ``{"path": "/synonyms"|"/analogy", "body": {...}}`` request posted
    to BOTH the live fleet and the canary and scored for top-k
    agreement (the vienna/berlin + capital-of analogy gates of
    QUALITY.json, restated as live-vs-candidate agreement so no
    expected-answer labels are needed). Mirrored live traffic — every
    ``mirror_every``-th request on ``mirror_paths`` — adds organic
    samples until ``min_scores`` are collected or ``mirror_seconds``
    elapse. The mean agreement must clear ``agreement_gate`` or the
    candidate is held back. Choose probe words stable across
    generations: a live-404/canary-404 pair is unscorable (skipped),
    a one-sided 404 scores 0.
    """

    def __init__(self, *, mirror_paths=("/synonyms", "/analogy"),
                 mirror_every: int = 4, min_scores: int = 8,
                 mirror_seconds: float = 10.0,
                 agreement_gate: float = 0.6, top_k: int = 10,
                 probes: Optional[List[dict]] = None):
        self.mirror_paths = tuple(mirror_paths)
        self.mirror_every = max(1, int(mirror_every))
        self.min_scores = max(0, int(min_scores))
        self.mirror_seconds = float(mirror_seconds)
        self.agreement_gate = float(agreement_gate)
        self.top_k = max(1, int(top_k))
        self.probes = list(probes or [])

    def scoped(self, model_id: str) -> "CanaryConfig":
        """The same gate addressed to ONE catalog model (ISSUE 20):
        probe and mirror paths gain the ``/m/<id>`` routing prefix, so
        a per-model rollout canaries against that model's live
        traffic/answers only."""
        prefix = f"/m/{model_id}"
        return CanaryConfig(
            mirror_paths=tuple(prefix + p for p in self.mirror_paths),
            mirror_every=self.mirror_every,
            min_scores=self.min_scores,
            mirror_seconds=self.mirror_seconds,
            agreement_gate=self.agreement_gate,
            top_k=self.top_k,
            probes=[
                {**p, "path": prefix + str(p.get("path", "/synonyms"))}
                for p in self.probes
            ],
        )


class ReplicaHoldLedger:
    """The replica-hold ownership protocol the rollout coordinator and
    the autoscaler share (ISSUE 19): every administrative hold on a
    replica is owned by a NAMED owner — ``"rollout"`` (drain during a
    swap, or a canary serving a candidate) or ``"autoscale"`` (a warm
    spare parked out of rotation) — and applied through one pair of
    callbacks (ref-counted breaker holds on the supervisor shard,
    fanned out to every balancer shard by the data-plane facade).

    The protocol:
      * one hold per (owner, replica) — double-acquire is a no-op;
      * owners compose: a rollout may drain a PARKED spare (swapping
        it keeps the spare warm on the promoted generation) and
        releasing the rollout's hold leaves it parked;
      * a replica held by anyone besides the autoscaler is NEVER spare
        capacity (a held canary must not be readmitted by a scale-up);
      * after a relaunch wipes a replica's breaker holds
        (``clear_holds`` in supervisor adoption), :meth:`reapply`
        restores every surviving owner's hold — a parked spare that
        crashed comes back parked, not serving."""

    def __init__(self, hold: Callable[[int], None],
                 release: Callable[[int], None],
                 clear: Optional[Callable[[int], None]] = None):
        self._hold = hold
        self._release = release
        self._clear = clear
        self._mu = threading.Lock()
        self._owners: Dict[int, set] = {}

    def acquire(self, owner: str, i: int) -> bool:
        with self._mu:
            owners = self._owners.setdefault(i, set())
            if owner in owners:
                return False
            owners.add(owner)
        self._hold(i)
        return True

    def release(self, owner: str, i: int) -> bool:
        with self._mu:
            owners = self._owners.get(i) or set()
            if owner not in owners:
                return False
            owners.discard(owner)
        self._release(i)
        return True

    def owners(self, i: int) -> frozenset:
        with self._mu:
            return frozenset(self._owners.get(i) or ())

    def parked(self, owner: str) -> List[int]:
        """Replicas held by ``owner`` and NOBODY else — the only ones
        that count as spare capacity when ``owner == "autoscale"``."""
        with self._mu:
            return sorted(
                i for i, owners in self._owners.items()
                if owners == {owner}
            )

    def reapply(self, i: int) -> None:
        """Re-assert every owner's hold on ``i`` after a relaunch
        cleared the replica's breaker holds."""
        with self._mu:
            owners = sorted(self._owners.get(i) or ())
        if self._clear is not None:
            self._clear(i)
        for _ in owners:
            self._hold(i)

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "held": {
                    str(i): sorted(owners)
                    for i, owners in self._owners.items() if owners
                },
            }


class RolloutCoordinator:
    """Orders fleet-wide generation rollouts, one replica at a time.

    Follows ``LATEST.json`` the way the serving ``SnapshotWatcher``
    does, but instead of letting every replica swap simultaneously it
    drives the sequence: (canary gate, when configured) then for each
    replica — breaker hold, drain, ``POST /reload`` with the explicit
    generation dir, wait healthy + warm (the swap added zero
    post-warmup compiles), readmit. Fleet capacity never drops below
    N-1 replicas.

    Failure taxonomy:
      * replica unavailable (dead / mid-restart / not yet readmitted):
        the rollout HALTS — the old generation keeps serving on every
        un-swapped replica — and is retried on a later poll once the
        fleet is whole again;
      * staging failure (replica answered /reload with an error): the
        generation is marked failed and NOT retried until the pointer
        moves (the SnapshotWatcher contract, fleet-wide);
      * canary regression: the candidate is held back — canary
        restored to the live generation, counted, left on disk.
    """

    def __init__(self, lb: LoadBalancer, watch_dir: str, *,
                 poll_seconds: float = 1.0,
                 current: Optional[str] = None,
                 current_dir: Optional[str] = None,
                 canary: Optional[CanaryConfig] = None,
                 step_timeout: float = 600.0,
                 drain_seconds: float = 0.25,
                 replica_ok: Optional[Callable[[int], bool]] = None,
                 on_generation=None,
                 holds: Optional[ReplicaHoldLedger] = None,
                 model_id: Optional[str] = None):
        self.lb = lb
        self.watch_dir = watch_dir
        #: Which catalog model this coordinator rolls (None = the
        #: default). A per-model coordinator reloads through the
        #: ``/m/<id>/`` routing prefix and reads the replica's
        #: per-model metrics block, so one model's pointer move swaps
        #: ONLY that model's tables — every other model's generation,
        #: caches, and counters on the same replicas stay untouched.
        self.model_id = model_id
        prefix = f"/m/{model_id}" if model_id else ""
        self._reload_path = prefix + "/reload"
        self._metrics_path = prefix + "/metrics"
        self._healthz_path = prefix + "/healthz"
        self.poll_seconds = max(0.05, float(poll_seconds))
        self.canary = canary
        self.step_timeout = float(step_timeout)
        self.drain_seconds = float(drain_seconds)
        self._replica_ok = replica_ok or (lambda i: True)
        self.on_generation = on_generation
        #: Shared hold-ownership ledger (supervisor-provided when an
        #: autoscaler coexists); standalone use gets a private ledger
        #: over this balancer's breakers.
        self.holds = holds if holds is not None else ReplicaHoldLedger(
            lambda i: lb.breakers[i].hold(),
            lambda i: lb.breakers[i].release(),
        )
        self._mu = threading.Lock()
        #: Generation name the whole fleet serves (None when booted
        #: from a plain --model dir outside the publish protocol).
        self.current = current
        #: Model directory replicas (re)launch from — the previous
        #: generation the canary is restored to on hold-back.
        self.current_dir = current_dir
        self._failed: Optional[str] = None
        self._held_back: Optional[str] = None
        self._in_progress = False
        self._phase = "idle"
        self._stats = {
            "rollouts_started_total": 0,
            "rollouts_completed_total": 0,
            "rollouts_halted_total": 0,
            "rollout_steps_total": 0,
            "generations_failed_total": 0,
            "watch_errors_total": 0,
            "canary": {
                "evaluations_total": 0,
                "holdbacks_total": 0,
                "last_agreement": None,
                "last_scored": 0,
                "last_generation": None,
                "last_verdict": None,
                "agreement_gate": (
                    canary.agreement_gate if canary is not None else None
                ),
            },
        }
        self._poll_mu = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- pointer following ---------------------------------------------

    def poll_once(self) -> Optional[str]:
        """One pointer check; returns the generation name when a full
        rollout completed, else None. Never raises."""
        with self._poll_mu:
            return self._poll_once_locked()

    def _poll_once_locked(self) -> Optional[str]:
        from glint_word2vec_tpu.streaming.publish import read_latest

        try:
            latest = read_latest(self.watch_dir, raise_errors=True)
        except (OSError, ValueError) as e:
            with self._mu:
                self._stats["watch_errors_total"] += 1
            logger.warning(
                "rollout coordinator: transient pointer read error: %s "
                "(retrying next poll)", e,
            )
            return None
        if latest is None:
            return None
        gen = str(latest["generation"])
        with self._mu:
            if gen in (self.current, self._failed, self._held_back):
                return None
        gen_dir = os.path.join(self.watch_dir, gen)
        try:
            return self._rollout(gen, gen_dir)
        except Exception as e:  # pragma: no cover - defensive
            logger.error("rollout of %s failed unexpectedly: %s", gen, e)
            return self._halt(gen, f"unexpected error: {e}")

    def _rollout(self, gen: str, gen_dir: str) -> Optional[str]:
        lb = self.lb
        n = len(lb.replicas)
        ok_idx = [i for i in range(n) if self._replica_ok(i)]
        with self._mu:
            self._stats["rollouts_started_total"] += 1
            self._in_progress = True
            self._phase = "starting"
        # A hot-swap arriving while a replica is mid-restart WAITS: the
        # rollout needs the whole (non-written-off) fleet serving, so
        # it halts and retries once the supervisor readmits the
        # replica — never racing a relaunch with a reload.
        not_ready = [
            i for i in ok_idx if not self._ready_for_rollout(i)
        ]
        if not ok_idx or not_ready:
            return self._halt(
                gen,
                f"replicas not serving: {not_ready or 'all written off'}",
            )
        completed: List[int] = []
        if self.canary is not None and len(ok_idx) < 2:
            if len(lb.replicas) >= 2:
                # Configured for canarying but degraded below a live
                # pair: never roll an unvetted candidate onto the only
                # serving replica — wait for the supervisor to restore
                # a peer, then evaluate properly.
                return self._halt(
                    gen, "canary gate needs >= 2 serving replicas "
                    f"(only {len(ok_idx)} left)",
                )
            # A deliberately single-replica fleet cannot canary (there
            # is no live side to hold out) — proceed, loudly.
            logger.warning(
                "single-replica fleet: canary gate impossible, "
                "rolling %s without evaluation", gen,
            )
        if self.canary is not None and len(ok_idx) >= 2:
            verdict = self._canary_phase(ok_idx[0], gen, gen_dir)
            if verdict == "held_back":
                with self._mu:
                    self._held_back = gen
                    self._stats["canary"]["holdbacks_total"] += 1
                    self._in_progress = False
                    self._phase = "held_back"
                    cur = self.current
                logger.error(
                    "canary HELD BACK %s: live generation %s keeps "
                    "serving everywhere; candidate left on disk at %s",
                    gen, cur, gen_dir,
                )
                return None
            if verdict == "stage_failed":
                return self._stage_failed(gen)
            if verdict != "pass":
                return self._halt(gen, f"canary: {verdict}")
            completed.append(ok_idx[0])
        for i in ok_idx:
            if i in completed:
                continue
            try:
                faults.fire("fleet.rollout_step")
            except Exception as e:
                return self._halt(gen, f"rollout step fault: {e}")
            with self._mu:
                self._stats["rollout_steps_total"] += 1
                self._phase = "rolling"
            if not self._replica_ok(i) or not self._ready_for_rollout(i):
                # Replica killed mid-rollout: halt — the old generation
                # keeps serving on every un-swapped replica, and the
                # next poll retries once the fleet is whole.
                return self._halt(gen, f"replica {i} unavailable")
            # Hold only when a SERVING peer can absorb the drained
            # traffic: written-off replicas don't count, so the sole
            # survivor of a degraded fleet is never held (its reload
            # stages off the request path anyway).
            res = self._swap_replica(
                i, gen, gen_dir, hold=len(ok_idx) > 1
            )
            if res == "stage_failed":
                return self._stage_failed(gen)
            if res != "ok":
                return self._halt(gen, f"replica {i}: {res}")
        with self._mu:
            self.current = gen
            self.current_dir = gen_dir
            self._stats["rollouts_completed_total"] += 1
            self._in_progress = False
            self._phase = "idle"
        if self.on_generation is not None:
            self.on_generation(gen, gen_dir)
        logger.info(
            "rollout complete: fleet promoted to %s (%d replicas)",
            gen, len(ok_idx),
        )
        return gen

    def _ready_for_rollout(self, i: int) -> bool:
        """A replica is rollable when its breaker is serving-eligible
        OR it is a healthy warm spare parked ONLY by the autoscaler:
        spares are swapped too (they must stay warm on the promoted
        generation, ready for a zero-compile readmit) and must never
        stall a rollout. Any other hold — a canary carrying a
        candidate, a drain in progress — still blocks."""
        b = self.lb.breakers[i]
        if b.eligible():
            return True
        return (
            b.state() == ReplicaBreaker.CLOSED
            and self.holds.owners(i) == frozenset(("autoscale",))
        )

    def in_progress(self) -> bool:
        """Cheap rollout-pinning flag for the autoscaler: while a
        rollout (canary phase included) is in flight the replica set
        is PINNED — no scale transitions may fight the swap order."""
        with self._mu:
            return self._in_progress

    def _halt(self, gen: str, reason: str) -> None:
        """Transient abort: retried on a later poll (the pointer still
        names the generation)."""
        with self._mu:
            self._stats["rollouts_halted_total"] += 1
            self._in_progress = False
            self._phase = "halted"
            cur = self.current
        logger.warning(
            "rollout of %s HALTED: %s — old generation %s still "
            "serving on un-swapped replicas; retrying on a later poll",
            gen, reason, cur,
        )
        return None

    def _stage_failed(self, gen: str) -> None:
        """Permanent (until the pointer moves): the candidate failed
        staging on a replica."""
        with self._mu:
            self._failed = gen
            self._stats["generations_failed_total"] += 1
            self._in_progress = False
            self._phase = "failed"
            cur = self.current
        logger.error(
            "rollout of %s ABORTED: staging failed; generation marked "
            "failed (not retried until the pointer moves); %s keeps "
            "serving", gen, cur,
        )
        return None

    # -- per-replica swap ----------------------------------------------

    def _post_replica(self, i: int, path: str, payload,
                      timeout: Optional[float] = None,
                      shadow: bool = False):
        """Direct POST to one replica (NOT through the balancer's
        rotation): the rollout/canary control channel."""
        with self.lb._mu:
            host, port = self.lb.replicas[i]
        body = (
            payload if isinstance(payload, (bytes, bytearray))
            else json.dumps(payload).encode()
        )
        headers = {"Content-Type": "application/json"}
        if shadow:
            # Tag control/scoring traffic so a replica's access view
            # (and the stub replicas in tests) can tell shadow traffic
            # from live traffic that must never reach a held canary.
            headers["X-Glint-Shadow"] = "1"
        conn = http.client.HTTPConnection(
            host, port,
            timeout=self.step_timeout if timeout is None else timeout,
        )
        try:
            conn.request("POST", path, body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            try:
                doc = json.loads(data.decode() or "null")
            except ValueError:
                doc = None
            return resp.status, doc
        finally:
            conn.close()

    def _replica_metrics(self, i: int) -> Tuple[Optional[str], int, bool]:
        """(generation, post_warmup_compiles, healthy) of one replica —
        scoped to THIS coordinator's model (the per-model snapshot has
        the same hot_swap/compiles shape as the top-level one)."""
        try:
            status, snap = self.lb._get_json(i, self._metrics_path)
            hstatus, _ = self.lb._get_json(i, self._healthz_path)
        except Exception:
            return None, -1, False
        if status != 200:
            return None, -1, False
        gen = (snap.get("hot_swap") or {}).get("generation")
        compiles = int((snap.get("compiles") or {}).get("post_warmup") or 0)
        return gen, compiles, hstatus == 200

    def _wait_replica_on(self, i: int, gen: str,
                         compiles_before: int) -> str:
        """Poll until the replica serves ``gen``, healthy, with NO
        post-warmup compiles added by the swap. Returns "ok" or a
        reason string."""
        deadline = time.monotonic() + self.step_timeout
        while time.monotonic() < deadline and not self._stop.is_set():
            rgen, compiles, healthy = self._replica_metrics(i)
            if rgen == gen and healthy:
                if compiles_before >= 0 and compiles > compiles_before:
                    return (
                        f"swap added {compiles - compiles_before} "
                        "post-warmup compiles"
                    )
                return "ok"
            time.sleep(0.1)
        return f"not healthy on {gen} within {self.step_timeout:.0f}s"

    def _swap_replica(self, i: int, gen: str, gen_dir: str,
                      hold: bool) -> str:
        """One rollout step: drain via breaker hold, reload, wait
        healthy + warm, readmit. Returns "ok", "stage_failed", or a
        transient reason. Single-replica fleets skip the hold — with
        no peer to absorb traffic, ejecting the only replica would
        drop availability to zero, and the reload stages off the
        request path anyway."""
        _, compiles_before, _ = self._replica_metrics(i)
        if hold:
            # Through the shared ledger: on a parked spare this stacks
            # a "rollout" hold on the autoscaler's (ref-counted), and
            # releasing below leaves the spare parked, not serving.
            self.holds.acquire("rollout", i)
            time.sleep(self.drain_seconds)  # in-flight requests drain
        try:
            try:
                status, resp = self._post_replica(
                    i, self._reload_path,
                    {"dir": gen_dir, "generation": gen},
                    shadow=True,
                )
            except Exception as e:
                return f"unreachable during reload: {e}"
            if status == 503:
                # Transient staging trouble (storage hiccup on an
                # existing dir, answered 503 by the replica): halt and
                # retry the rollout on a later poll — branding the
                # generation failed is for REJECTED staging only.
                return f"transient staging error: {resp}"
            if status != 200:
                logger.error(
                    "replica %d rejected %s: http %d %s",
                    i, gen, status, resp,
                )
                return "stage_failed"
            return self._wait_replica_on(i, gen, compiles_before)
        finally:
            if hold:
                self.holds.release("rollout", i)

    # -- shadow canary -------------------------------------------------

    def _score_probe(self, ci: int, probe: dict) -> Optional[float]:
        """One deterministic probe: POST the same body to the live
        fleet (the held canary is excluded from rotation by
        construction) and to the canary; score top-k agreement."""
        path = str(probe.get("path", "/synonyms"))
        body = json.dumps(probe.get("body", {})).encode()
        try:
            lstatus, lbody, _ = self.lb.forward("POST", path, body)
            cstatus, cdoc = self._post_replica(
                ci, path, body, timeout=30.0, shadow=True
            )
        except Exception:
            return None
        if lstatus in _SHED_STATUSES or cstatus in _SHED_STATUSES:
            # Backpressure is not a model answer: an overloaded-but-
            # healthy fleet must not hold back a good candidate.
            return None
        if lstatus != 200 and cstatus != 200:
            return None  # unscorable on both sides (e.g. shared OOV)
        if lstatus != 200 or cstatus != 200:
            return 0.0  # one-sided SEMANTIC failure is disagreement
        try:
            ldoc = json.loads(lbody)
        except ValueError:
            return None
        return _topk_overlap(ldoc, cdoc, self.canary.top_k)

    def _canary_phase(self, ci: int, gen: str, gen_dir: str) -> str:
        """Stage the candidate on ONE held replica, mirror a sampled
        slice of live traffic to it, score agreement, and decide.
        Returns "pass", "held_back", "stage_failed", or a transient
        reason. The held replica serves NO live traffic throughout —
        the candidate generation cannot reach a client until it
        passes."""
        lb = self.lb
        with self._mu:
            self._stats["canary"]["evaluations_total"] += 1
            self._phase = "canary"
        self.holds.acquire("rollout", ci)
        mirroring = False
        restored = True
        try:
            _, compiles_before, _ = self._replica_metrics(ci)
            time.sleep(self.drain_seconds)
            # From the moment the reload is POSTed the replica may
            # have adopted the candidate (the handler swaps before
            # answering): pessimistically un-restored until a path
            # below proves the live generation is back.
            restored = False
            try:
                status, resp = self._post_replica(
                    ci, self._reload_path,
                    {"dir": gen_dir, "generation": gen},
                    shadow=True,
                )
            except Exception as e:
                # The reload may have been APPLIED with the response
                # lost — restore before ever releasing the hold.
                restored = self._restore_canary(ci, gen)
                return f"canary unreachable during reload: {e}"
            if status == 503:
                # Transient staging trouble on the replica (storage
                # hiccup): the old tables stayed live — retry the
                # whole rollout on a later poll.
                restored = True
                return f"canary transient staging error: {resp}"
            if status != 200:
                logger.error(
                    "canary replica %d rejected %s: http %d %s",
                    ci, gen, status, resp,
                )
                restored = True  # staging rejected: old tables live
                return "stage_failed"
            warm = self._wait_replica_on(ci, gen, compiles_before)
            if warm != "ok":
                # The candidate IS live on the canary but never proved
                # healthy/warm: restore before releasing the hold.
                restored = self._restore_canary(ci, gen)
                return f"canary {warm}"
            scores: List[float] = []
            for probe in (self.canary.probes or []):
                s = self._score_probe(ci, probe)
                if s is not None:
                    scores.append(s)
            lb.start_mirror(
                self.canary.mirror_paths, self.canary.mirror_every
            )
            mirroring = True
            deadline = time.monotonic() + self.canary.mirror_seconds
            want = max(self.canary.min_scores, len(scores))
            while (len(scores) < want
                   and time.monotonic() < deadline
                   and not self._stop.is_set()):
                drained = lb.drain_mirror(16)
                if not drained:
                    time.sleep(0.05)
                    continue
                for path, body, lstatus, lbody in drained:
                    if lstatus != 200:
                        continue
                    try:
                        cstatus, cdoc = self._post_replica(
                            ci, urlparse(path).path, body,
                            timeout=30.0, shadow=True,
                        )
                        if cstatus in _SHED_STATUSES:
                            continue  # backpressure, not an answer
                        if cstatus != 200:
                            scores.append(0.0)
                            continue
                        s = _topk_overlap(
                            json.loads(lbody), cdoc, self.canary.top_k
                        )
                        if s is not None:
                            scores.append(s)
                    except Exception:
                        continue
            lb.stop_mirror()
            mirroring = False
            agreement = (
                sum(scores) / len(scores) if scores else None
            )
            ok = (
                agreement is None
                or agreement >= self.canary.agreement_gate
            )
            with self._mu:
                can = self._stats["canary"]
                can["last_agreement"] = (
                    round(agreement, 4) if agreement is not None else None
                )
                can["last_scored"] = len(scores)
                can["last_generation"] = gen
                can["last_verdict"] = "pass" if ok else "held_back"
            if agreement is None:
                logger.warning(
                    "canary for %s collected no scoreable responses "
                    "(no live traffic, no probes) — passing vacuously",
                    gen,
                )
            if ok:
                logger.info(
                    "canary PASSED for %s: agreement %.3f >= %.3f "
                    "over %d responses",
                    gen, agreement if agreement is not None else 1.0,
                    self.canary.agreement_gate, len(scores),
                )
                restored = True  # it now serves the PROMOTED generation
                return "pass"
            # Hold-back: restore the canary to the live generation so
            # the candidate never serves a client, then count it.
            restored = self._restore_canary(ci, gen)
            return "held_back"
        finally:
            if mirroring:
                lb.stop_mirror()
            if restored:
                self.holds.release("rollout", ci)
            # NOT restored: the canary still holds the regressed
            # candidate — the "rollout" hold stays in the ledger (no
            # live traffic, and the autoscaler can never count it as
            # spare capacity) for the operator; the README runbook
            # documents recovery.

    def _restore_canary(self, ci: int, candidate: str) -> bool:
        """Reload the canary back to the live generation after a
        hold-back. Retried a few times; on total failure the replica
        is left HELD (serving nothing) rather than ever exposing the
        regressed candidate to clients."""
        with self._mu:
            prev_gen, prev_dir = self.current, self.current_dir
        if prev_dir is None:
            logger.error(
                "no previous generation dir to restore canary from "
                "(booted outside the publish protocol?) — replica "
                "stays held",
            )
            return False
        for _ in range(3):
            try:
                status, _ = self._post_replica(
                    ci, self._reload_path,
                    {"dir": prev_dir, "generation": prev_gen},
                    shadow=True,
                )
                if status == 200 and self._wait_replica_on(
                        ci, prev_gen, -1) == "ok":
                    logger.info(
                        "canary restored to %s after holding back %s",
                        prev_gen, candidate,
                    )
                    return True
            except Exception:
                pass
            time.sleep(0.5)
        logger.error(
            "canary restore to %s FAILED after holding back %s — "
            "replica left held out of rotation", prev_gen, candidate,
        )
        return False

    # -- observability -------------------------------------------------

    def stats(self) -> dict:
        with self._mu:
            out = {
                k: v for k, v in self._stats.items() if k != "canary"
            }
            out["canary"] = dict(self._stats["canary"])
            out["model"] = self.model_id
            out["in_progress"] = self._in_progress
            out["phase"] = self._phase
            out["generation"] = self.current
            out["failed_generation"] = self._failed
            out["held_back_generation"] = self._held_back
            return out

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        suffix = f"-{self.model_id}" if self.model_id else ""
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"glint-fleet-rollout{suffix}",
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_seconds):
            self.poll_once()

    def stop(self) -> None:
        self._stop.set()


# ----------------------------------------------------------------------
# Multi-process data plane (ISSUE 19): shard subprocesses + facade
# ----------------------------------------------------------------------


def run_balancer_shard(config_path: str) -> int:
    """Entry point of one ``fleet-shard`` subprocess: a full
    :class:`LoadBalancer` data plane (own thread pool, per-thread
    keep-alive replica connections, breakers + prober, EventRecorder
    sink) accepting from the SHARED fleet port, plus a private control
    listener the supervisor drives. Exits when stopped over the
    control channel or when the parent dies (orphan watchdog)."""
    from glint_word2vec_tpu.utils import atomic_write_json

    with open(config_path) as f:
        cfg = json.load(f)
    if cfg.get("trace_log"):
        obs_events.set_recorder(obs_events.EventRecorder(
            jsonl_path=cfg["trace_log"],
        ))
    replicas = cfg["replicas"]
    qos_cfg = cfg.get("qos")
    lb = LoadBalancer(
        [f"http://{r['host']}:{r['port']}" for r in replicas],
        host=cfg.get("host", "127.0.0.1"),
        port=int(cfg.get("port", 0)),
        reuse_port=bool(cfg.get("reuse_port")),
        listen_fd=cfg.get("listen_fd"),
        control=True,
        shard_id=int(cfg.get("shard", 1)),
        proxy_control=(
            tuple(cfg["parent_control"])
            if cfg.get("parent_control") else None
        ),
        qos=QosConfig(**qos_cfg) if qos_cfg else None,
        proxy_timeout=float(cfg.get("proxy_timeout", 60.0)),
        scrape_timeout=float(cfg.get("scrape_timeout", 2.0)),
        breaker_failures=int(cfg.get("breaker_failures", 3)),
        breaker_successes=int(cfg.get("breaker_successes", 2)),
        breaker_open_seconds=float(cfg.get("breaker_open_seconds", 2.0)),
        probe_interval=float(cfg.get("probe_interval", 0.5)),
        probe_timeout=float(cfg.get("probe_timeout", 2.0)),
    )
    for i, r in enumerate(replicas):
        if r.get("generation") is not None:
            lb.set_replica_address(
                i, r["host"], int(r["port"]),
                generation=r["generation"],
            )
        if r.get("held"):
            lb.breakers[i].hold()
        if r.get("restarting"):
            lb.set_restarting(i, True)
            lb.breakers[i].force_open()
    atomic_write_json(cfg["port_file"], {
        "shard": lb.shard_id,
        "pid": os.getpid(),
        "host": lb.host,
        "port": lb.port,
        "control_host": lb.control_addr[0],
        "control_port": lb.control_addr[1],
    })
    lb.start_background()
    lb.start_prober()
    ppid = os.getppid()
    try:
        while not lb.stopped():
            if os.getppid() != ppid:
                # Parent supervisor died without tearing us down: a
                # balancer shard must NEVER outlive its fleet.
                logger.error(
                    "fleet shard %d: parent died — exiting",
                    lb.shard_id,
                )
                break
            time.sleep(0.25)
    except KeyboardInterrupt:
        pass
    finally:
        lb.stop()
    return 0


class _ShardHandle:
    """The supervisor's view of one shard subprocess: its process and
    its private control address."""

    def __init__(self, shard_id: int, proc, host: str, port: int,
                 timeout: float = 5.0):
        self.shard_id = shard_id
        self.proc = proc
        self.host, self.port = host, int(port)
        self.timeout = float(timeout)

    def _request(self, method: str, path: str, payload=None):
        conn = http.client.HTTPConnection(
            self.host, self.port, timeout=self.timeout
        )
        try:
            body = (
                json.dumps(payload).encode()
                if payload is not None else None
            )
            conn.request(
                method, path, body=body,
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, json.loads(data.decode() or "null")
        finally:
            conn.close()

    def control(self, op: dict) -> bool:
        try:
            status, _ = self._request("POST", "/_shard/control", op)
            return status == 200
        except Exception as e:
            logger.warning(
                "fleet shard %d control op %s failed: %s",
                self.shard_id, op.get("op"), e,
            )
            return False

    def snapshot(self) -> dict:
        try:
            status, doc = self._request("GET", "/_shard/snapshot")
            if status == 200 and isinstance(doc, dict):
                return doc
            return {
                "shard": self.shard_id, "up": False,
                "error": f"http {status}",
            }
        except Exception as e:
            return {
                "shard": self.shard_id, "up": False, "error": str(e),
            }

    def request_stop(self) -> bool:
        try:
            status, _ = self._request("POST", "/_shard/stop", {})
            return status == 200
        except Exception:
            return False


class BalancerShardManager:
    """Launches and owns the extra balancer shard subprocesses of a
    multi-process data plane (``--balancer-procs N`` = the supervisor
    shard + N-1 of these). Each shard shares the fleet's listen port —
    SO_REUSEPORT when the platform has it, otherwise the parent-bound
    listener inherited by fd — and runs its own breakers/prober/
    thread pool; this manager is purely control plane: config
    handoff, mirror-op broadcast, snapshot scrape, teardown."""

    def __init__(self, lb: LoadBalancer, count: int, *,
                 replica_specs: List[dict],
                 qos: Optional[dict] = None,
                 trace_dir: Optional[str] = None,
                 log_dir: Optional[str] = None,
                 start_timeout: float = 60.0,
                 kill_grace_seconds: float = 5.0):
        self.lb = lb
        self.count = max(0, int(count))
        self.replica_specs = list(replica_specs)
        self.qos = qos
        self.trace_dir = trace_dir
        self.log_dir = log_dir
        self.start_timeout = float(start_timeout)
        self.kill_grace_seconds = float(kill_grace_seconds)
        self.handles: List[_ShardHandle] = []
        self._procs: List = []
        self._logs: List = []
        self._tmp: Optional[str] = None

    def start(self) -> None:
        import tempfile

        if self.count <= 0:
            return
        self._tmp = tempfile.mkdtemp(prefix="glint_fleet_shards_")
        parent_control = self.lb.control_addr
        if parent_control is None:
            raise RuntimeError(
                "shard fan-out needs the parent balancer built with "
                "control=True"
            )
        pass_fds = ()
        listen_fd = None
        if not self.lb._reuse_port:
            # Fallback shared listener: children adopt the parent's
            # bound socket by fd (one shared accept queue).
            listen_fd = self.lb._listener.fileno()
            pass_fds = (listen_fd,)
        launched = []
        for k in range(self.count):
            shard_id = k + 1
            port_file = os.path.join(
                self._tmp, f"shard-{shard_id}.port"
            )
            cfg = {
                "shard": shard_id,
                "host": self.lb.host,
                "port": self.lb.port,
                "reuse_port": self.lb._reuse_port,
                "listen_fd": listen_fd,
                "port_file": port_file,
                "parent_control": list(parent_control),
                "replicas": self.replica_specs,
                "qos": self.qos,
                "proxy_timeout": self.lb.proxy_timeout,
                "scrape_timeout": self.lb.scrape_timeout,
                "probe_interval": self.lb.probe_interval,
                "probe_timeout": self.lb.probe_timeout,
                "breaker_failures": self.lb.breakers[0].fail_threshold,
                "breaker_successes":
                    self.lb.breakers[0].success_threshold,
                "breaker_open_seconds": self.lb.breakers[0].open_seconds,
                "trace_log": (
                    os.path.join(
                        self.trace_dir,
                        f"balancer-shard-{shard_id}.jsonl",
                    )
                    if self.trace_dir else None
                ),
            }
            cfg_path = os.path.join(
                self._tmp, f"shard-{shard_id}.json"
            )
            # graftlint: ignore[atomic-persist] one-shot handoff file read once by the child
            with open(cfg_path, "w") as f:
                json.dump(cfg, f)
            log = None
            if self.log_dir:
                os.makedirs(self.log_dir, exist_ok=True)
                # graftlint: ignore[atomic-persist] append-mode process log, not an artifact
                log = open(
                    os.path.join(
                        self.log_dir, f"balancer-shard-{shard_id}.log"
                    ),
                    "ab",
                )
                self._logs.append(log)
            proc = subprocess.Popen(
                [
                    sys.executable, "-m", "glint_word2vec_tpu.cli",
                    "fleet-shard", "--config", cfg_path,
                ],
                pass_fds=pass_fds,
                stdout=log, stderr=log and subprocess.STDOUT,
                start_new_session=True,
            )
            self._procs.append(proc)
            launched.append((shard_id, proc, port_file))
        deadline = time.monotonic() + self.start_timeout
        for shard_id, proc, port_file in launched:
            info = None
            while time.monotonic() < deadline:
                try:
                    with open(port_file) as f:
                        info = json.load(f)
                    break
                except (OSError, ValueError):
                    if proc.poll() is not None:
                        self.stop_all()
                        raise RuntimeError(
                            f"balancer shard {shard_id} exited "
                            f"rc={proc.returncode} before binding"
                        )
                    time.sleep(0.05)
            if info is None:
                self.stop_all()
                raise TimeoutError(
                    f"balancer shard {shard_id} not ready in "
                    f"{self.start_timeout:.0f}s"
                )
            self.handles.append(_ShardHandle(
                shard_id, proc,
                info["control_host"], info["control_port"],
            ))
        logger.info(
            "fleet data plane: %d shard subprocess(es) sharing "
            "%s:%d (%s)", self.count, self.lb.host, self.lb.port,
            "SO_REUSEPORT" if self.lb._reuse_port
            else "inherited listener fd",
        )

    def broadcast(self, op: dict) -> None:
        for h in self.handles:
            h.control(op)

    def snapshots(self) -> List[dict]:
        return [h.snapshot() for h in self.handles]

    def stop_all(self) -> None:
        """Fan-out teardown: ask every shard to stop over its control
        channel, then escalate to terminate/kill — ``serve-fleet``
        never leaves an orphan balancer process."""
        for h in self.handles:
            h.request_stop()
        deadline = time.monotonic() + self.kill_grace_seconds
        for proc in self._procs:
            while proc.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if proc.poll() is None:
                terminate_process(
                    proc, grace_seconds=self.kill_grace_seconds
                )
        for f in self._logs:
            try:
                f.close()
            except OSError:
                pass
        self._logs = []
        if self._tmp:
            import shutil

            shutil.rmtree(self._tmp, ignore_errors=True)
            self._tmp = None


class _FleetDataPlane:
    """The supervisor's single write path to EVERY balancer shard: an
    op is applied to the in-process balancer first (cheap, lock-free
    hot path) and mirrored to each shard subprocess over its control
    channel. Repeated per-sweep assertions (``down``/``fail`` are
    re-asserted every 0.25s pass) are deduplicated so steady state
    costs zero control-channel traffic — each shard's own prober
    keeps its breakers honest between transitions."""

    def __init__(self, lb: LoadBalancer,
                 shards: Optional[BalancerShardManager] = None):
        self.lb = lb
        self.shards = shards
        self._sent_state: Dict[int, str] = {}

    def _bcast(self, op: dict) -> None:
        if self.shards is not None:
            self.shards.broadcast(op)

    def adopt(self, i: int, host: str, port: int,
              generation: Optional[str]) -> None:
        self.lb.set_replica_address(i, host, port, generation=generation)
        self.lb.set_restarting(i, False)
        self.lb.breakers[i].clear_holds()
        self.lb.breakers[i].trial()
        self._sent_state[i] = "up"
        self._bcast({
            "op": "set_address", "i": i, "host": host, "port": port,
            "generation": generation,
        })
        self._bcast({"op": "set_restarting", "i": i, "flag": False})
        self._bcast({"op": "clear_holds", "i": i})
        self._bcast({"op": "trial", "i": i})

    def down(self, i: int) -> None:
        """Replica inside a restart window: retry-on-refused + firmly
        open everywhere."""
        self.lb.set_restarting(i, True)
        self.lb.breakers[i].force_open()
        if self._sent_state.get(i) != "down":
            self._sent_state[i] = "down"
            self._bcast({"op": "set_restarting", "i": i, "flag": True})
            self._bcast({"op": "force_open", "i": i})

    def fail(self, i: int) -> None:
        """Replica written off (restart budget exhausted): no restart
        window, breaker firmly open everywhere."""
        self.lb.set_restarting(i, False)
        self.lb.breakers[i].force_open()
        if self._sent_state.get(i) != "failed":
            self._sent_state[i] = "failed"
            self._bcast({"op": "set_restarting", "i": i, "flag": False})
            self._bcast({"op": "force_open", "i": i})

    def hold(self, i: int) -> None:
        self.lb.breakers[i].hold()
        self._bcast({"op": "hold", "i": i})

    def release(self, i: int) -> None:
        self.lb.breakers[i].release()
        self._bcast({"op": "release", "i": i})

    def clear_holds(self, i: int) -> None:
        self.lb.breakers[i].clear_holds()
        self._bcast({"op": "clear_holds", "i": i})


# ----------------------------------------------------------------------
# Warm-spare autoscaler (ISSUE 19)
# ----------------------------------------------------------------------


@dataclass
class AutoscaleConfig:
    """Demand-driven capacity policy. Scale-up = RELEASE a warm
    spare's park hold (the replica is already launched and warmed —
    readmit, never a cold boot); scale-down = park the highest-index
    live replica back to spare. Hysteresis windows + cooldown keep the
    loop from flapping; ``min_live``/``max_live`` bound it."""

    min_live: int
    max_live: int
    #: Policy evaluation period (seconds).
    interval: float = 0.5
    #: Scale-up pressure: fleet shed rate (sheds/sec across shards,
    #: QoS sheds included) at or above this...
    up_shed_per_sec: float = 1.0
    #: ...or forward-path p95 (ms, max across shards) at or above
    #: this. None = resolve to the SLO latency threshold
    #: (GLINT_SLO_LATENCY_MS, 250ms default).
    up_p95_ms: Optional[float] = None
    #: Pressure must be SUSTAINED this long before a scale-up...
    up_window_seconds: float = 1.0
    #: ...and idle this long before a scale-down (asymmetric on
    #: purpose: readmitting is cheap and urgent, parking is neither).
    down_window_seconds: float = 10.0
    #: Minimum seconds between ANY two transitions.
    cooldown_seconds: float = 5.0


class Autoscaler:
    """The FleetSupervisor's demand policy loop: reads the signals the
    fleet already emits (shed rate, forward-path p95 vs the SLO
    latency target, breaker-open count, fast-burn transitions) and
    moves replicas between live and parked through the shared
    :class:`ReplicaHoldLedger` — the same protocol the rollout
    coordinator holds through, so the two can never fight over a
    replica. A rollout in progress PINS the replica set (steps are
    counted, not applied); a replica held by any owner besides the
    autoscaler — a held canary above all — is never spare capacity.

    Dependency-injected callables keep it unit-testable without a
    fleet: ``signals()`` returns the current signal doc, ``parked()``
    the readmittable spares, ``live()`` the parkable live replicas,
    ``pinned()`` the rollout-pinning flag."""

    def __init__(self, *, holds: ReplicaHoldLedger,
                 config: AutoscaleConfig,
                 signals: Callable[[], dict],
                 parked: Callable[[], List[int]],
                 live: Callable[[], List[int]],
                 pinned: Optional[Callable[[], bool]] = None,
                 now_fn: Callable[[], float] = time.monotonic):
        cfg = config
        if cfg.up_p95_ms is None:
            cfg.up_p95_ms = float(
                os.environ.get("GLINT_SLO_LATENCY_MS") or 250.0
            )
        self.config = cfg
        self.holds = holds
        self._signals = signals
        self._parked = parked
        self._live = live
        self._pinned = pinned or (lambda: False)
        self._now = now_fn
        self._mu = threading.Lock()
        self._last_shed_total: Optional[float] = None
        self._last_step_t: Optional[float] = None
        self._pressure_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._last_transition_t: Optional[float] = None
        self._steps = 0
        self._step_faults = 0
        self._scale_ups = 0
        self._scale_downs = 0
        self._pinned_skips = 0
        self._last_shed_rate = 0.0
        self._last_p95_ms: Optional[float] = None
        self._transitions: deque = deque(maxlen=16)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def step(self) -> Optional[str]:
        """One policy evaluation; returns "up"/"down" when a
        transition happened, else None."""
        with self._mu:
            self._steps += 1
        try:
            faults.fire("fleet.autoscale_step")
        except Exception:
            with self._mu:
                self._step_faults += 1
            return None
        now = self._now()
        sig = self._signals() or {}
        shed_total = float(sig.get("shed_total") or 0.0)
        p95 = sig.get("p95_ms")
        with self._mu:
            if self._last_shed_total is None or self._last_step_t is None:
                rate = 0.0
            else:
                dt = max(now - self._last_step_t, 1e-6)
                rate = max(0.0, shed_total - self._last_shed_total) / dt
            self._last_shed_total = shed_total
            self._last_step_t = now
            self._last_shed_rate = rate
            self._last_p95_ms = p95
        cfg = self.config
        pressure = (
            rate >= cfg.up_shed_per_sec
            or (p95 is not None and p95 >= cfg.up_p95_ms)
            or int(sig.get("breakers_open") or 0) > 0
            or bool(sig.get("fast_burn"))
        )
        with self._mu:
            if pressure:
                self._idle_since = None
                if self._pressure_since is None:
                    self._pressure_since = now
                pressure_for = now - self._pressure_since
                idle_for = 0.0
            else:
                self._pressure_since = None
                if self._idle_since is None:
                    self._idle_since = now
                idle_for = now - self._idle_since
                pressure_for = 0.0
            last_t = self._last_transition_t
        if self._pinned():
            # Rollout/canary in flight: the replica set is pinned.
            # Hysteresis clocks keep running — a surge during a
            # rollout scales up the moment the swap completes.
            with self._mu:
                self._pinned_skips += 1
            return None
        if last_t is not None and now - last_t < cfg.cooldown_seconds:
            return None
        if pressure and pressure_for >= cfg.up_window_seconds:
            live = self._live()
            if len(live) >= cfg.max_live:
                return None
            spares = [
                i for i in self.holds.parked("autoscale")
                if i in set(self._parked())
            ]
            if not spares:
                return None
            i = spares[0]
            self.holds.release("autoscale", i)
            with self._mu:
                self._scale_ups += 1
                self._last_transition_t = now
                self._transitions.append({
                    "dir": "up", "replica": i,
                    "shed_rate": round(rate, 3),
                    "p95_ms": p95,
                    "t": round(now, 3),
                })
            logger.info(
                "autoscale UP: readmitted warm spare %d "
                "(shed %.2f/s, p95 %s ms)", i, rate, p95,
            )
            return "up"
        if not pressure and idle_for >= cfg.down_window_seconds:
            live = self._live()
            if len(live) <= cfg.min_live:
                return None
            candidates = [
                i for i in live if not self.holds.owners(i)
            ]
            if not candidates:
                return None
            i = max(candidates)
            self.holds.acquire("autoscale", i)
            with self._mu:
                self._scale_downs += 1
                self._last_transition_t = now
                self._idle_since = now
                self._transitions.append({
                    "dir": "down", "replica": i,
                    "shed_rate": round(rate, 3),
                    "p95_ms": p95,
                    "t": round(now, 3),
                })
            logger.info(
                "autoscale DOWN: parked replica %d as warm spare "
                "(idle %.1fs)", i, idle_for,
            )
            return "down"
        return None

    def stats(self) -> dict:
        cfg = self.config
        with self._mu:
            return {
                "enabled": True,
                "live": len(self._live()),
                "spares": len(self.holds.parked("autoscale")),
                "min_live": cfg.min_live,
                "max_live": cfg.max_live,
                "scale_ups_total": self._scale_ups,
                "scale_downs_total": self._scale_downs,
                "pinned_skips_total": self._pinned_skips,
                "steps_total": self._steps,
                "step_faults_total": self._step_faults,
                "last_shed_rate": round(self._last_shed_rate, 3),
                "last_p95_ms": self._last_p95_ms,
                "transitions": list(self._transitions),
            }

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="glint-fleet-autoscale",
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.config.interval):
            try:
                self.step()
            except Exception:  # pragma: no cover - defensive
                logger.exception("autoscale step failed")

    def stop(self) -> None:
        self._stop.set()


def _hist_window_delta(prev: Optional[dict],
                       cur: dict) -> Optional[LatencyHistogram]:
    """The traffic between two cumulative :class:`LatencyHistogram`
    states, as a histogram. ``prev`` None (first observation) returns
    the whole cumulative state; a bucket that went BACKWARDS means the
    producer restarted and reset, so the current state IS the window.
    The window's true max is unknowable from cumulative states — the
    cumulative max only widens the quantile interpolation clamp."""
    cur_h = LatencyHistogram.from_state(cur)
    if prev is None:
        return cur_h
    prev_h = LatencyHistogram.from_state(prev)
    out = LatencyHistogram()
    for i, c in enumerate(cur_h.counts):
        d = c - prev_h.counts[i]
        if d < 0:
            return cur_h
        out.counts[i] = d
    out.n = max(0, cur_h.n - prev_h.n)
    out.total = max(0.0, cur_h.total - prev_h.total)
    out.max = cur_h.max
    return out


def _sum_balancer_stats(blocks: List[dict]) -> dict:
    """Fold per-shard ``balancer_stats`` blocks into fleet totals
    (counters sum; QoS inflight gauges sum, peaks max, per-tenant
    maps merge key-wise)."""
    out = {
        "shed_retries_total": 0,
        "exhausted_total": 0,
        "proxied_total": 0,
        "proxy_errors_total": 0,
        "breaker_skips_total": 0,
        "restart_retries_total": 0,
        "retry_after_honored_total": 0,
    }
    qos_out = None
    for b in blocks:
        if not b:
            continue
        for k in out:
            out[k] += int(b.get(k) or 0)
        q = b.get("qos")
        if q:
            if qos_out is None:
                qos_out = {
                    "admitted_total": {},
                    "shed_total": {},
                    "per_tenant_shed_total": {},
                    "bulk_inflight": 0,
                    "bulk_inflight_peak": 0,
                }
            for key in ("admitted_total", "shed_total",
                        "per_tenant_shed_total"):
                for name, n in (q.get(key) or {}).items():
                    qos_out[key][name] = (
                        qos_out[key].get(name, 0) + int(n)
                    )
            qos_out["bulk_inflight"] += int(q.get("bulk_inflight") or 0)
            qos_out["bulk_inflight_peak"] = max(
                qos_out["bulk_inflight_peak"],
                int(q.get("bulk_inflight_peak") or 0),
            )
    if qos_out is not None:
        out["qos"] = qos_out
    return out


# ----------------------------------------------------------------------
# Fleet supervisor + launcher
# ----------------------------------------------------------------------


@dataclass
class _ReplicaSlot:
    """One supervised replica slot: the live process, its launch
    generation (the /healthz handshake value), and restart pacing."""

    index: int
    state: str = "starting"   # starting | up | backoff | failed | stopped
    proc: Optional[subprocess.Popen] = None
    launch_generation: int = -1
    port_file: str = ""
    host: Optional[str] = None
    port: Optional[int] = None
    restarts: int = 0
    relaunch_at: float = 0.0
    started_at: float = 0.0
    detect_t: Optional[float] = None
    last_reason: Optional[str] = None
    restart_records: List[dict] = field(default_factory=list)

    def gen_tag(self) -> str:
        return f"{self.index}.{self.launch_generation}"


class FleetSupervisor:
    """Self-healing serving fleet: supervised replicas behind a
    breaker-aware balancer, with coordinated rolling rollout.

    The PR 7 supervisor pattern on the serving tier: replica liveness
    is watched via ``waitpid`` (crash) AND the balancer's active
    prober (hang — a replica whose probes fail continuously for
    ``hang_kill_seconds`` while its process still runs is killed and
    treated as crashed). Dead replicas relaunch from the fleet's
    CURRENT model directory under capped exponential backoff and a
    per-replica ``max_restarts`` budget; a replica out of budget is
    left down (the balancer serves from the survivors) and counted on
    ``/metrics``. Every launch exports ``GLINT_FLEET_GEN``; the
    replica echoes it on ``/healthz`` and in its port file, so a stale
    process or port file can never be adopted as the new incarnation.

    With ``watch_dir`` (coordinated mode, the default), replicas do
    NOT watch the publish dir themselves — the
    :class:`RolloutCoordinator` orders every swap one replica at a
    time, gated by the shadow canary when configured. A relaunched
    replica boots from the fleet's current (promoted) generation, so
    a restart mid-rollout converges with the coordinator instead of
    racing it.
    """

    #: ``lb``/``coordinator``/``dp``/``holds``/``autoscaler``/``shards``
    #: are written exactly once (in run(), before the supervision loop
    #: and any metrics request can touch them) and read-only
    #: afterwards; lock-free reads see either None (ignored) or the
    #: final object.
    _ATOMIC_ATTRS = frozenset({
        "lb", "coordinator", "model_coordinators", "dp", "holds",
        "autoscaler", "shards",
    })

    def __init__(
        self,
        model_dir: Optional[str],
        *,
        replicas: int = 2,
        host: str = "127.0.0.1",
        port: int = 8800,
        watch_dir: Optional[str] = None,
        watch_poll: float = 1.0,
        replica_flags: Optional[List[str]] = None,
        log_dir: Optional[str] = None,
        trace_dir: Optional[str] = None,
        ready_timeout: float = 900.0,
        port_file: Optional[str] = None,
        max_restarts: int = 3,
        backoff_base_seconds: float = 1.0,
        backoff_cap_seconds: float = 30.0,
        hang_kill_seconds: float = 10.0,
        poll_interval: float = 0.25,
        kill_grace_seconds: float = 5.0,
        probe_interval: float = 0.5,
        probe_timeout: float = 2.0,
        breaker_failures: int = 3,
        breaker_successes: int = 2,
        breaker_open_seconds: float = 2.0,
        canary: Optional[CanaryConfig] = None,
        rollout_step_timeout: float = 600.0,
        coordinated: bool = True,
        build_replica_argv: Optional[Callable[[int, str], List[str]]] = None,
        replica_env_first_launch: Optional[Dict[int, Dict[str, str]]] = None,
        warm_spares: int = 0,
        autoscale: Optional[AutoscaleConfig] = None,
        balancer_procs: int = 1,
        qos: Optional[QosConfig] = None,
        models: Optional[Dict[str, str]] = None,
        model_watch_dirs: Optional[Dict[str, str]] = None,
        model_memory_budget=None,
    ):
        if model_dir is None and watch_dir is None \
                and build_replica_argv is None:
            raise ValueError("model_dir or watch_dir required")
        self.model_dir = model_dir
        #: ``replicas`` live + ``warm_spares`` launched-and-parked: a
        #: spare boots, warms, and then sits held out of rotation until
        #: the autoscaler readmits it (scale-up is never a cold boot).
        self.base_replicas = max(1, int(replicas))
        self.warm_spares = max(0, int(warm_spares))
        self.num_replicas = self.base_replicas + self.warm_spares
        self.host, self.port = host, int(port)
        self.watch_dir = watch_dir
        self.watch_poll = float(watch_poll)
        self.replica_flags = list(replica_flags or [])
        self.log_dir = log_dir
        #: Distributed-tracing root (ISSUE 18): when set, the balancer
        #: records its spans to ``<trace_dir>/balancer.jsonl``, every
        #: replica gets ``--trace-log``/``--flight-dir`` flags pointing
        #: into it, and the balancer's fleet-wide flight recorder
        #: bundles into ``<trace_dir>/flight``. ``cli trace-merge``
        #: stitches the per-process JSONLs into one Perfetto timeline.
        self.trace_dir = trace_dir
        self.ready_timeout = float(ready_timeout)
        self.port_file = port_file
        self.max_restarts = int(max_restarts)
        self.backoff_base_seconds = float(backoff_base_seconds)
        self.backoff_cap_seconds = float(backoff_cap_seconds)
        self.hang_kill_seconds = float(hang_kill_seconds)
        self.poll_interval = float(poll_interval)
        self.kill_grace_seconds = float(kill_grace_seconds)
        self.probe_interval = float(probe_interval)
        self.probe_timeout = float(probe_timeout)
        self.breaker_failures = int(breaker_failures)
        self.breaker_successes = int(breaker_successes)
        self.breaker_open_seconds = float(breaker_open_seconds)
        self.canary = canary
        self.rollout_step_timeout = float(rollout_step_timeout)
        self.coordinated = bool(coordinated)
        self._build_replica_argv = build_replica_argv
        self.replica_env_first_launch = dict(replica_env_first_launch or {})
        self.balancer_procs = max(1, int(balancer_procs))
        self.qos = qos
        self.autoscale_config = autoscale
        # -- multi-model catalog (ISSUE 20) ----------------------------
        #: Extra model id -> model dir every replica serves besides the
        #: default (carried to replicas as --add-model flags).
        self.models: Dict[str, str] = dict(models or {})
        #: model id -> publish dir: each gets its OWN rollout
        #: coordinator, so one model's LATEST.json move rolls only
        #: that model across the fleet.
        self.model_watch_dirs: Dict[str, str] = dict(
            model_watch_dirs or {}
        )
        self.model_memory_budget = model_memory_budget
        self._mu = threading.Lock()
        self._slots = [
            _ReplicaSlot(index=i) for i in range(self.num_replicas)
        ]
        self._restarts_total = 0
        #: Model directory replicas (re)launch from; the rollout
        #: coordinator advances it on every promoted generation.
        self._current_model_dir = model_dir
        self._logs: List = []
        self._tmp: Optional[str] = None
        self._stop = threading.Event()
        #: Set once the balancer + prober (+ coordinator) are live —
        #: the test/readiness barrier.
        self.ready = threading.Event()
        self.lb: Optional[LoadBalancer] = None
        self.coordinator: Optional[RolloutCoordinator] = None
        #: Per-model rollout coordinators (one per model_watch_dirs
        #: entry), sharing the balancer + hold ledger with the default
        #: coordinator. Written once in run().
        self.model_coordinators: List[RolloutCoordinator] = []
        self.dp: Optional[_FleetDataPlane] = None
        self.holds: Optional[ReplicaHoldLedger] = None
        self.autoscaler: Optional[Autoscaler] = None
        self.shards: Optional[BalancerShardManager] = None
        #: Previous per-(shard, endpoint) forward-path histogram
        #: states, diffed by ``_autoscale_signals`` into a windowed
        #: p95. Touched only by the autoscaler's policy thread.
        self._autoscale_prev_hists: Dict[Tuple, dict] = {}

    # -- replica launch ------------------------------------------------

    def _default_replica_argv(self, index: int,
                              port_file: str) -> List[str]:
        argv = [
            sys.executable, "-m", "glint_word2vec_tpu.cli", "serve",
            "--host", "127.0.0.1", "--port", "0",
            "--port-file", port_file,
        ]
        with self._mu:
            model = self._current_model_dir
        if self.coordinated or self.watch_dir is None:
            # Coordinated mode: the replica serves ONE generation and
            # swaps only when the rollout coordinator orders it.
            argv += ["--model", model]
        else:
            # Legacy uncoordinated mode: every replica follows the
            # publish dir itself (simultaneous fleet-wide swaps).
            if model:
                argv += ["--model", model]
            argv += [
                "--watch-checkpoint", self.watch_dir,
                "--watch-poll", str(self.watch_poll),
            ]
        if self.trace_dir:
            argv += [
                "--trace-log",
                os.path.join(self.trace_dir, f"replica-{index}.jsonl"),
                "--flight-dir",
                os.path.join(self.trace_dir, "flight"),
            ]
        # Multi-model catalog (ISSUE 20): every replica hosts the same
        # model set. Watched models launch from their CURRENT promoted
        # generation (the per-model coordinator advances it), so a
        # relaunched replica converges with the per-model rollouts
        # instead of racing them.
        with self._mu:
            catalog = dict(self.models)
        for mid in sorted(catalog):
            argv += ["--add-model", f"{mid}={catalog[mid]}"]
        if self.model_memory_budget is not None:
            argv += [
                "--model-memory-budget", str(self.model_memory_budget)
            ]
        return argv + list(self.replica_flags)

    def _argv(self, index: int, port_file: str) -> List[str]:
        if self._build_replica_argv is not None:
            return self._build_replica_argv(index, port_file)
        return self._default_replica_argv(index, port_file)

    def _open_log(self, index: int):
        if not self.log_dir:
            return None
        os.makedirs(self.log_dir, exist_ok=True)
        # graftlint: ignore[atomic-persist] append-mode process log, not an artifact
        f = open(
            os.path.join(self.log_dir, f"replica-{index}.log"), "ab"
        )
        self._logs.append(f)
        return f

    def _launch(self, slot: _ReplicaSlot) -> None:
        slot.launch_generation += 1
        slot.port_file = os.path.join(
            self._tmp,
            f"replica-{slot.index}.{slot.launch_generation}.port",
        )
        try:
            os.remove(slot.port_file)
        except OSError:
            pass
        env = dict(os.environ)
        env["GLINT_FLEET_GEN"] = slot.gen_tag()
        if slot.launch_generation == 0:
            # The chaos seam (PR 7's rank_env_first_launch pattern): a
            # GLINT_FAULTS schedule armed here fires once and is NOT
            # re-armed on the relaunch.
            env.update(self.replica_env_first_launch.get(slot.index, {}))
        log = self._open_log(slot.index)
        if log is not None:
            log.write(
                f"\n===== launch generation {slot.launch_generation} "
                f"replica {slot.index} =====\n".encode()
            )
            log.flush()
        slot.proc = subprocess.Popen(
            self._argv(slot.index, slot.port_file),
            env=env, stdout=log, stderr=log and subprocess.STDOUT,
            start_new_session=True,
        )
        slot.state = "starting"
        slot.started_at = time.monotonic()
        logger.info(
            "fleet: replica %d launched (generation %s, pid %d)",
            slot.index, slot.gen_tag(), slot.proc.pid,
        )

    def _read_port_file(self, slot: _ReplicaSlot) -> Optional[dict]:
        """The replica's readiness file, generation-verified: a stale
        file from a previous incarnation is never adopted."""
        try:
            with open(slot.port_file) as f:
                info = json.load(f)
        except (OSError, ValueError):
            return None
        gen = info.get("fleet_generation")
        if gen is not None and str(gen) != slot.gen_tag():
            return None
        return info

    # -- supervision ---------------------------------------------------

    def _schedule_restart(self, slot: _ReplicaSlot, reason: str) -> None:
        now = time.monotonic()
        with self._mu:
            if slot.restarts >= self.max_restarts:
                slot.state = "failed"
                slot.last_reason = reason
                logger.error(
                    "fleet: replica %d FAILED (%s) with restart budget "
                    "%d exhausted — left down, fleet serves from the "
                    "survivors", slot.index, reason, self.max_restarts,
                )
                if self.dp is not None:
                    self.dp.fail(slot.index)
                return
            backoff = capped_backoff(
                slot.restarts, self.backoff_base_seconds,
                self.backoff_cap_seconds,
            )
            slot.restarts += 1
            self._restarts_total += 1
            slot.state = "backoff"
            slot.relaunch_at = now + backoff
            slot.detect_t = now
            slot.last_reason = reason
            slot.restart_records.append({
                "reason": reason,
                "backoff_seconds": round(backoff, 3),
                "launch_generation": slot.launch_generation,
                "detect_to_ready_seconds": None,
            })
        logger.error(
            "fleet: replica %d DOWN (%s); restart %d/%d in %.1fs",
            slot.index, reason, slot.restarts, self.max_restarts,
            backoff,
        )

    def _adopt(self, slot: _ReplicaSlot, info: dict) -> None:
        """A (re)launched replica published its generation-verified
        port file: point EVERY balancer shard at it and half-open its
        breaker so each shard's prober readmits it after M successes.
        Ledger holds survive the relaunch — a parked warm spare that
        crashes comes back parked, not silently live."""
        slot.host = info.get("host", "127.0.0.1")
        slot.port = int(info["port"])
        self.dp.adopt(slot.index, slot.host, slot.port, slot.gen_tag())
        if self.holds is not None:
            self.holds.reapply(slot.index)
        with self._mu:
            slot.state = "up"
            if slot.detect_t is not None and slot.restart_records:
                slot.restart_records[-1]["detect_to_ready_seconds"] = (
                    round(time.monotonic() - slot.detect_t, 3)
                )
                slot.detect_t = None
        logger.info(
            "fleet: replica %d ready on %s:%d (generation %s)",
            slot.index, slot.host, slot.port, slot.gen_tag(),
        )

    def _sweep(self) -> None:
        """One supervision pass over every slot."""
        now = time.monotonic()
        for slot in self._slots:
            if slot.state in ("failed", "stopped"):
                if slot.state == "failed" and self.dp is not None:
                    # Keep the breaker firmly open: no trials against
                    # a written-off address.
                    self.dp.fail(slot.index)
                continue
            rc = slot.proc.poll() if slot.proc is not None else None
            if rc is not None and slot.state in ("up", "starting"):
                if self._stop.is_set():
                    slot.state = "stopped"
                    continue
                self.dp.down(slot.index)
                self._schedule_restart(
                    slot,
                    f"exited rc={rc}" if rc >= 0
                    else f"killed by signal {-rc}",
                )
                continue
            if slot.state == "up":
                failing = self.lb.breakers[slot.index].failing_for()
                if failing > self.hang_kill_seconds:
                    # Hung: the process lives but probes have failed
                    # continuously past the budget — put it down and
                    # treat it as a crash.
                    logger.error(
                        "fleet: replica %d HUNG (probes failing for "
                        "%.1fs) — killing pid %d", slot.index, failing,
                        slot.proc.pid,
                    )
                    self.dp.down(slot.index)
                    terminate_process(
                        slot.proc, grace_seconds=self.kill_grace_seconds
                    )
                    self._schedule_restart(
                        slot, f"hung ({failing:.1f}s of probe failures)"
                    )
                continue
            if slot.state == "backoff":
                self.dp.down(slot.index)
                if now >= slot.relaunch_at:
                    self._launch(slot)
                continue
            if slot.state == "starting":
                self.dp.down(slot.index)
                info = self._read_port_file(slot)
                if info is not None:
                    self._adopt(slot, info)
                elif now - slot.started_at > self.ready_timeout:
                    terminate_process(
                        slot.proc, grace_seconds=self.kill_grace_seconds
                    )
                    self._schedule_restart(
                        slot,
                        f"not ready within {self.ready_timeout:.0f}s",
                    )

    # -- observability -------------------------------------------------

    def _doc_extra(self) -> dict:
        with self._mu:
            states = [
                {
                    "replica": s.index,
                    "state": s.state,
                    "restarts": s.restarts,
                    "launch_generation": s.launch_generation,
                    "last_reason": s.last_reason,
                    "restart_records": list(s.restart_records[-8:]),
                }
                for s in self._slots
            ]
            sup = {
                "restarts_total": self._restarts_total,
                "replicas_failed": sum(
                    1 for s in self._slots if s.state == "failed"
                ),
                "max_restarts": self.max_restarts,
                "replica_states": states,
            }
        doc = {"supervisor": sup}
        if self.coordinator is not None:
            doc["rollout"] = self.coordinator.stats()
        if self.model_coordinators:
            doc["model_rollouts"] = {
                c.model_id: c.stats() for c in self.model_coordinators
            }
        if self.autoscaler is not None:
            doc["autoscale"] = self.autoscaler.stats()
        if self.holds is not None:
            doc["holds"] = self.holds.snapshot()
        if self.lb is not None:
            doc["data_plane"] = {
                "balancer_procs": self.balancer_procs,
                "reuse_port": self.lb._reuse_port,
            }
        if self.shards is not None and self.shards.handles \
                and self.lb is not None:
            from glint_word2vec_tpu.obs.aggregate import (
                merge_serving_snapshots,
            )

            # Shard 0 is the supervisor's in-process balancer; the
            # rest are the subprocess shards. Fold their serving
            # snapshots exactly like replica snapshots (exact
            # histogram merge, SLO counts summed then re-derived) and
            # sum the per-shard balancer counters into fleet totals.
            shard_snaps = (
                [self.lb.shard_snapshot()] + self.shards.snapshots()
            )
            doc["balancer_shards"] = shard_snaps
            doc["balancer_fleet"] = merge_serving_snapshots([
                s["serving"] for s in shard_snaps if s.get("serving")
            ])
            doc["balancer"] = _sum_balancer_stats([
                s.get("stats") for s in shard_snaps if s.get("up")
            ])
        return doc

    def report(self) -> dict:
        """Restart accounting in the shape the drill records."""
        return self._doc_extra()

    # -- main loop -----------------------------------------------------

    def _resolve_boot(self) -> Optional[str]:
        """The generation name the fleet boots from (None when booting
        a plain model dir outside the publish protocol). Blocks until
        a first committed generation exists when only ``watch_dir``
        was given."""
        from glint_word2vec_tpu.streaming.publish import resolve_latest

        if self.model_dir is not None:
            if self.watch_dir is not None:
                md = os.path.abspath(self.model_dir)
                if os.path.dirname(md) == os.path.abspath(self.watch_dir):
                    return os.path.basename(md)
            return None
        if self.watch_dir is None:
            return None  # custom build_replica_argv owns the boot
        while not self._stop.is_set():
            gen_dir = resolve_latest(self.watch_dir)
            if gen_dir is not None:
                with self._mu:
                    self._current_model_dir = gen_dir
                return os.path.basename(gen_dir)
            logger.info(
                "fleet: waiting for a first committed generation in %s",
                self.watch_dir,
            )
            time.sleep(max(0.5, self.watch_poll))
        return None

    def _resolve_model_boots(self) -> Dict[str, str]:
        """Boot generation name per watched catalog model (ISSUE 20).

        A model that was also given a static ``--add-model`` dir INSIDE
        its publish dir boots from that generation (the operator pinned
        the start point); otherwise this blocks until the model's first
        committed generation exists and records its dir in
        ``self.models`` so every replica's ``--add-model`` argv carries
        a loadable path."""
        from glint_word2vec_tpu.streaming.publish import resolve_latest

        boots: Dict[str, str] = {}
        for mid in sorted(self.model_watch_dirs):
            pub = self.model_watch_dirs[mid]
            with self._mu:
                static = self.models.get(mid)
            if static is not None:
                sd = os.path.abspath(static)
                if os.path.dirname(sd) == os.path.abspath(pub):
                    boots[mid] = os.path.basename(sd)
                    continue
            while not self._stop.is_set():
                gen_dir = resolve_latest(pub)
                if gen_dir is not None:
                    with self._mu:
                        self.models[mid] = gen_dir
                    boots[mid] = os.path.basename(gen_dir)
                    break
                logger.info(
                    "fleet: waiting for model %r's first committed "
                    "generation in %s", mid, pub,
                )
                time.sleep(max(0.5, self.watch_poll))
        return boots

    def _wait_initial_ready(self) -> None:
        """Block until every replica published its generation-verified
        port file; a replica dying before that is a boot error (fail
        fast — the operator misconfigured the fleet)."""
        deadline = time.time() + self.ready_timeout
        for slot in self._slots:
            while True:
                if self._stop.is_set():
                    return  # stop() during boot: run() exits promptly
                info = self._read_port_file(slot)
                if info is not None:
                    slot.host = info.get("host", "127.0.0.1")
                    slot.port = int(info["port"])
                    slot.state = "up"
                    break
                if slot.proc.poll() is not None:
                    raise RuntimeError(
                        f"replica {slot.index} exited "
                        f"rc={slot.proc.returncode} before binding its "
                        "port"
                    )
                if time.time() > deadline:
                    raise TimeoutError(
                        f"replica {slot.index} not ready in "
                        f"{self.ready_timeout}s"
                    )
                time.sleep(0.1)

    def run(self) -> int:
        """Launch the fleet and supervise until shut down (POST
        /shutdown on the balancer, SIGINT, or stop()). Returns 0 on a
        clean shutdown."""
        import tempfile

        boot_gen: Optional[str] = None
        with tempfile.TemporaryDirectory(prefix="glint_fleet_") as tmp:
            self._tmp = tmp
            try:
                boot_gen = self._resolve_boot()
                if self._stop.is_set():
                    return 0
                # Catalog models watched through the publish protocol
                # must resolve to loadable dirs BEFORE the first
                # replica launch — their paths ride every replica's
                # --add-model argv.
                model_boots = self._resolve_model_boots()
                if self._stop.is_set():
                    return 0
                if self.trace_dir:
                    # Before the first replica launch: the replicas'
                    # --trace-log sinks open inside this directory.
                    os.makedirs(self.trace_dir, exist_ok=True)
                    obs_events.set_recorder(obs_events.EventRecorder(
                        jsonl_path=os.path.join(
                            self.trace_dir, "balancer.jsonl"
                        ),
                    ))
                for slot in self._slots:
                    self._launch(slot)
                self._wait_initial_ready()
                if self._stop.is_set():
                    return 0
                urls = [
                    f"http://{s.host}:{s.port}" for s in self._slots
                ]
                multi = self.balancer_procs > 1
                self.lb = LoadBalancer(
                    urls, host=self.host, port=self.port,
                    breaker_failures=self.breaker_failures,
                    breaker_successes=self.breaker_successes,
                    breaker_open_seconds=self.breaker_open_seconds,
                    probe_interval=self.probe_interval,
                    probe_timeout=self.probe_timeout,
                    reuse_port=multi,
                    control=multi,
                    shard_id=0,
                    qos=self.qos,
                )
                for slot in self._slots:
                    self.lb.set_replica_address(
                        slot.index, slot.host, slot.port,
                        generation=slot.gen_tag(),
                    )
                self.dp = _FleetDataPlane(self.lb)
                self.holds = ReplicaHoldLedger(
                    self.dp.hold, self.dp.release, self.dp.clear_holds,
                )
                # Park the warm spares BEFORE any traffic flows: they
                # are launched and fully warmed but held out of
                # rotation until the autoscaler readmits them.
                for slot in self._slots[self.base_replicas:]:
                    self.holds.acquire("autoscale", slot.index)
                self.lb.doc_extra = self._doc_extra
                self.lb.on_shutdown = self._stop.set
                if self.trace_dir:
                    self.lb.enable_flight_recorder(
                        os.path.join(self.trace_dir, "flight")
                    )
                self.lb.start_background()
                self.lb.start_prober()
                if multi:
                    qos_dict = None
                    if self.qos is not None:
                        qos_dict = {
                            "tenant_rate": self.qos.tenant_rate,
                            "tenant_burst": self.qos.tenant_burst,
                            "bulk_max_inflight":
                                self.qos.bulk_max_inflight,
                            "max_tenants": self.qos.max_tenants,
                        }
                    self.shards = BalancerShardManager(
                        self.lb, self.balancer_procs - 1,
                        replica_specs=[
                            {
                                "host": s.host, "port": s.port,
                                "generation": s.gen_tag(),
                                "held": bool(
                                    self.holds.owners(s.index)
                                ),
                            }
                            for s in self._slots
                        ],
                        qos=qos_dict,
                        trace_dir=self.trace_dir,
                        log_dir=self.log_dir,
                        kill_grace_seconds=self.kill_grace_seconds,
                    )
                    self.shards.start()
                    self.dp.shards = self.shards
                if self.coordinated and self.watch_dir is not None:
                    with self._mu:
                        cur_dir = self._current_model_dir
                    self.coordinator = RolloutCoordinator(
                        self.lb, self.watch_dir,
                        poll_seconds=self.watch_poll,
                        current=boot_gen,
                        current_dir=cur_dir,
                        canary=self.canary,
                        step_timeout=self.rollout_step_timeout,
                        replica_ok=self._replica_ok,
                        on_generation=self._on_generation,
                        holds=self.holds,
                    )
                    self.coordinator.start()
                if self.coordinated and self.model_watch_dirs:
                    # One rollout coordinator per watched catalog
                    # model: each follows its own LATEST.json and
                    # reloads through /m/<id>/, so one model's pointer
                    # move never swaps (or holds back) any other
                    # model's state on the shared replicas. They share
                    # the hold ledger with the default coordinator and
                    # the autoscaler, so concurrent rollouts can never
                    # double-hold a replica.
                    mcs: List[RolloutCoordinator] = []
                    for mid in sorted(self.model_watch_dirs):
                        with self._mu:
                            cur_dir = self.models.get(mid)
                        mcs.append(RolloutCoordinator(
                            self.lb, self.model_watch_dirs[mid],
                            poll_seconds=self.watch_poll,
                            current=model_boots.get(mid),
                            current_dir=cur_dir,
                            canary=(
                                self.canary.scoped(mid)
                                if self.canary is not None else None
                            ),
                            step_timeout=self.rollout_step_timeout,
                            replica_ok=self._replica_ok,
                            on_generation=self._on_model_generation(mid),
                            holds=self.holds,
                            model_id=mid,
                        ))
                    self.model_coordinators = mcs
                    for mc in mcs:
                        mc.start()
                if self.warm_spares > 0 \
                        or self.autoscale_config is not None:
                    cfg = self.autoscale_config or AutoscaleConfig(
                        min_live=self.base_replicas,
                        max_live=self.num_replicas,
                    )
                    coords = [
                        c for c in
                        [self.coordinator, *self.model_coordinators]
                        if c is not None
                    ]
                    pinned = (
                        (lambda: any(c.in_progress() for c in coords))
                        if coords else None
                    )
                    self.autoscaler = Autoscaler(
                        holds=self.holds, config=cfg,
                        signals=self._autoscale_signals,
                        parked=self._autoscale_parked,
                        live=self._autoscale_live,
                        pinned=pinned,
                    )
                    self.autoscaler.start()
                # The port file is the readiness signal: written only
                # once the WHOLE control plane (balancer shards,
                # rollout coordinator, autoscaler) is assembled, so
                # the first /metrics a reader sends after seeing it
                # already carries every doc section.
                if self.port_file:
                    from glint_word2vec_tpu.utils import atomic_write_json

                    atomic_write_json(
                        self.port_file,
                        {"host": self.lb.host, "port": self.lb.port},
                    )
                logger.info(
                    "fleet up: %d replicas (%s, %d warm spare(s)) "
                    "behind %s:%d x%d balancer proc(s)%s",
                    self.num_replicas, ", ".join(urls),
                    self.warm_spares,
                    self.lb.host, self.lb.port, self.balancer_procs,
                    f", serving {boot_gen}" if boot_gen else "",
                )
                self.ready.set()
                try:
                    while not self._stop.is_set() \
                            and not self.lb.stopped():
                        self._sweep()
                        time.sleep(self.poll_interval)
                except KeyboardInterrupt:
                    pass
                return 0
            finally:
                self._stop.set()
                self.ready.set()
                if self.autoscaler is not None:
                    self.autoscaler.stop()
                if self.coordinator is not None:
                    self.coordinator.stop()
                for mc in self.model_coordinators:
                    mc.stop()
                if self.shards is not None:
                    self.shards.stop_all()
                if self.lb is not None:
                    self.lb.stop()
                for slot in self._slots:
                    if slot.proc is not None:
                        terminate_process(
                            slot.proc,
                            grace_seconds=self.kill_grace_seconds,
                        )
                for f in self._logs:
                    try:
                        f.close()
                    except OSError:
                        pass
                self._logs = []
                self._tmp = None

    def _replica_ok(self, i: int) -> bool:
        with self._mu:
            return self._slots[i].state not in ("failed", "stopped")

    # -- autoscaler plumbing -------------------------------------------

    def _autoscale_signals(self) -> dict:
        """The demand signals the fleet already emits, folded across
        every balancer shard: cumulative shed count (retry-path sheds +
        exhaustions + QoS sheds), WINDOWED forward-path p95 (over the
        traffic since the previous policy step — a cumulative p95
        would never decay after one surge, so idle could never be
        detected and scale-down would never fire), breaker-open count,
        and any SLO fast-burn alert."""
        lb = self.lb
        if lb is None:
            return {}
        blocks = [lb.balancer_stats()]
        snaps = [lb.shard_snapshot()]
        if self.shards is not None:
            for s in self.shards.snapshots():
                snaps.append(s)
                if s.get("up") and s.get("stats"):
                    blocks.append(s["stats"])
        shed = 0.0
        for b in blocks:
            shed += int(b.get("shed_retries_total") or 0)
            shed += int(b.get("exhausted_total") or 0)
            q = b.get("qos")
            if q:
                shed += sum((q.get("shed_total") or {}).values())
        fast_burn = False
        deltas = []
        cur: Dict[Tuple, dict] = {}
        for s in snaps:
            serving = s.get("serving") or {}
            for path, ep in (serving.get("endpoints") or {}).items():
                hs = ep.get("hist")
                if not hs:
                    continue
                key = (s.get("shard"), path)
                cur[key] = hs
                d = _hist_window_delta(
                    self._autoscale_prev_hists.get(key), hs
                )
                if d is not None:
                    deltas.append(d)
            slo = serving.get("slo") or {}
            for ep in (slo.get("endpoints") or {}).values():
                if (ep.get("alerts") or {}).get("fast_burn"):
                    fast_burn = True
        self._autoscale_prev_hists = cur
        p95 = None
        if deltas:
            h = LatencyHistogram.merge(deltas)
            if h.n > 0:
                p95 = round(h.quantile(0.95) * 1e3, 3)
        breakers_open = sum(
            1 for b in lb.breakers
            if b.state() == ReplicaBreaker.OPEN
        )
        return {
            "shed_total": shed,
            "p95_ms": p95,
            "breakers_open": breakers_open,
            "fast_burn": fast_burn,
        }

    def _autoscale_live(self) -> List[int]:
        """Replicas currently serving traffic: up, and held by no
        owner (a parked spare or a mid-rollout replica is not live)."""
        out = []
        for s in self._slots:
            with self._mu:
                up = s.state == "up"
            if up and not self.holds.owners(s.index):
                out.append(s.index)
        return out

    def _autoscale_parked(self) -> List[int]:
        """Warm spares the autoscaler may readmit: up, breaker CLOSED
        (the prober vouches for them), and held by the autoscaler
        ALONE — a canary or rollout hold disqualifies a replica from
        being spare capacity."""
        out = []
        for s in self._slots:
            with self._mu:
                up = s.state == "up"
            if not up:
                continue
            if self.holds.owners(s.index) != frozenset(("autoscale",)):
                continue
            if self.lb.breakers[s.index].state() \
                    != ReplicaBreaker.CLOSED:
                continue
            out.append(s.index)
        return out

    def _on_generation(self, gen: str, gen_dir: str) -> None:
        """Rollout coordinator promoted ``gen`` fleet-wide: relaunches
        from now on boot from it (a replica restarting mid-rollout
        converges instead of resurrecting an old generation)."""
        with self._mu:
            self._current_model_dir = gen_dir

    def _on_model_generation(self, model_id: str):
        """Callback factory for the per-model coordinators: promoting
        model ``model_id``'s generation updates ONLY that model's
        --add-model boot dir, so a replica relaunch rejoins with the
        whole catalog at its promoted state."""
        def cb(gen: str, gen_dir: str) -> None:
            with self._mu:
                self.models[model_id] = gen_dir
        return cb

    def stop(self) -> None:
        self._stop.set()


def serve_fleet(
    model_dir: Optional[str],
    *,
    replicas: int = 2,
    host: str = "127.0.0.1",
    port: int = 8800,
    watch_dir: Optional[str] = None,
    replica_flags: Optional[List[str]] = None,
    log_dir: Optional[str] = None,
    ready_timeout: float = 900.0,
    port_file: Optional[str] = None,
    **supervisor_kwargs,
) -> int:
    """Launch ``replicas`` supervised serving processes following one
    model (or one publish dir) and front them with a breaker-aware
    :class:`LoadBalancer` in this process until killed.

    Each replica binds an ephemeral port and signals readiness through
    its generation-stamped ``--port-file`` — written only after the
    full serving warmup (and ANN build + recall gate, when enabled),
    so the balancer's first request never lands on a cold replica.
    ``replica_flags`` pass through to every ``cli serve`` invocation
    verbatim. Dead or hung replicas are relaunched by the
    :class:`FleetSupervisor` under capped backoff and a restart
    budget; with ``watch_dir``, generation moves are rolled out one
    replica at a time behind the shadow-canary gate (see
    ``supervisor_kwargs``: ``canary``, ``max_restarts``, breaker and
    probe knobs, ...). Returns the exit code (0 on clean shutdown).
    """
    return FleetSupervisor(
        model_dir,
        replicas=replicas,
        host=host,
        port=port,
        watch_dir=watch_dir,
        replica_flags=replica_flags,
        log_dir=log_dir,
        ready_timeout=ready_timeout,
        port_file=port_file,
        **supervisor_kwargs,
    ).run()
