"""Horizontal serving: N replica processes behind one load balancer.

One serving process tops out on one device and one GIL; production
traffic needs N of them. This module adds the front half of ISSUE 12's
scale-out story:

* :class:`LoadBalancer` — a stdlib HTTP proxy that spreads requests
  round-robin over a replica fleet, using the replicas' OWN overload
  signals (PR 7's bounded-admission 429 and degraded-mode 429/503) as
  honest backpressure: a shed replica is skipped for the next one, and
  only when EVERY replica sheds does the client see the 429 (with its
  ``Retry-After``) — the balancer never invents capacity, it only finds
  it. Per-replica connections are kept alive per handler thread, so the
  proxy adds one local hop, not a reconnect.

* Fleet observability — ``GET /metrics`` scrapes every replica's JSON
  snapshot and folds them through PR 8's
  :func:`~glint_word2vec_tpu.obs.aggregate.merge_serving_snapshots`
  into ONE ServingMetrics-shaped document (rendered by the same
  ``serving_to_prometheus``, index family included), alongside
  per-replica blocks and the balancer's own counters
  (``fleet_to_prometheus``).

* :func:`serve_fleet` — the launcher: N ``cli serve`` subprocesses on
  ephemeral ports following one model dir (or one publish dir, so a
  streaming trainer hot-swaps the WHOLE fleet), readiness via each
  replica's ``--port-file`` (written only after warmup, so the
  balancer never routes to a cold replica), then the balancer in the
  launcher process. ``POST /shutdown`` on the balancer fans out to
  every replica and stops the fleet — the one-switch teardown CI uses.

Replicas are plain ``serve`` processes: nothing here is in their code
path, so a balancer crash leaves N independently addressable servers.
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional
from urllib.parse import parse_qs, urlparse

logger = logging.getLogger(__name__)


def _read_request(sock, buf: bytearray):
    """Read one HTTP/1.1 request off a keep-alive socket: returns
    (method, path, lowercase-header dict, body) or None on a clean
    close between requests. Raises on transport errors or malformed
    framing. Content-Length framing only — the serving stack (and
    every client of it) never chunks."""
    while True:
        head_end = buf.find(b"\r\n\r\n")
        if head_end >= 0:
            break
        chunk = sock.recv(65536)
        if not chunk:
            if buf:
                raise ConnectionError("client closed mid-request")
            return None
        buf += chunk
    head = bytes(buf[:head_end]).decode("latin-1")
    lines = head.split("\r\n")
    parts = lines[0].split(None, 2)
    if len(parts) < 3:
        raise ValueError(f"malformed request line: {lines[0]!r}")
    method, path = parts[0], parts[1]
    headers = {}
    for line in lines[1:]:
        k, _, v = line.partition(":")
        headers[k.strip().lower()] = v.strip()
    clen = int(headers.get("content-length", 0))
    body_end = head_end + 4 + clen
    while len(buf) < body_end:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("client closed mid-body")
        buf += chunk
    body = bytes(buf[head_end + 4 : body_end])
    del buf[:body_end]
    return method, path, headers, body

#: Statuses that mean "this replica cannot take the request right now,
#: another one might": bounded admission / degraded mode (429), plus
#: 503 for a replica mid-restart behind a stale port. 404/400/504 are
#: NOT retried — they are answers about the request, not the replica.
_SHED_STATUSES = frozenset((429, 503))


class _ReplicaConn:
    """One persistent keep-alive socket to a replica with a minimal
    HTTP/1.1 reader — the balancer's per-request cost IS the fleet's
    overhead floor, so the proxy hop skips ``http.client`` entirely.
    Owned by exactly one handler thread (per-thread pools), so no
    locking. The replica always answers Content-Length-framed JSON
    (serving.py's ``_send``)."""

    __slots__ = ("host", "port", "timeout", "_sock", "_buf", "_prefix")

    def __init__(self, host: str, port: int, timeout: float):
        self.host, self.port, self.timeout = host, port, timeout
        self._sock = None
        self._buf = bytearray()
        self._prefix = (
            f"Host: {host}:{port}\r\n"
            "Content-Type: application/json\r\n"
            "Content-Length: "
        )

    def _connect(self):
        s = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )
        # NODELAY: requests/responses are small multi-segment writes;
        # Nagle + delayed ACK turns each proxied call into a ~40ms
        # stall otherwise (the PR 2 serving-side fix, outbound twin).
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s
        self._buf.clear()
        return s

    def roundtrip(self, method: str, path: str, body: bytes):
        """One request/response exchange; returns (status, body,
        header-dict with lowercase keys). Raises on any transport
        error (caller drops the connection and tries the next
        replica)."""
        sock = self._sock or self._connect()
        req = (
            f"{method} {path} HTTP/1.1\r\n{self._prefix}"
            f"{len(body)}\r\n\r\n"
        ).encode("latin-1") + body
        try:
            sock.sendall(req)
        except OSError:
            # The replica closed our idle keep-alive socket (timeout,
            # restart): one fresh-connection retry is safe — nothing
            # of this request reached a handler.
            sock = self._connect()
            sock.sendall(req)
        buf = self._buf
        while True:
            head_end = buf.find(b"\r\n\r\n")
            if head_end >= 0:
                break
            chunk = sock.recv(65536)
            if not chunk:
                raise ConnectionError("replica closed mid-response")
            buf += chunk
        head = bytes(buf[:head_end]).decode("latin-1")
        lines = head.split("\r\n")
        status = int(lines[0].split(None, 2)[1])
        headers = {}
        clen = 0
        for line in lines[1:]:
            k, _, v = line.partition(":")
            k = k.strip().lower()
            v = v.strip()
            headers[k] = v
            if k == "content-length":
                clen = int(v)
        body_end = head_end + 4 + clen
        while len(buf) < body_end:
            chunk = sock.recv(65536)
            if not chunk:
                raise ConnectionError("replica closed mid-body")
            buf += chunk
        rbody = bytes(buf[head_end + 4 : body_end])
        del buf[:body_end]
        return status, rbody, headers

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None


class LoadBalancer:
    """Round-robin HTTP proxy over serving replicas with
    overload-aware retry and a merged fleet exposition.

    Routes:
      GET  /healthz   fleet health: replicas up/total (200 while >= 1 up)
      GET  /metrics   merged fleet snapshot (JSON; ?format=prometheus
                      renders the merged serving exposition + the
                      glint_fleet_* balancer family)
      POST /shutdown  fan-out shutdown to every replica, then stop
      anything else   proxied to a replica (round robin; sheds retried
                      on the next replica, exhaustion relays the shed)
    """

    def __init__(self, replica_urls: List[str], host: str = "127.0.0.1",
                 port: int = 0, *, scrape_timeout: float = 2.0,
                 proxy_timeout: float = 60.0):
        self.replicas = [self._parse(u) for u in replica_urls]
        if not self.replicas:
            raise ValueError("at least one replica url required")
        self.scrape_timeout = float(scrape_timeout)
        self.proxy_timeout = float(proxy_timeout)
        self._mu = threading.Lock()
        self._rr = 0
        self._proxied = [0] * len(self.replicas)
        self._errors = [0] * len(self.replicas)
        self._shed_retries = 0
        self._exhausted = 0
        self._local = threading.local()
        # Data plane: a thread-per-connection raw-socket loop with a
        # minimal HTTP/1.1 parser instead of ThreadingHTTPServer. The
        # balancer's per-request GIL time is the FLEET's throughput
        # ceiling — BaseHTTPRequestHandler's readline/email parsing and
        # per-response date formatting alone cost more than a whole
        # warmed ANN dispatch, and at N replicas the proxy must stay
        # the cheapest stage in the chain.
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(128)
        self.host, self.port = self._listener.getsockname()[:2]
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._prev_switch: Optional[float] = None

    # -- data plane ----------------------------------------------------

    _STATUS_LINE = {
        code: f"HTTP/1.1 {code} {reason}\r\n".encode("latin-1")
        for code, reason in (
            (200, "OK"), (400, "Bad Request"), (404, "Not Found"),
            (429, "Too Many Requests"), (500, "Internal Server Error"),
            (503, "Service Unavailable"), (504, "Gateway Timeout"),
        )
    }

    def _respond(self, sock, code: int, body: bytes, ctype: str,
                 retry_after: Optional[str] = None) -> None:
        head = self._STATUS_LINE.get(
            code, f"HTTP/1.1 {code} X\r\n".encode("latin-1")
        )
        extra = (
            f"Retry-After: {retry_after}\r\n" if retry_after else ""
        )
        sock.sendall(
            head
            + (
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(body)}\r\n{extra}\r\n"
            ).encode("latin-1")
            + body
        )

    def _respond_json(self, sock, code: int, obj,
                      retry_after: Optional[str] = None) -> None:
        self._respond(
            sock, code, json.dumps(obj).encode(), "application/json",
            retry_after,
        )

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed by stop()
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True,
                name="glint-fleet-conn",
            ).start()

    def _serve_conn(self, sock) -> None:
        """One client connection: parse requests with the minimal
        framed reader, route control paths locally, proxy the rest.
        Keep-alive by default (HTTP/1.1); 'Connection: close' honored."""
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        buf = bytearray()
        try:
            while not self._stop.is_set():
                req = _read_request(sock, buf)
                if req is None:
                    return  # client closed between requests
                method, path, headers, body = req
                self._route(sock, method, path, headers, body)
                if headers.get("connection", "").lower() == "close":
                    return
        except (OSError, ValueError, ConnectionError):
            pass  # torn client connection / malformed request
        finally:
            sock.close()
            pool = getattr(self._local, "conns", None)
            if pool:
                for c in pool.values():
                    c.close()
                pool.clear()

    def _route(self, sock, method: str, path: str, headers: dict,
               body: bytes) -> None:
        url = urlparse(path)
        if method == "GET" and url.path == "/healthz":
            up, total, states = self.health()
            return self._respond_json(sock, 200 if up else 503, {
                "status": "ok" if up == total else (
                    "degraded" if up else "down"
                ),
                "replicas": total,
                "replicas_up": up,
                "replica_states": states,
            })
        if method == "GET" and url.path == "/metrics":
            doc = self.metrics_doc()
            fmt = parse_qs(url.query).get("format", ["json"])[0]
            if fmt == "prometheus":
                from glint_word2vec_tpu.obs.prometheus import (
                    fleet_to_prometheus,
                    serving_to_prometheus,
                )

                text = fleet_to_prometheus(doc)
                if doc.get("fleet"):
                    text += serving_to_prometheus(doc["fleet"])
                return self._respond(
                    sock, 200, text.encode(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            return self._respond_json(sock, 200, doc)
        if method == "POST" and url.path == "/shutdown":
            results = self.shutdown_fleet()
            self._respond_json(sock, 200, {
                "status": "shutting down fleet",
                "replicas": results,
            })
            threading.Thread(target=self.stop, daemon=True).start()
            return
        status, rbody, rheaders = self.forward(method, path, body)
        self._respond(
            sock, status, rbody,
            rheaders.get("content-type") or "application/json",
            rheaders.get("retry-after"),
        )

    @staticmethod
    def _parse(url: str):
        u = urlparse(url if "//" in url else f"http://{url}")
        return (u.hostname, int(u.port))

    # -- request forwarding --------------------------------------------

    def _conn(self, i: int) -> "_ReplicaConn":
        pool = getattr(self._local, "conns", None)
        if pool is None:
            pool = self._local.conns = {}
        c = pool.get(i)
        if c is None:
            host, port = self.replicas[i]
            c = pool[i] = _ReplicaConn(host, port, self.proxy_timeout)
        return c

    def _drop_conn(self, i: int) -> None:
        pool = getattr(self._local, "conns", None)
        if pool and i in pool:
            try:
                pool.pop(i).close()
            except Exception:
                pass

    def _next_start(self) -> int:
        with self._mu:
            self._rr += 1
            return self._rr

    def forward(self, method: str, path: str, body: bytes):
        """Send one request to the fleet: round-robin start, advance on
        connection failure or a shed status (429/503), at most one
        attempt per replica. Returns (status, body, headers). When
        every replica sheds, the LAST shed response is relayed — its
        Retry-After included — so the client sees the fleet's own
        backpressure, not an invented error.

        The hop rides one persistent raw keep-alive socket per
        (handler thread, replica) with a minimal response reader: at
        fleet throughput the balancer's per-request CPU is the fleet's
        overhead floor, so the hot path avoids the ``http.client``
        object machinery entirely."""
        n = len(self.replicas)
        start = self._next_start()
        last_shed = None
        attempted = 0
        for j in range(n):
            i = (start + j) % n
            try:
                status, rbody, rheaders = self._conn(i).roundtrip(
                    method, path, body
                )
            except Exception:
                self._drop_conn(i)
                with self._mu:
                    self._errors[i] += 1
                attempted += 1
                continue
            attempted += 1
            if status in _SHED_STATUSES:
                last_shed = (status, rbody, rheaders)
                with self._mu:
                    self._shed_retries += 1
                continue
            with self._mu:
                self._proxied[i] += 1
            return status, rbody, rheaders
        with self._mu:
            self._exhausted += 1
        if last_shed is not None:
            return last_shed
        return (
            503,
            json.dumps({
                "error": f"no replica reachable ({attempted} tried)"
            }).encode(),
            {"Content-Type": "application/json", "Retry-After": "1"},
        )

    # -- fleet views ---------------------------------------------------

    def _get_json(self, i: int, path: str):
        host, port = self.replicas[i]
        conn = http.client.HTTPConnection(
            host, port, timeout=self.scrape_timeout
        )
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read().decode())
        finally:
            conn.close()

    def health(self):
        """(up, total, per-replica state) from each replica's
        /healthz; a dead replica reports "unreachable"."""
        states = []
        up = 0
        for i in range(len(self.replicas)):
            try:
                status, h = self._get_json(i, "/healthz")
                state = h.get("status", f"http {status}")
                if status == 200:
                    up += 1
            except Exception:
                state = "unreachable"
            states.append({
                "url": self.replica_url(i), "state": state,
            })
        return up, len(self.replicas), states

    def replica_url(self, i: int) -> str:
        host, port = self.replicas[i]
        return f"http://{host}:{port}"

    def balancer_stats(self) -> dict:
        with self._mu:
            return {
                "shed_retries_total": self._shed_retries,
                "exhausted_total": self._exhausted,
                "proxied_total": int(sum(self._proxied)),
                "proxy_errors_total": int(sum(self._errors)),
            }

    def metrics_doc(self) -> dict:
        """The merged fleet document: per-replica snapshots (scraped
        now, failures reported not fatal), the PR 8 exact merge as
        ``fleet``, and the balancer's own counters."""
        from glint_word2vec_tpu.obs.aggregate import (
            merge_serving_snapshots,
        )

        replicas = []
        snaps = []
        with self._mu:
            proxied = list(self._proxied)
            errors = list(self._errors)
        for i in range(len(self.replicas)):
            entry: Dict[str, object] = {
                "url": self.replica_url(i),
                "proxied_total": proxied[i],
                "proxy_errors_total": errors[i],
            }
            try:
                _, snap = self._get_json(i, "/metrics")
                entry["up"] = True
                entry["snapshot"] = snap
                snaps.append(snap)
            except Exception as e:
                entry["up"] = False
                entry["scrape_error"] = str(e)
            replicas.append(entry)
        return {
            "replicas": replicas,
            "fleet": merge_serving_snapshots(snaps),
            "balancer": self.balancer_stats(),
        }

    def shutdown_fleet(self) -> List[dict]:
        """POST /shutdown to every replica (best effort)."""
        results = []
        for i in range(len(self.replicas)):
            host, port = self.replicas[i]
            try:
                conn = http.client.HTTPConnection(
                    host, port, timeout=self.scrape_timeout
                )
                try:
                    conn.request(
                        "POST", "/shutdown", body=b"{}",
                        headers={"Content-Type": "application/json"},
                    )
                    resp = conn.getresponse()
                    resp.read()
                    results.append({
                        "url": self.replica_url(i),
                        "status": resp.status,
                    })
                finally:
                    conn.close()
            except Exception as e:
                results.append({
                    "url": self.replica_url(i), "error": str(e),
                })
        return results

    # -- lifecycle -----------------------------------------------------

    def _tighten_gil_switch(self) -> None:
        # One handler thread per client connection, each a chain of
        # short GIL-holding sections (parse, forward, relay): at the
        # default 5ms switch interval the convoy adds whole scheduling
        # quanta per proxied call (the same effect serving.py tightens
        # for). Restored by stop().
        if self._prev_switch is None:
            self._prev_switch = sys.getswitchinterval()
            sys.setswitchinterval(0.001)

    def serve_forever(self) -> None:
        logger.info(
            "fleet balancer on %s:%d over %d replica(s)",
            self.host, self.port, len(self.replicas),
        )
        self._tighten_gil_switch()
        self._accept_loop()

    def start_background(self) -> None:
        self._tighten_gil_switch()
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="glint-fleet-lb",
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # Waking a thread blocked in accept() needs more than close():
        # on Linux, closing the fd from another thread leaves the
        # accept blocked forever. shutdown() wakes it with EINVAL; the
        # best-effort self-connect covers platforms where it doesn't.
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            socket.create_connection(
                (self.host, self.port), timeout=1
            ).close()
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:  # pragma: no cover - already closed
            pass
        if self._prev_switch is not None:
            sys.setswitchinterval(self._prev_switch)
            self._prev_switch = None


# ----------------------------------------------------------------------
# Launcher
# ----------------------------------------------------------------------


def _replica_argv(i: int, port_file: str, model_dir: Optional[str],
                  watch_dir: Optional[str], replica_flags: List[str]):
    argv = [
        sys.executable, "-m", "glint_word2vec_tpu.cli", "serve",
        "--host", "127.0.0.1", "--port", "0", "--port-file", port_file,
    ]
    if model_dir:
        argv += ["--model", model_dir]
    if watch_dir:
        argv += ["--watch-checkpoint", watch_dir]
    return argv + list(replica_flags)


def serve_fleet(
    model_dir: Optional[str],
    *,
    replicas: int = 2,
    host: str = "127.0.0.1",
    port: int = 8800,
    watch_dir: Optional[str] = None,
    replica_flags: Optional[List[str]] = None,
    log_dir: Optional[str] = None,
    ready_timeout: float = 900.0,
    port_file: Optional[str] = None,
) -> int:
    """Launch ``replicas`` serving processes following one model (or
    one publish dir) and front them with a :class:`LoadBalancer` in
    this process until killed.

    Each replica binds an ephemeral port and signals readiness through
    its ``--port-file`` — written only after the full serving warmup
    (and ANN build + recall gate, when enabled), so the balancer's
    first request never lands on a cold replica. ``replica_flags``
    pass through to every ``cli serve`` invocation verbatim (ann
    flags, cache size, overload bounds...). ``log_dir`` captures one
    ``replica-N.log`` per process; default inherits stderr.

    Returns the exit code (0 on clean shutdown). A dead replica is NOT
    relaunched here — run replicas under ``cli supervise`` for that;
    the balancer keeps serving from the survivors either way.
    """
    import tempfile

    replicas = max(1, int(replicas))
    procs: List[subprocess.Popen] = []
    logs = []
    with tempfile.TemporaryDirectory(prefix="glint_fleet_") as tmp:
        port_files = [
            os.path.join(tmp, f"replica-{i}.port") for i in range(replicas)
        ]
        try:
            for i in range(replicas):
                stderr = None
                if log_dir:
                    os.makedirs(log_dir, exist_ok=True)
                    # graftlint: ignore[atomic-persist] append-mode process log, not an artifact
                    f = open(
                        os.path.join(log_dir, f"replica-{i}.log"), "ab"
                    )
                    logs.append(f)
                    stderr = f
                procs.append(subprocess.Popen(
                    _replica_argv(
                        i, port_files[i], model_dir, watch_dir,
                        replica_flags or [],
                    ),
                    stdout=stderr, stderr=stderr,
                ))
            urls = []
            deadline = time.time() + ready_timeout
            for i, pf in enumerate(port_files):
                while not os.path.exists(pf):
                    if procs[i].poll() is not None:
                        raise RuntimeError(
                            f"replica {i} exited rc={procs[i].returncode} "
                            "before binding its port"
                        )
                    if time.time() > deadline:
                        raise TimeoutError(
                            f"replica {i} not ready in {ready_timeout}s"
                        )
                    time.sleep(0.1)
                with open(pf) as f:
                    info = json.load(f)
                urls.append(f"http://{info['host']}:{info['port']}")
            lb = LoadBalancer(urls, host=host, port=port)
            if port_file:
                from glint_word2vec_tpu.utils import atomic_write_json

                atomic_write_json(
                    port_file, {"host": lb.host, "port": lb.port}
                )
            logger.info(
                "fleet up: %d replicas (%s) behind %s:%d",
                replicas, ", ".join(urls), lb.host, lb.port,
            )
            try:
                lb.serve_forever()
            except KeyboardInterrupt:
                lb.stop()
            return 0
        finally:
            for p in procs:
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
            deadline = time.time() + 10
            for p in procs:
                try:
                    p.wait(timeout=max(0.1, deadline - time.time()))
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
            for f in logs:
                f.close()
