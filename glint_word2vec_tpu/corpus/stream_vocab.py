"""Streaming vocabulary: approximate counts over an unbounded sentence
stream plus online vocab growth — the ISGNS construction
(arXiv:1704.03956) the streaming trainer builds on.

Batch training scans the corpus twice: once for exact counts
(:func:`corpus.vocab.build_vocab`), once to encode. A stream gets one
look at each sentence and has no end, so three things change:

- **Admitted words keep exact counts.** Incrementing an int per
  occurrence is free; the adaptive subsample and negative-sampling
  distributions are recomputed from these live counts on a cadence
  (``EmbeddingEngine.set_noise_counts`` keeps the alias-table shapes
  fixed, so the refresh never recompiles a train program).
- **Candidate (out-of-vocabulary) words go through a space-saving
  sketch** (:class:`SpaceSavingSketch`, Misra-Gries family): bounded
  memory regardless of how many distinct junk tokens the stream carries,
  with the classic guarantee that any word occurring more than
  ``stream_words / capacity`` times since the sketch started is
  guaranteed present, and every estimate carries its own error bound.
- **Promotion assigns new words to the engine's spare extra rows**
  (``EmbeddingEngine.assign_extra_row``): a candidate whose GUARANTEED
  count (estimate minus error) clears ``min_count`` joins the
  vocabulary at the next free row index, so the grown word list stays
  aligned with the table by construction and the serving top-k mask
  (a traced scalar bound) widens without a recompile.

The vocabulary INDEX ordering therefore differs from a batch build
(batch ranks by frequency; streaming appends in promotion order).
Everything downstream keys on words, not ranks — the distributions are
functions word -> value — which is what the replay-parity test in
tests/test_stream_vocab.py pins down.
"""

from __future__ import annotations

import heapq
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from glint_word2vec_tpu.corpus.vocab import Vocabulary


class SpaceSavingSketch:
    """Space-saving heavy-hitter counter over a bounded ``capacity`` of
    tracked items (Metwally et al.; the Misra-Gries family ISGNS uses
    for its candidate vocabulary).

    Semantics: while under capacity, counts are exact (``error == 0``).
    At capacity, a new item evicts the currently-smallest tracked item
    and inherits its count as overestimation ``error``. Guarantees:

    - ``estimate(w) >= true_count(w)`` for every tracked ``w``, and
      ``estimate(w) - error(w) <= true_count(w)`` (the guaranteed lower
      bound promotion thresholds use);
    - any item with ``true_count > items_seen / capacity`` is tracked;
    - ``error(w) <= items_seen / capacity`` for every tracked item.

    Eviction uses a lazy min-heap over (count, item) snapshots: stale
    heap entries (the item's count moved on, or it was evicted) are
    skipped on pop, and the heap is rebuilt when it outgrows
    ``4 * capacity`` entries — amortized O(log capacity) per add,
    bounded memory.
    """

    __slots__ = ("capacity", "items_seen", "_counts", "_errors", "_heap")

    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        #: Total items ever added (the N in the error bound N/capacity).
        self.items_seen = 0
        self._counts: Dict[str, int] = {}
        self._errors: Dict[str, int] = {}
        self._heap: List[Tuple[int, str]] = []

    def __len__(self) -> int:
        return len(self._counts)

    def __contains__(self, item: str) -> bool:
        return item in self._counts

    def add(self, item: str, n: int = 1) -> None:
        self.items_seen += n
        c = self._counts.get(item)
        if c is not None:
            self._counts[item] = c + n
            heapq.heappush(self._heap, (c + n, item))
        elif len(self._counts) < self.capacity:
            self._counts[item] = n
            self._errors[item] = 0
            heapq.heappush(self._heap, (n, item))
        else:
            m, victim = self._pop_min()
            del self._counts[victim]
            del self._errors[victim]
            self._counts[item] = m + n
            self._errors[item] = m
            heapq.heappush(self._heap, (m + n, item))
        if len(self._heap) > 4 * self.capacity:
            self._heap = [(c, w) for w, c in self._counts.items()]
            heapq.heapify(self._heap)

    def _pop_min(self) -> Tuple[int, str]:
        """Current (count, item) minimum among tracked items, popping
        stale heap snapshots on the way."""
        while self._heap:
            c, w = heapq.heappop(self._heap)
            if self._counts.get(w) == c:
                return c, w
        # Heap drained of live entries (all stale): rebuild and retry.
        self._heap = [(c, w) for w, c in self._counts.items()]
        heapq.heapify(self._heap)
        return heapq.heappop(self._heap)

    def estimate(self, item: str) -> Tuple[int, int]:
        """(count_estimate, error) for a tracked item — the estimate
        overcounts by at most ``error``. Raises ``KeyError`` when the
        item is not tracked (its true count is then bounded by
        ``items_seen / capacity``)."""
        return self._counts[item], self._errors[item]

    def guaranteed(self, item: str) -> int:
        """Lower bound on the item's true count (0 when untracked)."""
        c = self._counts.get(item)
        if c is None:
            return 0
        return c - self._errors[item]

    def pop(self, item: str) -> Tuple[int, int]:
        """Remove a tracked item (promotion took it), returning its
        final (estimate, error)."""
        c = self._counts.pop(item)
        e = self._errors.pop(item)
        return c, e

    def over_threshold(self, threshold: int) -> List[Tuple[str, int, int]]:
        """Tracked items whose GUARANTEED count clears ``threshold``,
        as (item, estimate, error), largest estimates first — the
        promotion candidate scan."""
        out = [
            (w, c, self._errors[w])
            for w, c in self._counts.items()
            if c - self._errors[w] >= threshold
        ]
        out.sort(key=lambda t: (-t[1], t[0]))
        return out

    @property
    def max_untracked_count(self) -> float:
        """Upper bound on the true count of any UNtracked item — the
        sketch's blind spot, surfaced as a gauge."""
        if len(self._counts) < self.capacity:
            return 0.0
        return self.items_seen / self.capacity


class StreamVocab:
    """A vocabulary that grows while a stream is consumed.

    Wraps a bootstrap :class:`~glint_word2vec_tpu.corpus.vocab
    .Vocabulary` (exact counts from the bootstrap window) and maintains:
    exact live counts for every admitted word, the candidate sketch for
    everything else, and the word -> row mapping that mirrors the
    engine's row assignment (base vocab rows first, promoted words
    appended in promotion order at ``vocab_size + j``).
    """

    def __init__(self, base: Vocabulary, *, sketch_capacity: int = 65536,
                 max_size: Optional[int] = None):
        self.words: List[str] = list(base.words)
        self.word_index: Dict[str, int] = dict(base.word_index)
        self._counts: List[int] = [int(c) for c in base.counts]
        #: Engine ``vocab_size``: rows below this came from the
        #: bootstrap scan; rows at or above it are promoted words on
        #: extra rows.
        self.base_size = base.size
        #: Total KEPT (in-vocabulary) word occurrences observed,
        #: bootstrap included — the ``train_words_count`` analogue the
        #: adaptive subsample distribution normalizes by.
        self.train_words_count = int(base.train_words_count)
        #: Out-of-vocabulary occurrences routed to the sketch.
        self.oov_words_seen = 0
        self.promoted = 0
        self.sketch = SpaceSavingSketch(sketch_capacity)
        #: Hard cap on len(words) (base + promotable); None = unbounded
        #: here (the engine's spare-row pool still bounds promotion).
        self.max_size = max_size

    @property
    def size(self) -> int:
        return len(self.words)

    def __contains__(self, word: str) -> bool:
        return word in self.word_index

    def counts_array(self) -> np.ndarray:
        """Live counts snapshot aligned with ``words`` (int64)."""
        return np.asarray(self._counts, dtype=np.int64)

    def observe(self, sentence: Sequence[str]) -> List[int]:
        """Count one sentence and encode its in-vocabulary words.

        Admitted words get an exact count increment and their row index
        in the output; OOV words feed the candidate sketch (and are
        dropped from the encoding, exactly as batch training drops OOV
        — until promotion admits them, from which point on they train).
        """
        ids: List[int] = []
        wi = self.word_index
        counts = self._counts
        kept = 0
        for w in sentence:
            i = wi.get(w)
            if i is None:
                self.sketch.add(w)
                self.oov_words_seen += 1
            else:
                counts[i] += 1
                kept += 1
                ids.append(i)
        self.train_words_count += kept
        return ids

    def encode(self, sentence: Sequence[str]) -> List[int]:
        """Encode WITHOUT counting — for replaying sentences whose
        occurrences are already in the counts (the bootstrap window,
        whose exact counts seeded the base vocabulary and the sketch).
        OOV words are dropped, not sketched."""
        wi = self.word_index
        return [i for w in sentence if (i := wi.get(w)) is not None]

    def promotable(self, min_count: int,
                   limit: Optional[int] = None) -> List[Tuple[str, int]]:
        """Candidates whose guaranteed sketch count clears
        ``min_count``, as (word, estimated_count), most frequent first,
        at most ``limit`` of them. Respects ``max_size``."""
        room = None
        if self.max_size is not None:
            room = max(0, self.max_size - self.size)
        out = [
            (w, est)
            for w, est, _err in self.sketch.over_threshold(min_count)
        ]
        if room is not None:
            out = out[:room]
        if limit is not None:
            out = out[:limit]
        return out

    def promote(self, word: str, count: Optional[int] = None) -> int:
        """Admit a candidate: append it to the vocabulary at the next
        row index (which the caller pairs with
        ``engine.assign_extra_row`` — both count assignments in the
        same order, so the indices agree by construction). ``count``
        defaults to the sketch estimate; the word leaves the sketch.
        Returns the new index."""
        if word in self.word_index:
            raise ValueError(f"word {word!r} already in vocabulary")
        if self.max_size is not None and self.size >= self.max_size:
            raise ValueError(
                f"vocabulary at max_size ({self.max_size}); cannot "
                f"promote {word!r}"
            )
        if count is None:
            count = self.sketch.estimate(word)[0]
        if word in self.sketch:
            self.sketch.pop(word)
        idx = len(self.words)
        self.words.append(word)
        self.word_index[word] = idx
        self._counts.append(int(count))
        # A promoted word's pre-promotion occurrences were counted by
        # the sketch, not train_words_count; fold the estimate in so
        # the subsample normalizer reflects what the counts claim.
        self.train_words_count += int(count)
        self.promoted += 1
        return idx

    # -- adaptive distributions ----------------------------------------

    def keep_probabilities(self, subsample_ratio: float) -> np.ndarray:
        """Per-word keep probability over the GROWN vocabulary — the
        exact :meth:`Vocabulary.keep_probabilities` formula evaluated
        on the live counts (the ISGNS adaptive subsample
        distribution). The streaming trainer applies these host-side
        while filling each round's buffer."""
        if subsample_ratio <= 0:
            return np.ones(self.size, dtype=np.float64)
        counts = self.counts_array()
        pcn = counts.astype(np.float64) / float(
            max(self.train_words_count, 1)
        )
        with np.errstate(divide="ignore", invalid="ignore"):
            ran = (np.sqrt(pcn / subsample_ratio) + 1.0) * (
                subsample_ratio / pcn
            )
        ran = np.where(counts > 0, ran, 0.0)
        return np.clip(ran, 0.0, 1.0)

    def noise_counts(self) -> np.ndarray:
        """Live counts over the BASE vocabulary only — the adaptive
        negative-sampling distribution (``engine.set_noise_counts``
        keeps the alias shapes fixed at vocab_size; promoted words are
        never negative-sampled, like fastText bucket rows)."""
        return np.asarray(self._counts[: self.base_size], dtype=np.int64)

    def noise_weights(self, power: float = 0.75) -> np.ndarray:
        """Normalized ``count^power`` noise distribution over the base
        vocab — what :meth:`noise_counts` induces; used for the
        distribution-drift gauge."""
        w = np.power(self.noise_counts().astype(np.float64), power)
        s = w.sum()
        return w / s if s > 0 else w

    def snapshot_vocabulary(self) -> Vocabulary:
        """Immutable :class:`Vocabulary` of the current grown state —
        what a published model generation carries (words.txt order ==
        row order)."""
        return Vocabulary(
            words=list(self.words),
            counts=self.counts_array(),
            word_index=dict(self.word_index),
            train_words_count=int(self.train_words_count),
        )


def bootstrap_stream_vocab(
    sentences: Iterable[Sequence[str]],
    *,
    min_count: int = 5,
    sketch_capacity: int = 65536,
    max_size: Optional[int] = None,
) -> StreamVocab:
    """Build a :class:`StreamVocab` from a bootstrap window of the
    stream: exact batch-style counts (``build_vocab`` semantics —
    frequency-ranked indices, first-seen ties) seed the base
    vocabulary, and every bootstrap word that fell below ``min_count``
    seeds the candidate sketch with its exact count, so a word that
    was warming up during bootstrap is not forgotten."""
    import collections

    from glint_word2vec_tpu.corpus.vocab import build_vocab

    counter: collections.Counter = collections.Counter()
    materialized = []
    for s in sentences:
        counter.update(s)
        materialized.append(s)
    base = build_vocab(materialized, min_count=min_count)
    sv = StreamVocab(
        base, sketch_capacity=sketch_capacity, max_size=max_size
    )
    for w, c in counter.items():
        if w not in base.word_index:
            sv.sketch.add(w, c)
            sv.oov_words_seen += c
    return sv
