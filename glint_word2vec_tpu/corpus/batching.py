"""Sentence -> fixed-shape skip-gram minibatch pipeline (host side).

Reference data path (mllib/feature/ServerSideGlintWord2Vec.scala:329-429):
words -> vocab indices (OOV dropped, mllib:336), sentences chunked at
``maxSentenceLength`` (mllib:341), per-iteration frequency subsampling
(mllib:371-379), per-position shrunk context windows (mllib:381-390), then
``sliding(batchSize)`` groups of positions fed to the parameter servers
(mllib:417-421).

The TPU restatement: every minibatch is a *static-shape* triple

    centers  (B,)       int32   -- center word indices
    contexts (B, 2W)    int32   -- padded context word indices
    mask     (B, 2W)    float32 -- 1.0 where the context slot is real

so the jit-compiled step never recompiles. Variable-length sentences,
shrunk windows, and partial final batches all become mask, not shape.

Window semantics mirror the reference exactly (documented divergences only):
for each position ``i``, draw ``b ~ U[0, window)`` and take context positions
``[max(0, i-b), min(i+b, len))`` excluding ``i`` (mllib:384-388) — note the
half-open upper bound, inherited from Scala's ``until``. Offsets therefore
span ``[-(window-1), window-2]``, so a row needs exactly ``2*window - 3``
context lanes (``window-1`` on the left, ``window-2`` on the right);
:func:`context_width` is the single source of truth for that shape.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from glint_word2vec_tpu.corpus.vocab import Vocabulary


def context_width(window: int) -> int:
    """Context lanes per center position.

    Reachable offsets are ``-(window-1) .. -1`` and ``1 .. window-2`` (see
    module docstring), i.e. ``2*window - 3`` lanes. ``window=1`` draws
    ``b = 0`` always — the reference trains nothing in that configuration —
    kept as one permanently-masked lane so device arrays are never 0-width.
    """
    return max(1, 2 * int(window) - 3)


def packed_pair_batch(
    batch_size: int, window: int, multiple: int = 1
) -> int:
    """Dense pair slots covering ~``batch_size`` center positions.

    The packed scan consumes whole positions until the next one's pairs
    would overflow the pair batch, so the EFFECTIVE synchronous batch
    of one packed step is ``P / E[pairs per position]`` positions.
    Sizing ``P`` as ``batch_size * context_width`` (the grid step's lane
    count) silently trains a ~1/density larger synchronous batch than
    the grid step — enough to cross the hot-row overshoot threshold on
    small vocabularies (all of a frequent word's same-direction rank-1
    updates in a step are computed from the same pre-step row, so their
    sum scales with its per-step occurrence count). This rule instead
    matches the grid step's position coverage: ``E[pairs/position] =
    E[max(2b - 1, 0)]`` for the shrink draw ``b ~ U[0, W)`` =
    ``(W-1)^2 / W`` (sentence-boundary clipping only lowers it, which
    just makes a step cover slightly more positions). Floored at the
    lane count (forward-progress guarantee of pack_window_pairs) and
    rounded up to ``multiple`` (the data-axis size)."""
    W = int(window)
    exp_pairs = max((W - 1) ** 2 / W, 1.0)
    P = max(
        int(np.ceil(batch_size * exp_pairs)),
        context_width(W),
        int(multiple),
    )
    return -(-P // int(multiple)) * int(multiple)


def window_offsets(window: int) -> np.ndarray:
    """The lane -> relative-offset map matching :func:`context_width`."""
    W = int(window)
    if W == 1:
        return np.array([1], dtype=np.int64)  # never valid; see context_width
    return np.concatenate([np.arange(-(W - 1), 0), np.arange(1, W - 1)])


def encode_sentences(
    sentences: Iterable[Sequence[str]], vocab: Vocabulary
) -> List[np.ndarray]:
    """Words -> int32 index arrays, OOV dropped, empty results removed.

    Reference: ``words.flatMap(bcVocabHash.value.get)`` (mllib:335-340).
    """
    out = []
    for s in sentences:
        ids = vocab.encode(s)
        if ids.size:
            out.append(ids)
    return out


def pack_query_block(
    encoded: Sequence[np.ndarray], rows: Optional[int] = None
) -> Tuple[Optional[np.ndarray], Optional[np.ndarray], int]:
    """Pack encoded sentences into one dense pow2-bucketed ``(rows, len)``
    index/mask pair — the :meth:`Word2VecModel.transform_sentences`
    padding factored out for the bulk pipeline
    (``glint_word2vec_tpu.batch``). ``rows`` fixes the row bucket (the
    bulk producer packs full fixed-size batches so the compiled family
    is one row bucket wide); None falls back to ``next_pow2(len(...))``,
    the serving quantization. Mask-0 padding keeps the device means
    exact: padded rows come back as zero vectors (sliced off by the
    caller), padded columns add exact +0.0 terms to each masked mean.

    Returns ``(idx, mask, n)`` where ``n`` is the real row count. A
    block whose sentences are ALL empty (blank/all-OOV lines) returns
    ``(None, None, n)`` — nothing to dispatch, every row is the zero
    vector."""
    from glint_word2vec_tpu.utils import next_pow2

    n = len(encoded)
    max_len = max((len(x) for x in encoded), default=0)
    if max_len == 0:
        return None, None, n
    r = int(rows) if rows is not None else next_pow2(n)
    if n > r:
        raise ValueError(f"{n} sentences exceed the {r}-row bucket")
    idx = np.zeros((r, next_pow2(max_len)), np.int32)
    mask = np.zeros(idx.shape, np.float32)
    for i, x in enumerate(encoded):
        if len(x):
            idx[i, : len(x)] = x
            mask[i, : len(x)] = 1.0
    return idx, mask, n


def chunk_sentences(
    sentences: Iterable[np.ndarray], max_sentence_length: int
) -> List[np.ndarray]:
    """Split long sentences into chunks of at most ``max_sentence_length``.

    Reference: ``sentenceSplit.grouped(maxSentenceLength)`` (mllib:341-343).
    """
    if max_sentence_length <= 0:
        raise ValueError("max_sentence_length must be > 0")
    out = []
    for ids in sentences:
        for start in range(0, len(ids), max_sentence_length):
            out.append(ids[start : start + max_sentence_length])
    return out


def subsample_sentence(
    ids: np.ndarray, keep_prob: np.ndarray, rng: np.random.Generator
) -> np.ndarray:
    """Frequency subsampling with the *intended* reference formula.

    Keep word ``w`` with probability ``keep_prob[w]`` (see
    :meth:`Vocabulary.keep_probabilities`). The reference's implementation of
    this pass is a silent no-op due to an integer-division bug (mllib:375,
    SURVEY.md §5); this is the fixed float semantics, reseeded per (epoch,
    partition) exactly like the reference reseeds ``k ^ idx`` (mllib:371-373)
    — callers pass a per-epoch ``rng``.
    """
    if ids.size == 0:
        return ids
    keep = rng.random(ids.size) <= keep_prob[ids]
    return ids[keep]


def window_batch(
    ids: np.ndarray, window: int, rng: np.random.Generator
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """All (center, padded-context, mask) rows for one sentence, vectorized.

    For each position ``i``: ``b = rng.integers(0, window)`` and context
    positions ``[max(0, i-b), min(i+b, len))`` minus ``i`` (mllib:384-388).
    Returns ``centers (L,)``, ``contexts (L, C)``, ``mask (L, C)`` with
    ``C = context_width(window)``.
    """
    L = int(ids.size)
    W = int(window)
    C = context_width(W)
    if L == 0:
        z = np.zeros((0, C), dtype=np.int32)
        return np.zeros((0,), dtype=np.int32), z, np.zeros((0, C), np.float32)
    b = rng.integers(0, W, size=L)  # [0, window)
    offsets = window_offsets(W)  # (C,)
    pos = np.arange(L)[:, None] + offsets[None, :]  # (L, 2W)
    valid = (
        (offsets[None, :] >= -b[:, None])
        & (offsets[None, :] <= b[:, None] - 1)
        & (pos >= 0)
        & (pos < L)
    )
    contexts = ids[np.clip(pos, 0, L - 1)].astype(np.int32)
    contexts = np.where(valid, contexts, 0)
    return ids.astype(np.int32), contexts, valid.astype(np.float32)


@dataclass
class Batch:
    """One fixed-shape skip-gram minibatch plus progress metadata."""

    centers: np.ndarray  # (B,) int32
    contexts: np.ndarray  # (B, C) int32, C = context_width(window)
    mask: np.ndarray  # (B, C) float32
    words_done: int  # cumulative trained-word count (drives LR anneal)


@dataclass
class BatchGroup:
    """One dispatch group: ``group_size`` minibatches stacked to the
    on-device scan's ``(K, ...)`` shape, tail-padded with zero-mask rows
    so the jitted scan never sees a second K. Produced off the training
    thread (see :func:`group_batches`) so the stacking cost overlaps
    device compute instead of serializing dispatches (ISSUE 5)."""

    centers: np.ndarray  # (K, B) int32
    contexts: np.ndarray  # (K, B, C) int32
    mask: np.ndarray  # (K, B, C) float32
    words_done: List[int]  # per-slot cumulative count (pad repeats last)
    n_real: int  # live minibatches; slots [n_real, K) are zero-mask pad

    def __len__(self) -> int:
        return int(self.centers.shape[0])


def group_batches(
    batches: Iterator[Batch], group_size: int
) -> Iterator[BatchGroup]:
    """Collect ``group_size`` minibatches at a time and stack them into
    the dispatch-ready :class:`BatchGroup` form.

    This is the per-group host assembly the fit loop used to run inline
    between dispatches; yielding it from a generator lets
    ``utils.prefetch`` move the whole thing (windowing + stacking +
    padding) onto the producer thread — a bounded depth-2 pipeline that
    keeps batch production overlapped with device execution. Each
    group's assembly is recorded as a ``batch_prefetch`` span (on the
    producer thread's tid) when observability is on."""
    from glint_word2vec_tpu.obs import events as obs_events

    K = int(group_size)
    if K <= 0:
        raise ValueError("group_size must be > 0")
    from glint_word2vec_tpu.utils import faults

    g = 0
    while True:
        # Fault seam: fires on the producer thread, so an injected
        # exception exercises the prefetch pipeline's error propagation
        # and an injected hang exercises the consumer's stall accounting.
        faults.fire("producer.batch")
        with obs_events.span("batch_prefetch", group=g):
            group: List[Batch] = []
            for batch in batches:
                group.append(batch)
                if len(group) == K:
                    break
            if not group:
                return
            n_real = len(group)
            if n_real < K:
                # Epoch-tail pad: zero-mask rows update nothing; the pad
                # slots inherit the last live words_done so the LR
                # schedule inputs stay well-defined (they are never
                # recorded — n_real excludes them).
                proto = group[0]
                pad = Batch(
                    centers=np.zeros_like(proto.centers),
                    contexts=np.zeros_like(proto.contexts),
                    mask=np.zeros_like(proto.mask),
                    words_done=group[-1].words_done,
                )
                group.extend([pad] * (K - n_real))
            out = BatchGroup(
                centers=np.stack([b.centers for b in group]),
                contexts=np.stack([b.contexts for b in group]),
                mask=np.stack([b.mask for b in group]),
                words_done=[b.words_done for b in group],
                n_real=n_real,
            )
        yield out
        g += 1


class SkipGramBatcher:
    """Streams fixed-shape minibatches from an encoded corpus.

    One instance per training run; :meth:`epoch` performs the per-iteration
    subsample + window passes (reference re-runs both every iteration with
    fresh epoch-dependent seeds, mllib:367-390) and yields :class:`Batch`es of
    exactly ``batch_size`` center positions — the final partial batch is
    zero-padded with mask 0 rows so device shapes stay static.

    ``words_done`` counts *pre-subsampling* words (original word2vec
    convention, and the reference's effective behavior since its subsampling
    is a no-op): the LR anneal in fit() divides by ``num_iterations *
    train_words_count`` (mllib:405-410), so counting kept words only would
    stall the schedule whenever subsampling discards tokens.
    """

    def __init__(
        self,
        sentences: List[np.ndarray],
        vocab: Vocabulary,
        batch_size: int,
        window: int,
        subsample_ratio: float = 0.0,
        seed: int = 1,
        shuffle: bool = False,
    ):
        if batch_size <= 0:
            raise ValueError("batch_size must be > 0")
        if window <= 0:
            raise ValueError("window must be > 0")
        self.sentences: Optional[List[np.ndarray]] = sentences
        self.vocab = vocab
        self.batch_size = int(batch_size)
        self.window = int(window)
        self.seed = int(seed)
        self.shuffle = bool(shuffle)
        self.keep_prob = vocab.keep_probabilities(subsample_ratio)
        self.words_done = 0
        # Flattened corpus view (ids, offsets) for the native epoch pass;
        # built lazily from `sentences`, or supplied directly by
        # :meth:`from_flat` (streaming ingestion, corpus/vocab.encode_file).
        self._flat: tuple | None = None

    @classmethod
    def from_flat(
        cls,
        ids: np.ndarray,
        offsets: np.ndarray,
        vocab: Vocabulary,
        *,
        batch_size: int,
        window: int,
        subsample_ratio: float = 0.0,
        seed: int = 1,
        shuffle: bool = False,
    ) -> "SkipGramBatcher":
        """Build from the flat (ids, offsets) corpus encoding without ever
        materializing per-sentence Python objects — constant ~4 bytes of
        host memory per kept word (see corpus/vocab.encode_file)."""
        b = cls(
            [], vocab, batch_size=batch_size, window=window,
            subsample_ratio=subsample_ratio, seed=seed, shuffle=shuffle,
        )
        b.sentences = None
        b._flat = (
            np.ascontiguousarray(ids, dtype=np.int32),
            np.ascontiguousarray(offsets, dtype=np.int64),
        )
        return b

    def _n_sentences(self) -> int:
        if self.sentences is not None:
            return len(self.sentences)
        return len(self._flat[1]) - 1

    def _sentence(self, i: int) -> np.ndarray:
        if self.sentences is not None:
            return self.sentences[i]
        ids, offsets = self._flat
        return ids[offsets[i] : offsets[i + 1]]

    def epoch(self, epoch_index: int) -> Iterator[Batch]:
        """Yield every minibatch of one pass over the corpus.

        Uses the native C++ epoch pass (subsample + window in one sweep,
        native/host_ops.cpp) when available; the Python path is the
        fallback and the semantic reference. The two paths draw different
        RNG streams, so batches are deterministic per (path, seed, epoch)
        but not identical across paths.
        """
        if not self.shuffle:
            native = self._epoch_native(epoch_index)
            if native is not None:
                yield from native
                return
        yield from self._epoch_python(epoch_index)

    #: Words per native-pass block: bounds host memory to ~60 bytes/word *
    #: this (≈250 MB) regardless of corpus size, while amortizing call
    #: overhead. One epoch = a sequence of native calls over sentence blocks.
    NATIVE_BLOCK_WORDS = 4_000_000

    def _epoch_native(self, epoch_index: int) -> Optional[Iterator[Batch]]:
        from glint_word2vec_tpu.native import get_lib

        if get_lib() is None:
            return None
        if self._flat is None:
            if self.sentences:
                ids = np.concatenate(self.sentences).astype(np.int32)
                lens = np.array([len(s) for s in self.sentences], np.int64)
            else:
                ids = np.zeros(0, np.int32)
                lens = np.zeros(0, np.int64)
            offsets = np.zeros(len(lens) + 1, np.int64)
            np.cumsum(lens, out=offsets[1:])
            self._flat = (ids, offsets)
        return self._native_batches(epoch_index)

    def _native_batches(self, epoch_index: int) -> Iterator[Batch]:
        from glint_word2vec_tpu.native import window_batch_epoch_native

        ids, offsets = self._flat
        kp = self.keep_prob.astype(np.float32)
        n_sent = len(offsets) - 1
        B = self.batch_size
        C = context_width(self.window)
        # Carry buffer for the partial batch spanning block boundaries.
        buf_c = np.zeros(B, np.int32)
        buf_x = np.zeros((B, C), np.int32)
        buf_m = np.zeros((B, C), np.float32)
        fill = 0

        s = 0
        block = 0
        while s < n_sent:
            # Grow the block until it holds ~NATIVE_BLOCK_WORDS words.
            e = int(
                np.searchsorted(
                    offsets, offsets[s] + self.NATIVE_BLOCK_WORDS, side="left"
                )
            )
            e = max(e, s + 1)
            e = min(e, n_sent)
            seed = int(
                np.random.SeedSequence(
                    (self.seed, epoch_index, block)
                ).generate_state(1, np.uint64)[0]
            )
            out = window_batch_epoch_native(
                ids[offsets[s] : offsets[e]],
                offsets[s : e + 1] - offsets[s],
                kp,
                self.window,
                seed,
            )
            centers, contexts, mask, words_done = out
            # Attribute the block's word count to its batches *pro rata* by
            # center positions consumed, so the LR anneal sees a smooth
            # words_done ramp. Bumping the counter once per block would hand
            # every batch the block-end count — at block size >= corpus size
            # that collapses the whole linear schedule to one alpha per
            # epoch (and the floor for the final epoch).
            wd_base = self.words_done
            block_words = int(words_done)
            self.words_done += block_words
            n = centers.shape[0]
            start = 0
            while n - start > 0:
                take = min(B - fill, n - start)
                buf_c[fill : fill + take] = centers[start : start + take]
                buf_x[fill : fill + take] = contexts[start : start + take]
                buf_m[fill : fill + take] = mask[start : start + take]
                fill += take
                start += take
                if fill == B:
                    wd = wd_base + int(round(block_words * (start / n)))
                    yield Batch(buf_c.copy(), buf_x.copy(), buf_m.copy(), wd)
                    fill = 0
            s = e
            block += 1
        if fill > 0:
            buf_c[fill:] = 0
            buf_x[fill:] = 0
            buf_m[fill:] = 0.0
            yield Batch(buf_c.copy(), buf_x.copy(), buf_m.copy(), self.words_done)

    def _epoch_python(self, epoch_index: int) -> Iterator[Batch]:
        B, W2 = self.batch_size, context_width(self.window)
        rng = np.random.default_rng((self.seed, epoch_index))
        order = np.arange(self._n_sentences())
        if self.shuffle:
            rng.shuffle(order)

        buf_c = np.zeros(B, dtype=np.int32)
        buf_x = np.zeros((B, W2), dtype=np.int32)
        buf_m = np.zeros((B, W2), dtype=np.float32)
        fill = 0
        for si in order:
            sent = self._sentence(si)
            self.words_done += int(sent.size)
            ids = subsample_sentence(sent, self.keep_prob, rng)
            c, x, m = window_batch(ids, self.window, rng)
            n = c.shape[0]
            start = 0
            while n - start > 0:
                take = min(B - fill, n - start)
                buf_c[fill : fill + take] = c[start : start + take]
                buf_x[fill : fill + take] = x[start : start + take]
                buf_m[fill : fill + take] = m[start : start + take]
                fill += take
                start += take
                if fill == B:
                    yield Batch(buf_c.copy(), buf_x.copy(), buf_m.copy(), self.words_done)
                    fill = 0
        if fill > 0:
            buf_c[fill:] = 0
            buf_x[fill:] = 0
            buf_m[fill:] = 0.0
            yield Batch(buf_c.copy(), buf_x.copy(), buf_m.copy(), self.words_done)
