"""Host-side corpus layer: vocabulary, subsampling, windowing, noise tables.

This is the layer the reference implements as Spark RDD passes
(mllib/feature/ServerSideGlintWord2Vec.scala:258-390) and never unit-tests
(SURVEY.md §4). Here it is pure NumPy, fully vectorized, and fully tested.
"""

from glint_word2vec_tpu.corpus.vocab import Vocabulary, build_vocab
from glint_word2vec_tpu.corpus.alias import AliasTable, build_unigram_alias
from glint_word2vec_tpu.corpus.batching import (
    SkipGramBatcher,
    chunk_sentences,
    encode_sentences,
    subsample_sentence,
    window_batch,
)

__all__ = [
    "Vocabulary",
    "build_vocab",
    "AliasTable",
    "build_unigram_alias",
    "SkipGramBatcher",
    "chunk_sentences",
    "encode_sentences",
    "subsample_sentence",
    "window_batch",
]
