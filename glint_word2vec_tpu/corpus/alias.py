"""Unigram noise distribution as an alias table (Vose/Walker).

Reference semantics: the Glint servers hold a shared unigram table of
``unigramTableSize`` entries (default 1e8) filled proportionally to
``count^0.75``, from which they draw the ``n`` negatives per (center, context)
pair server-side, seeded by the client (call sites mllib:351,421; SURVEY.md
§2.2 ``Word2VecArguments`` / ``dotprod``).

A discrete alias table is an *exact* O(1)-per-draw sampler for the same
distribution — it is what the quantized 1e8-entry table approximates. We keep
an optional ``table_size`` quantization mode for bit-level compatibility
studies, but default to the exact alias construction (documented divergence:
strictly more faithful to the target distribution).

The table is two dense vocab-length arrays (``prob`` float32, ``alias`` int32)
that live on-device (replicated — 8 bytes/word, 80 MB at 10M vocab) so that
negative sampling happens inside the jit-compiled train step with no
host round-trips: draw ``k ~ U[0, vocab)``, ``u ~ U[0,1)``, and pick
``k`` if ``u < prob[k]`` else ``alias[k]``.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class AliasTable:
    """Walker alias table over ``{0..n-1}`` with probabilities ``weights/sum``."""

    prob: np.ndarray  # float32 (n,)
    alias: np.ndarray  # int32 (n,)

    @property
    def size(self) -> int:
        return int(self.prob.shape[0])

    def sample(self, rng: np.random.Generator, shape) -> np.ndarray:
        """Host-side sampling (tests / non-jit paths)."""
        k = rng.integers(0, self.size, size=shape, dtype=np.int64)
        u = rng.random(size=shape)
        return np.where(u < self.prob[k], k, self.alias[k]).astype(np.int32)


def build_alias(weights: np.ndarray) -> AliasTable:
    """Construct an alias table for an arbitrary nonnegative weight vector.

    Uses the native C++ builder when available (the O(V) two-pointer loop is
    minutes of Python at 10M vocab, milliseconds in C++ — see
    native/host_ops.cpp); both produce valid alias decompositions of the
    same distribution.
    """
    w = np.asarray(weights, dtype=np.float64)
    if w.ndim != 1 or w.size == 0:
        raise ValueError("weights must be a nonempty 1-D array")
    if np.any(w < 0) or not np.all(np.isfinite(w)):
        raise ValueError("weights must be finite and nonnegative")
    total = w.sum()
    if total <= 0:
        raise ValueError("weights must sum to > 0")

    from glint_word2vec_tpu.native import alias_build_native

    native = alias_build_native(w)
    if native is not None:
        return AliasTable(prob=native[0], alias=native[1])

    n = w.size
    scaled = w * (n / total)  # mean 1.0
    prob = np.ones(n, dtype=np.float64)
    alias = np.arange(n, dtype=np.int64)

    small = [i for i in range(n) if scaled[i] < 1.0]
    large = [i for i in range(n) if scaled[i] >= 1.0]
    while small and large:
        s = small.pop()
        l = large.pop()
        prob[s] = scaled[s]
        alias[s] = l
        scaled[l] = (scaled[l] + scaled[s]) - 1.0
        if scaled[l] < 1.0:
            small.append(l)
        else:
            large.append(l)
    # Remaining entries keep prob 1.0 (numerical leftovers).
    return AliasTable(prob=prob.astype(np.float32), alias=alias.astype(np.int32))


def unigram_weights(counts: np.ndarray, power: float = 0.75) -> np.ndarray:
    """``count^power`` noise weights (word2vec standard, power 3/4)."""
    return np.power(counts.astype(np.float64), power)


def build_unigram_alias(
    counts: np.ndarray,
    power: float = 0.75,
    table_size: int | None = None,
) -> AliasTable:
    """Alias table over the unigram^power noise distribution.

    ``table_size`` (reference ``unigramTableSize``, default 1e8 at mllib:81)
    optionally quantizes each word's weight to its integer number of slots in
    a table of that size before building the alias structure — reproducing the
    reference's quantized distribution, including its dropping of words whose
    weight rounds to zero slots. Default (None) uses exact weights.
    """
    w = unigram_weights(counts, power)
    if table_size is not None:
        if table_size < counts.size:
            raise ValueError(
                f"table_size ({table_size}) must be >= vocab size ({counts.size})"
            )
        slots = np.floor(w / w.sum() * table_size)
        # Words rounding to zero slots are unsampleable in the reference's
        # quantized table; keep that behavior in this compatibility mode.
        w = slots
        if w.sum() <= 0:
            raise ValueError("table_size too small: all words quantized away")
    return build_alias(w)
