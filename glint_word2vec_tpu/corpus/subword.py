"""Character n-gram subwords (fastText-style) for the subword model family.

The reference framework is word-level only; subword buckets are the stretch
capability named in this repo's target configs (BASELINE.json: "fastText
char-ngram subword buckets — stretch sharded-matrix API beyond word-level").
Conventions follow fastText: words are wrapped in '<'/'>' boundary markers,
n-grams of length [min_n, max_n] are hashed with FNV-1a(32) into ``bucket``
slots, and a word's input representation is the mean of its own vector and
its n-gram bucket vectors. OOV words compose from buckets alone.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

FNV_OFFSET = 2166136261
FNV_PRIME = 16777619
MASK32 = 0xFFFFFFFF


def fnv1a_32(data: bytes) -> int:
    """FNV-1a 32-bit hash (the fastText n-gram hash)."""
    h = FNV_OFFSET
    for b in data:
        h ^= b
        h = (h * FNV_PRIME) & MASK32
    return h


def word_ngrams(word: str, min_n: int = 3, max_n: int = 6) -> List[str]:
    """Character n-grams of '<word>' with lengths in [min_n, max_n].

    The full wrapped token is excluded (it is represented by the word's own
    vector); a wrapped token shorter than min_n yields no n-grams.
    """
    if min_n <= 0 or max_n < min_n:
        raise ValueError("need 0 < min_n <= max_n")
    wrapped = f"<{word}>"
    L = len(wrapped)
    out = []
    # n is capped at L-1: the whole wrapped token (n == L) is excluded —
    # it is represented by the word's own vector.
    for n in range(min_n, min(max_n, L - 1) + 1):
        for i in range(L - n + 1):
            out.append(wrapped[i : i + n])
    return out


def ngram_bucket_ids(
    word: str, vocab_size: int, bucket: int, min_n: int, max_n: int
) -> List[int]:
    """Bucket-row ids (offset by vocab_size) for a word's n-grams."""
    return [
        vocab_size + (fnv1a_32(g.encode("utf-8")) % bucket)
        for g in word_ngrams(word, min_n, max_n)
    ]


def subword_group(
    word: str,
    word_id: int | None,
    vocab_size: int,
    bucket: int,
    min_n: int,
    max_n: int,
    max_subwords: int,
) -> List[int]:
    """The id group whose mean represents ``word``: the word's own row (if
    in-vocab) followed by its n-gram bucket rows, truncated to
    ``max_subwords`` (the word's own row is never truncated away)."""
    ids = [] if word_id is None else [word_id]
    ids += ngram_bucket_ids(word, vocab_size, bucket, min_n, max_n)
    return ids[:max_subwords]


def build_subword_table(
    words: Sequence[str],
    vocab_size: int,
    bucket: int,
    min_n: int = 3,
    max_n: int = 6,
    max_subwords: int = 32,
) -> Tuple[np.ndarray, np.ndarray]:
    """Precompute the (V, S) id/mask arrays mapping each vocab word to its
    subword group; used host-side to expand minibatch centers."""
    V = len(words)
    ids = np.zeros((V, max_subwords), np.int32)
    mask = np.zeros((V, max_subwords), np.float32)
    for w_id, w in enumerate(words):
        group = subword_group(
            w, w_id, vocab_size, bucket, min_n, max_n, max_subwords
        )
        ids[w_id, : len(group)] = group
        mask[w_id, : len(group)] = 1.0
    return ids, mask
