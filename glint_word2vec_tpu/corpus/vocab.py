"""Vocabulary construction with reference semantics.

Reference behavior (mllib/feature/ServerSideGlintWord2Vec.scala:258-279,
``learnVocab``): count words, drop those with count < min_count, sort by count
descending, and assign each word its frequency rank as its integer index.
``train_words_count`` is the total count of *kept* word occurrences and drives
the learning-rate annealing schedule (mllib:405-413).

The reference runs this as a Spark ``flatMap -> reduceByKey -> filter ->
collect -> sortBy`` pipeline; here it is a single vectorized pass. Ties in
counts are broken by first-seen order to keep the indexing deterministic for a
given corpus ordering (Scala's ``sortBy`` is stable, giving the same property).
"""

from __future__ import annotations

import collections
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np


@dataclass
class Vocabulary:
    """Immutable result of a vocab scan.

    Attributes:
      words: vocab words, index == frequency rank (most frequent first).
      counts: int64 occurrence counts aligned with ``words``.
      word_index: word -> index map (reference ``vocabHash``, mllib:267).
      train_words_count: total kept-word occurrences (reference
        ``trainWordsCount``, mllib:268).
    """

    words: List[str]
    counts: np.ndarray
    word_index: Dict[str, int] = field(repr=False)
    train_words_count: int

    @property
    def size(self) -> int:
        return len(self.words)

    @classmethod
    def from_sorted(
        cls, words: List[str], counts: np.ndarray,
        min_count: Optional[int] = None,
    ) -> "Vocabulary":
        """Assemble a Vocabulary from an already-sorted (count desc,
        first-seen ties) word/count listing — the single construction
        point shared by the Python and native scan paths. Raises
        ValueError on an empty vocab (the reference's minimum-viability
        check; ``min_count`` only improves the message)."""
        if not words:
            hint = f" (={min_count})" if min_count is not None else ""
            raise ValueError(
                "The vocabulary size should be > 0. "
                f"Lower min_count{hint} or supply a larger corpus."
            )
        counts = np.asarray(counts, dtype=np.int64)
        return cls(
            words=words,
            counts=counts,
            word_index={w: i for i, w in enumerate(words)},
            train_words_count=int(counts.sum()),
        )

    def __contains__(self, word: str) -> bool:
        return word in self.word_index

    def __getitem__(self, word: str) -> int:
        return self.word_index[word]

    def get(self, word: str, default=None):
        return self.word_index.get(word, default)

    def keep_probabilities(self, subsample_ratio: float) -> np.ndarray:
        """Per-word keep probability for frequency subsampling.

        The intended reference formula (mllib:371-379) is the classic word2vec
        subsampling rule: with ``f = count/total`` and ratio ``s``,

            keep(w) = (sqrt(f/s) + 1) * (s/f)        -- clipped to [0, 1]

        written in the reference as ``(sqrt(pcn/ratio) + 1) * (ratio/pcn)``
        where ``pcn = cn / trainWordsCount``. The reference computes ``pcn``
        with integer division (mllib:375) making subsampling a silent no-op
        (SURVEY.md §5 "known hazard"); this implementation uses float
        arithmetic, i.e. implements the *intended* semantics, and is unit
        tested (the reference could not be).

        A ``subsample_ratio`` of 0 disables subsampling (all-keep), matching
        the reference default path where the parameter effectively did nothing.
        """
        if subsample_ratio <= 0:
            return np.ones(self.size, dtype=np.float64)
        pcn = self.counts.astype(np.float64) / float(self.train_words_count)
        with np.errstate(divide="ignore", invalid="ignore"):
            ran = (np.sqrt(pcn / subsample_ratio) + 1.0) * (subsample_ratio / pcn)
        ran = np.where(self.counts > 0, ran, 0.0)
        return np.clip(ran, 0.0, 1.0)

    def device_keep_probabilities(self, subsample_ratio: float) -> np.ndarray:
        """:meth:`keep_probabilities` shaped for the device subsampling
        pass (ops/device_batching.subsample_keep_mask): float32, one
        entry per vocab row, indexable by the flat corpus ids. The f64
        -> f32 rounding moves each threshold by <= 6e-8 relative — far
        below the statistical resolution of any kept-fraction gate."""
        return self.keep_probabilities(subsample_ratio).astype(np.float32)

    def encode(self, sentence: Sequence[str]) -> np.ndarray:
        """Map words to indices, silently dropping OOV words.

        OOV-drop matches the reference training path (``flatMap(bcVocabHash
        .value.get)``, mllib:336) and the DataFrame transform path (ml:452).
        """
        ids = [self.word_index[w] for w in sentence if w in self.word_index]
        return np.asarray(ids, dtype=np.int32)

    def encode_strict(self, words: Sequence[str]) -> np.ndarray:
        """Map words to indices, raising on OOV.

        Matches the batched word-transform contract which throws on unseen
        words (mllib:536).
        """
        try:
            return np.asarray([self.word_index[w] for w in words], dtype=np.int32)
        except KeyError as e:
            raise KeyError(f"word {e.args[0]!r} not in vocabulary") from None


def build_vocab(
    sentences: Iterable[Sequence[str]],
    min_count: int = 5,
) -> Vocabulary:
    """Scan a corpus of tokenized sentences into a :class:`Vocabulary`.

    Reference: ``learnVocab`` (mllib:258-279). Index = frequency rank, most
    frequent word gets index 0; ties broken by first occurrence (stable sort).
    """
    counter: collections.Counter = collections.Counter()
    for sentence in sentences:
        counter.update(sentence)
    # Counter preserves insertion (first-seen) order and sort is stable, so
    # sorting by count desc alone breaks ties by first occurrence.
    items = [(w, c) for w, c in counter.items() if c >= min_count]
    items.sort(key=lambda wc: -wc[1])
    return Vocabulary.from_sorted(
        [w for w, _ in items],
        np.asarray([c for _, c in items], dtype=np.int64),
        min_count=min_count,
    )


def saved_model_vocabulary(
    model_dir: str, counts: np.ndarray, expected_rows: int
) -> Vocabulary:
    """Vocabulary for a saved model/generation directory — the cold
    load (``Word2VecModel.load``) and the serving hot-swap stage the
    same layout through this one helper: read ``words.txt``, validate
    the entry count against the matrix's queryable rows, and zero-pad
    the counts for words promoted onto extra rows (their live counts
    are trainer state, never persisted with a snapshot)."""
    import os

    with open(os.path.join(model_dir, "words.txt"), encoding="utf-8") as f:
        words = [line.rstrip("\n") for line in f if line.rstrip("\n")]
    if len(words) != expected_rows:
        raise ValueError(
            f"corrupt model dir at {model_dir}: words.txt has "
            f"{len(words)} entries, the matrix claims {expected_rows} "
            "queryable rows"
        )
    counts = np.asarray(counts, dtype=np.int64)
    if len(words) > counts.shape[0]:
        counts = np.concatenate(
            [counts, np.zeros(len(words) - counts.shape[0], np.int64)]
        )
    return Vocabulary(
        words=words,
        counts=counts[: len(words)],
        word_index={w: i for i, w in enumerate(words)},
        train_words_count=int(counts.sum()),
    )


def iter_text_file(path: str, lowercase: bool = False) -> Iterator[List[str]]:
    """Stream whitespace-tokenized sentences from a text file, one per line."""
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        for line in f:
            toks = line.lower().split() if lowercase else line.split()
            if toks:
                yield toks


def encode_file(
    path: str,
    vocab: Vocabulary,
    max_sentence_length: int = 1000,
    lowercase: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    """Streaming-encode a text file into the flat corpus representation:
    ``(ids int32[total], offsets int64[n_sentences+1])``, OOV dropped,
    sentences chunked at ``max_sentence_length`` (mllib:336,341 semantics).

    Host memory is ~4 bytes per kept word regardless of corpus size — the
    constant-factor fix for the reference's RDD-free analogue (a Python
    sentence list costs ~15x more). Pairs with
    ``SkipGramBatcher.from_flat``.
    """
    if max_sentence_length <= 0:
        raise ValueError("max_sentence_length must be > 0")
    wi = vocab.word_index
    id_blocks: List[np.ndarray] = []
    lengths: List[int] = []
    buf: List[int] = []
    BLOCK = 1 << 20
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        for line in f:
            toks = line.lower().split() if lowercase else line.split()
            ids = [wi[t] for t in toks if t in wi]
            if not ids:
                continue
            for s in range(0, len(ids), max_sentence_length):
                chunk = ids[s : s + max_sentence_length]
                lengths.append(len(chunk))
                buf.extend(chunk)
            if len(buf) >= BLOCK:
                id_blocks.append(np.asarray(buf, dtype=np.int32))
                buf = []
    if buf:
        id_blocks.append(np.asarray(buf, dtype=np.int32))
    flat = (
        np.concatenate(id_blocks) if id_blocks else np.zeros(0, np.int32)
    )
    offsets = np.zeros(len(lengths) + 1, dtype=np.int64)
    np.cumsum(np.asarray(lengths, dtype=np.int64), out=offsets[1:])
    return flat, offsets


#: Token-buffer flush threshold for the streaming scan (module-level so
#: tests can shrink it to exercise multi-block concatenation).
_STREAM_BLOCK = 1 << 20


def scan_and_encode_stream(
    sentences: Iterable[Sequence[str]],
    min_count: int = 5,
    max_sentence_length: int = 1000,
) -> Tuple[Vocabulary, np.ndarray, np.ndarray]:
    """Single-pass scan+encode for NON-REWINDABLE sentence iterables.

    ``fit(sentences)`` on a generator used to materialize the whole
    corpus as a Python list to get the two passes the reference takes
    over its RDD (vocab scan mllib:258-279, encode mllib:335-343) —
    ~15x the host memory of the flat encoding (round-4 verdict weak #7).
    This streams instead: one pass assigns provisional first-seen ids
    and counts occurrences (holding only a flat int32 token buffer +
    per-sentence lengths, ~4 bytes/word), then a vectorized remap onto
    the final frequency-ranked vocabulary drops below-``min_count``
    tokens, drops emptied sentences, and re-chunks at
    ``max_sentence_length`` — producing exactly the ``(vocab, ids,
    offsets)`` that :func:`build_vocab` + the list-path encode/chunk
    would (count-desc rank, first-seen ties, OOV drop, empty drop).
    """
    if max_sentence_length <= 0:
        raise ValueError("max_sentence_length must be > 0")
    prov: Dict[str, int] = {}
    counts_l: List[int] = []
    id_blocks: List[np.ndarray] = []
    buf: List[int] = []
    sent_lens: List[int] = []
    for sentence in sentences:
        n = 0
        for w in sentence:
            i = prov.get(w)
            if i is None:
                i = len(prov)
                prov[w] = i
                counts_l.append(1)
            else:
                counts_l[i] += 1
            buf.append(i)
            n += 1
        if n:
            sent_lens.append(n)
        if len(buf) >= _STREAM_BLOCK:
            id_blocks.append(np.asarray(buf, dtype=np.int32))
            buf = []
    if buf:
        id_blocks.append(np.asarray(buf, dtype=np.int32))
    flat = np.concatenate(id_blocks) if id_blocks else np.zeros(0, np.int32)
    counts = np.asarray(counts_l, dtype=np.int64)

    # Final ranks: count desc, ties by provisional (= first-seen) id.
    order = np.argsort(-counts, kind="stable")
    kept = order[counts[order] >= min_count]
    words_by_prov = list(prov)  # dict preserves insertion order
    vocab = Vocabulary.from_sorted(
        [words_by_prov[i] for i in kept], counts[kept], min_count=min_count
    )

    remap = np.full(len(counts_l) + 1, -1, dtype=np.int64)
    remap[kept] = np.arange(kept.size, dtype=np.int64)
    mapped = remap[flat]
    keep_mask = mapped >= 0
    ids = mapped[keep_mask].astype(np.int32)

    # Kept length per original sentence -> drop emptied, chunk the rest.
    prov_offsets = np.zeros(len(sent_lens) + 1, dtype=np.int64)
    np.cumsum(np.asarray(sent_lens, dtype=np.int64), out=prov_offsets[1:])
    kept_counts = np.add.reduceat(
        keep_mask.astype(np.int64), prov_offsets[:-1]
    ) if len(sent_lens) else np.zeros(0, np.int64)
    L = kept_counts[kept_counts > 0]
    n_chunks = (L + max_sentence_length - 1) // max_sentence_length
    lengths = np.full(int(n_chunks.sum()), max_sentence_length, np.int64)
    ends = np.cumsum(n_chunks) - 1
    lengths[ends] = L - (n_chunks - 1) * max_sentence_length
    offsets = np.zeros(lengths.size + 1, dtype=np.int64)
    np.cumsum(lengths, out=offsets[1:])
    return vocab, ids, offsets


def scan_and_encode_file(
    path: str,
    min_count: int = 5,
    max_sentence_length: int = 1000,
    lowercase: bool = False,
) -> Tuple[Vocabulary, np.ndarray, np.ndarray]:
    """Both ``fit_file`` ingestion passes — vocab scan + flat int32 encode —
    through the native C++ scanner when available (tens of MB/s on one
    core), falling back to the Python passes (:func:`build_vocab` over
    :func:`iter_text_file`, then :func:`encode_file`) otherwise.

    The native path reproduces the Python passes exactly (full
    ``str.split()`` whitespace set, universal-newline sentence
    boundaries) for valid-UTF-8 corpora, and declines — returning the
    work to the Python passes — whenever byte-level equality can't be
    guaranteed: invalid UTF-8 (``errors='replace'`` merging) or
    ``lowercase=True`` (Unicode-aware lowering). Returns
    ``(vocab, ids, offsets)``.
    """
    from glint_word2vec_tpu import native as _native

    res = _native.corpus_scan_native(
        path, min_count, max_sentence_length, lowercase=lowercase
    )
    if res is not None:
        words, counts, ids, offsets = res
        vocab = Vocabulary.from_sorted(words, counts, min_count=min_count)
        return vocab, ids, offsets
    vocab = build_vocab(
        iter_text_file(path, lowercase=lowercase), min_count=min_count
    )
    ids, offsets = encode_file(
        path, vocab, max_sentence_length=max_sentence_length,
        lowercase=lowercase,
    )
    return vocab, ids, offsets
