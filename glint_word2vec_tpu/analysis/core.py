"""graftlint core: the parsed-module cache, the finding type, per-line
suppressions, and the checker registry.

Everything here is stdlib-only (``ast`` + ``tokenize``) so the whole
pass imports in milliseconds and never pulls jax into the CI lint
runner. All checkers run from a single :class:`ModuleCache`, so each
target file is read and parsed exactly once per invocation.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

#: Suppression comment grammar: "graftlint: ignore" + [rules] + reason.
_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*ignore\[([a-zA-Z0-9_,\- ]*)\]\s*(.*)$"
)

#: The meta-rule id for malformed suppressions (empty reason, unknown or
#: empty rule list). Not a registered checker: it is emitted by the
#: runner itself and cannot be suppressed or baselined away.
SUPPRESSION_RULE = "graftlint-suppression"

#: Rule id for files that fail to parse.
PARSE_RULE = "graftlint-parse"


@dataclasses.dataclass
class Finding:
    """One rule violation at one site.

    ``context`` (the stripped source line) plus an occurrence index — not
    the line number — is the identity used for baseline matching, so
    unrelated edits that shift lines do not stale the baseline while an
    edit to the flagged line itself does.
    """

    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str
    hint: str = ""
    context: str = ""

    def identity(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.context)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def format(self) -> str:
        out = f"{self.path}:{self.line}: [{self.rule}] {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


@dataclasses.dataclass
class _Suppression:
    rules: Tuple[str, ...]
    reason: str
    line: int  # line the comment sits on


class Module:
    """One parsed target file: source, AST, and suppression map."""

    def __init__(self, root: str, rel: str, source: str):
        self.root = root
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(source, filename=rel)
        except SyntaxError as e:  # surfaced as a PARSE_RULE finding
            self.parse_error = e
        #: effective line -> suppression (a standalone comment line
        #: covers the next line; a trailing comment covers its own).
        self.suppressions: Dict[int, _Suppression] = {}
        self._scan_suppressions()
        self._imports: Optional[set] = None

    def _scan_suppressions(self) -> None:
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(self.source).readline
            )
            comments = [
                (t.start[0], t.string)
                for t in tokens
                if t.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, SyntaxError, IndentationError):
            comments = [
                (i, line[line.index("#"):])
                for i, line in enumerate(self.lines, 1)
                if "#" in line
            ]
        for lineno, text in comments:
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = tuple(
                r.strip() for r in m.group(1).split(",") if r.strip()
            )
            reason = m.group(2).strip()
            src_line = self.lines[lineno - 1] if lineno <= len(self.lines) else ""
            standalone = src_line.strip().startswith("#")
            target = lineno + 1 if standalone else lineno
            self.suppressions[target] = _Suppression(rules, reason, lineno)

    def line_at(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: str, node_or_line, message: str,
                hint: str = "") -> Finding:
        line = getattr(node_or_line, "lineno", node_or_line)
        return Finding(rule=rule, path=self.rel, line=line,
                       message=message, hint=hint,
                       context=self.line_at(line))

    def imports(self) -> set:
        """Top-level-ish set of imported module roots (``jax``, ``numpy``
        ...) — cheap taint signal for the sync-point checker."""
        if self._imports is None:
            mods = set()
            if self.tree is not None:
                for node in ast.walk(self.tree):
                    if isinstance(node, ast.Import):
                        for a in node.names:
                            mods.add(a.name.split(".")[0])
                    elif isinstance(node, ast.ImportFrom) and node.module:
                        mods.add(node.module.split(".")[0])
            self._imports = mods
        return self._imports


class ModuleCache:
    """Loads and parses each file once; shared by every checker."""

    def __init__(self, root: str, targets: Sequence[str]):
        self.root = os.path.abspath(root)
        self.targets = list(targets)
        self._modules: Dict[str, Optional[Module]] = {}

    def module(self, rel: str) -> Optional[Module]:
        """Load one repo-relative file (whether or not it is a target).
        Returns None when the file does not exist."""
        rel = rel.replace(os.sep, "/")
        if rel not in self._modules:
            path = os.path.join(self.root, rel)
            if not os.path.isfile(path):
                self._modules[rel] = None
            else:
                with open(path, "r", encoding="utf-8") as f:
                    src = f.read()
                self._modules[rel] = Module(self.root, rel, src)
        return self._modules[rel]

    def modules(self) -> Iterable[Module]:
        for rel in self.targets:
            mod = self.module(rel)
            if mod is not None:
                yield mod


# ----------------------------------------------------------------------
# Checker registry
# ----------------------------------------------------------------------

CheckerFunc = Callable[[ModuleCache], List[Finding]]


@dataclasses.dataclass
class CheckerInfo:
    rule: str
    doc: str
    func: CheckerFunc


CHECKERS: Dict[str, CheckerInfo] = {}


def checker(rule: str, doc: str) -> Callable[[CheckerFunc], CheckerFunc]:
    """Register a checker under its rule id. ``doc`` is the one-line
    catalog entry ``--list-rules`` prints."""

    def wrap(func: CheckerFunc) -> CheckerFunc:
        if rule in CHECKERS:
            raise ValueError(f"duplicate checker rule id {rule!r}")
        CHECKERS[rule] = CheckerInfo(rule, doc, func)
        return func

    return wrap


# ----------------------------------------------------------------------
# Target discovery + the runner
# ----------------------------------------------------------------------

_EXCLUDE_DIRS = {"__pycache__", ".git", "tests"}


def default_targets(root: str) -> List[str]:
    """The audited file set: the package, the scripts entry points (they
    persist JSON artifacts too), and the top-level bench driver."""
    out: List[str] = []
    for base in ("glint_word2vec_tpu", "scripts"):
        basedir = os.path.join(root, base)
        for dirpath, dirnames, filenames in os.walk(basedir):
            dirnames[:] = sorted(
                d for d in dirnames if d not in _EXCLUDE_DIRS
            )
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rel = os.path.relpath(
                        os.path.join(dirpath, fn), root
                    ).replace(os.sep, "/")
                    out.append(rel)
    if os.path.isfile(os.path.join(root, "bench.py")):
        out.append("bench.py")
    return out


def _apply_suppressions(
    findings: List[Finding], cache: ModuleCache
) -> Tuple[List[Finding], List[Finding]]:
    """Split raw findings into (kept, suppressed) per the inline
    ``# graftlint: ignore[...]`` comments, and emit meta-findings for
    malformed or unknown-rule suppressions."""
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        mod = cache.module(f.path)
        sup = mod.suppressions.get(f.line) if mod else None
        if sup and f.rule in sup.rules and sup.reason:
            suppressed.append(f)
        else:
            kept.append(f)
    # Malformed suppressions are findings in their own right, whether or
    # not they currently mask anything: an empty reason defeats the
    # audit trail, an unknown rule id is a typo that silently ignores
    # nothing.
    for mod in cache.modules():
        for target_line, sup in sorted(mod.suppressions.items()):
            if not sup.reason:
                kept.append(mod.finding(
                    SUPPRESSION_RULE, sup.line,
                    "suppression without a reason",
                    hint="write `# graftlint: ignore[rule] <why this "
                         "site is exempt>` — the reason is mandatory",
                ))
            for r in sup.rules:
                if r not in CHECKERS and r != SUPPRESSION_RULE:
                    kept.append(mod.finding(
                        SUPPRESSION_RULE, sup.line,
                        f"suppression names unknown rule {r!r}",
                        hint="see --list-rules for the catalog",
                    ))
            if not sup.rules:
                kept.append(mod.finding(
                    SUPPRESSION_RULE, sup.line,
                    "suppression with an empty rule list",
                    hint="name the rule(s): ignore[rule-a,rule-b] reason",
                ))
    return kept, suppressed


def run_analysis(
    root: str,
    targets: Optional[Sequence[str]] = None,
    rules: Optional[Sequence[str]] = None,
) -> Tuple[List[Finding], List[Finding]]:
    """Run every registered checker (or ``rules``) over ``targets``
    (default: :func:`default_targets`). Returns ``(findings,
    suppressed)`` both sorted by (path, line, rule)."""
    # Import for side effect: registers the built-in checkers exactly
    # once, without a hard import cycle at module load.
    from glint_word2vec_tpu.analysis import checkers as _  # noqa: F401

    if targets is None:
        targets = default_targets(root)
    cache = ModuleCache(root, targets)
    raw: List[Finding] = []
    for mod in cache.modules():
        if mod.parse_error is not None:
            raw.append(mod.finding(
                PARSE_RULE, mod.parse_error.lineno or 1,
                f"file does not parse: {mod.parse_error.msg}",
            ))
    active = rules if rules is not None else sorted(CHECKERS)
    for rule in active:
        if rule not in CHECKERS:
            raise ValueError(
                f"unknown rule {rule!r} (valid: {', '.join(sorted(CHECKERS))})"
            )
        raw.extend(CHECKERS[rule].func(cache))
    kept, suppressed = _apply_suppressions(raw, cache)
    key = lambda f: (f.path, f.line, f.rule, f.message)  # noqa: E731
    return sorted(kept, key=key), sorted(suppressed, key=key)
