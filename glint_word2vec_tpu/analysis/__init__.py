"""graftlint — AST-based invariant checkers for the engine's hand-enforced
contracts.

PRs 2-8 built a trainer/server whose correctness rests on conventions no
tool checked: host<->device syncs only in blessed harvest seams, every
persisted artifact through ``utils.atomic_write_*``, every table mutation
ticking ``table_version``, fault-point names in one registry, the two
Prometheus renderers consistent with the snapshots that feed them, and
shared mutable state accessed under its owning lock. Each of those has
already cost a PR to get right once; this package mechanizes them as a
jax-free analysis pass gating CI.

Usage::

    python -m glint_word2vec_tpu.analysis                  # report findings
    python -m glint_word2vec_tpu.analysis --check-baseline # CI gate
    python -m glint_word2vec_tpu.analysis --update-baseline

The package imports nothing heavier than ``ast`` — no jax, no numpy — so
the CI lint job runs on a bare Python in seconds.

Per-line suppression::

    something_flagged()  # graftlint: ignore[rule-id] reason it is fine

The reason is mandatory; a bare suppression is itself reported (rule
``graftlint-suppression``). The committed ``baseline.json`` holds the
audited-and-accepted findings so the CI gate is zero-NEW-findings, not
zero-findings; every baseline entry carries a non-empty ``note``.
"""

from glint_word2vec_tpu.analysis.core import (  # noqa: F401
    CHECKERS,
    Finding,
    ModuleCache,
    checker,
    default_targets,
    run_analysis,
)
from glint_word2vec_tpu.analysis.baseline import (  # noqa: F401
    compare_to_baseline,
    load_baseline,
    write_baseline,
)
