"""``python -m glint_word2vec_tpu.analysis`` — the graftlint CLI.

Exit codes: 0 clean (or baseline-matched with ``--check-baseline``),
1 findings / gate failure, 2 usage error. Imports no jax and no numpy;
the CI lint job runs it on a bare interpreter.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from glint_word2vec_tpu.analysis import core
from glint_word2vec_tpu.analysis import baseline as bl


def _repo_root() -> str:
    # analysis/ lives at <root>/glint_word2vec_tpu/analysis.
    return os.path.abspath(
        os.path.join(os.path.dirname(__file__), os.pardir, os.pardir)
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m glint_word2vec_tpu.analysis",
        description="graftlint: AST-based invariant checkers for the "
                    "engine's hand-enforced contracts.",
    )
    ap.add_argument("paths", nargs="*",
                    help="repo-relative files to analyze (default: the "
                         "package + scripts/ + bench.py)")
    ap.add_argument("--root", default=_repo_root(),
                    help="repo root (default: inferred from the package)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as a JSON document")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline path (default: <root>/{bl.BASELINE_REL})")
    ap.add_argument("--check-baseline", action="store_true",
                    help="CI gate: fail on NEW findings, on stale "
                         "baseline entries, and on noteless entries")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings, "
                         "preserving notes of entries that still match")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    # Registers the checkers (side-effect import, kept out of module
    # scope so --help stays instant).
    from glint_word2vec_tpu.analysis import checkers as _  # noqa: F401

    if args.list_rules:
        for rule in sorted(core.CHECKERS):
            print(f"{rule:22s} {core.CHECKERS[rule].doc}")
        return 0

    root = os.path.abspath(args.root)
    # Normalize CLI paths ("./x", absolute, backslashes) onto the
    # repo-relative posix form every checker and the baseline key on —
    # an unnormalized prefix would silently skip checks scoped by path.
    targets = []
    for p in args.paths:
        if not os.path.isabs(p) and \
                os.path.exists(os.path.join(root, os.path.normpath(p))):
            # Repo-relative (works from any cwd, "./" and all).
            rel = os.path.normpath(p)
        else:
            rel = os.path.relpath(os.path.abspath(p), root)
        if rel.startswith(os.pardir):
            print(f"error: path {p!r} is outside --root {root}",
                  file=sys.stderr)
            return 2
        targets.append(rel.replace(os.sep, "/"))
    targets = targets or None
    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    t0 = time.time()
    try:
        findings, suppressed = core.run_analysis(root, targets, rules)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    elapsed = time.time() - t0

    baseline_path = args.baseline or os.path.join(root, bl.BASELINE_REL)

    if args.update_baseline:
        old = bl.load_baseline(baseline_path)
        # Partial scope (explicit paths / --rules): only rewrite the
        # entries the current findings can speak for; everything out of
        # scope is preserved verbatim, notes included.
        tset = set(targets) if targets is not None else None
        rset = set(rules) if rules is not None else None

        def in_scope(e):
            return (tset is None or e["path"] in tset) and \
                   (rset is None or e["rule"] in rset)

        preserved = [e for e in old if not in_scope(e)]
        entries = bl.write_baseline(
            baseline_path, findings,
            [e for e in old if in_scope(e)], preserved,
        )
        empty = sum(1 for e in entries if not e["note"].strip())
        print(f"baseline: wrote {len(entries)} entries to "
              f"{os.path.relpath(baseline_path, root)}"
              + (f" ({empty} need a note before --check-baseline passes)"
                 if empty else ""))
        return 0

    if args.check_baseline:
        entries = bl.load_baseline(baseline_path)
        if targets is not None:
            # Partial run: entries for files outside the analyzed set
            # would all read as stale — only judge what was analyzed.
            analyzed = set(targets)
            entries = [e for e in entries if e["path"] in analyzed]
        if rules is not None:
            active = set(rules)
            entries = [e for e in entries if e["rule"] in active]
        new, stale, noteless = bl.compare_to_baseline(findings, entries)
        if args.as_json:
            print(json.dumps({
                "new": [f.to_dict() for f in new],
                "stale": stale, "noteless": noteless,
                "baselined": len(entries), "elapsed_seconds": elapsed,
            }, indent=1))
        else:
            for f in new:
                print(f.format())
            for e in stale:
                print(f"{e['path']}: [{e['rule']}] STALE baseline entry "
                      f"no longer matches any site: {e['context']!r}")
            for e in noteless:
                print(f"{e['path']}: [{e['rule']}] baseline entry has no "
                      f"note: {e['context']!r}")
            print(f"graftlint: {len(findings)} findings "
                  f"({len(entries)} baselined, {len(suppressed)} "
                  f"suppressed inline), {len(new)} new, {len(stale)} "
                  f"stale, {len(noteless)} noteless "
                  f"[{elapsed:.2f}s]")
        ok = not new and not stale and not noteless
        if not ok:
            print("graftlint: FAIL — fix the new findings, or audit "
                  "them into the baseline with --update-baseline and a "
                  "note per entry.", file=sys.stderr)
        return 0 if ok else 1

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "suppressed": len(suppressed),
            "elapsed_seconds": elapsed,
        }, indent=1))
    else:
        for f in findings:
            print(f.format())
        print(f"graftlint: {len(findings)} findings, "
              f"{len(suppressed)} suppressed inline [{elapsed:.2f}s]")
    return 0 if not findings else 1


if __name__ == "__main__":
    sys.exit(main())
