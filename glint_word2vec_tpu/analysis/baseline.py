"""Baseline bookkeeping: the committed record of audited-and-accepted
findings, so the CI gate is zero-NEW-findings rather than zero-findings.

Identity is ``(rule, path, context)`` plus an occurrence index — the
stripped source line, not the line number — so unrelated edits that
shift a file do not stale the baseline, while editing a flagged line
itself (or fixing it) does. Every entry must carry a non-empty ``note``
naming why the finding is accepted; ``--check-baseline`` fails on a
noteless entry just as it fails on a new finding.
"""

from __future__ import annotations

import collections
import json
import os
from typing import Dict, List, Sequence, Tuple

from glint_word2vec_tpu.analysis.core import (
    PARSE_RULE,
    SUPPRESSION_RULE,
    Finding,
)

#: Repo-relative path of the committed baseline.
BASELINE_REL = "glint_word2vec_tpu/analysis/baseline.json"

#: Meta-rules that can NEVER be baselined: a malformed suppression or an
#: unparseable file must be fixed, not accepted — otherwise the
#: mandatory-reason audit trail launders itself through the baseline.
UNBASELINEABLE = frozenset({SUPPRESSION_RULE, PARSE_RULE})

_SCHEMA = 1


def load_baseline(path: str) -> List[dict]:
    if not os.path.isfile(path):
        return []
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("schema") != _SCHEMA:
        raise ValueError(
            f"baseline {path}: unknown schema {doc.get('schema')!r}"
        )
    return list(doc.get("findings", []))


def write_baseline(path: str, findings: Sequence[Finding],
                   old_entries: Sequence[dict] = (),
                   preserved: Sequence[dict] = ()) -> List[dict]:
    """Serialize ``findings`` as the new baseline, carrying ``note``
    fields over from matching old entries (new entries get an empty note
    the check step will then demand be filled in). ``preserved`` entries
    are kept verbatim — the out-of-scope remainder of a partial
    (explicit-paths / ``--rules``) update, which the current findings
    say nothing about."""
    notes: Dict[Tuple[str, str, str], List[str]] = collections.defaultdict(list)
    for e in old_entries:
        notes[(e["rule"], e["path"], e["context"])].append(e.get("note", ""))
    entries = [dict(e) for e in preserved
               if e["rule"] not in UNBASELINEABLE]
    findings = [f for f in findings if f.rule not in UNBASELINEABLE]
    for f in findings:
        pool = notes.get(f.identity())
        note = pool.pop(0) if pool else ""
        entries.append({
            "rule": f.rule, "path": f.path, "line": f.line,
            "context": f.context, "note": note,
        })
    entries.sort(key=lambda e: (e["path"], e["line"], e["rule"],
                                e["context"]))
    doc = {"schema": _SCHEMA, "findings": entries}
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)
    return entries


def compare_to_baseline(
    findings: Sequence[Finding], entries: Sequence[dict]
) -> Tuple[List[Finding], List[dict], List[dict]]:
    """Match current findings against baseline entries by identity +
    occurrence index. Returns ``(new, stale, noteless)``:

    - ``new``: findings with no baseline entry — the gate's primary
      failure (someone broke an invariant).
    - ``stale``: entries that no longer match any site — the audited
      violation was fixed (or the line edited), so the entry must be
      dropped via ``--update-baseline`` to keep the record honest.
    - ``noteless``: matched entries whose ``note`` is empty — accepted
      findings must carry their justification in-repo.
    """
    by_id: Dict[Tuple[str, str, str], List[dict]] = collections.defaultdict(list)
    for e in entries:
        # An unbaselineable entry (hand-edited in) is treated as stale so
        # the gate forces it back out of the file.
        if e["rule"] not in UNBASELINEABLE:
            by_id[(e["rule"], e["path"], e["context"])].append(e)
    new: List[Finding] = []
    noteless: List[dict] = []
    for f in findings:
        pool = (by_id.get(f.identity())
                if f.rule not in UNBASELINEABLE else None)
        if pool:
            e = pool.pop(0)
            if not e.get("note", "").strip():
                noteless.append(e)
        else:
            new.append(f)
    stale = [e for pool in by_id.values() for e in pool]
    stale.extend(e for e in entries if e["rule"] in UNBASELINEABLE)
    return new, stale, noteless
