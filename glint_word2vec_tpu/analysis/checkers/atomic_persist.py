"""atomic-persist: every persisted artifact goes through an atomic
write — ``utils.atomic_write_*`` or a temp-dir + ``os.replace`` commit.

PR 5/7 made every run/checkpoint artifact crash-safe (a SIGKILL
mid-write leaves the previous complete file, never a torn one); a bare
``open(path, "w") + json.dump`` anywhere on a persistence path silently
reintroduces torn checkpoints. The rule flags write-mode ``open()``,
``np.save``/``np.savez``/``np.savetxt``, ``pickle.dump``, and
``Path.write_text/_bytes`` — UNLESS the enclosing function itself calls
``os.replace``/``os.rename`` (it is implementing the atomic commit
protocol: the ``utils`` helpers, the engine's temp-dir snapshot writer)
or appends (``"a"`` modes: JSONL event sinks are append-only by
design).

Sites that are genuinely fine non-atomic (process-private temp files,
debug dumps) carry an inline ``# graftlint: ignore[atomic-persist]
<why>``.

Granularity note: the bless is function-level — a function that calls
``os.replace`` owns ALL its raw writes (they are assumed to be the
temp-side of its commit). A bare write smuggled into an existing
committing function is therefore invisible to this rule; the guarded
boundary is new code paths, which start life without a commit protocol
and get flagged until they grow one.
"""

from __future__ import annotations

import ast
from typing import List, Set

from glint_word2vec_tpu.analysis.core import Finding, ModuleCache, checker
from glint_word2vec_tpu.analysis.checkers.common import (
    call_name,
    const_str,
    enclosing_map,
    walk_functions,
)

RULE = "atomic-persist"

#: Dotted call names that persist bytes to a path-like destination.
_PERSIST_CALLS = {
    "np.save", "numpy.save", "np.savez", "numpy.savez",
    "np.savez_compressed", "numpy.savez_compressed",
    "np.savetxt", "numpy.savetxt",
    "pickle.dump",
}

def _open_write_mode(node: ast.Call) -> bool:
    """True for ``open(path, "w"/"wb"/"x"...)`` — not append, not
    read."""
    if call_name(node) != "open":
        return False
    mode = None
    if len(node.args) >= 2:
        mode = const_str(node.args[1])
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = const_str(kw.value)
    if mode is None:
        return False
    return mode.startswith(("w", "x"))


def _commits_atomically(fn: ast.AST) -> bool:
    """Does this function itself perform the atomic commit (os.replace /
    os.rename)? If so, its raw writes ARE the protocol's temp side, not
    a violation (see the module docstring's granularity note)."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = call_name(node) or ""
            # Match through import aliases (`import os as _os`) without
            # catching str.replace(): the receiver must be the os
            # module under its conventional names.
            root, _, tail = name.rpartition(".")
            if root in ("os", "_os", "os.path") and \
                    tail in ("replace", "rename", "renames"):
                return True
    return False


@checker(RULE,
         "persisted artifacts must go through utils.atomic_write_* or a "
         "temp-dir + os.replace commit (append-only sinks exempt)")
def check_atomic_persist(cache: ModuleCache) -> List[Finding]:
    findings: List[Finding] = []
    for mod in cache.modules():
        if mod.tree is None:
            continue
        # Functions that implement the commit protocol themselves.
        atomic_fns: Set[str] = {
            qn for qn, fn in walk_functions(mod.tree)
            if _commits_atomically(fn)
        }
        # A nested function inherits its parent's blessing: the engine
        # snapshot writer builds per-file closures inside the committing
        # function.
        enclosing = enclosing_map(mod.tree)

        def blessed(node: ast.AST) -> bool:
            qn = enclosing.get(id(node), "")
            while True:
                if qn in atomic_fns:
                    return True
                if "." not in qn:
                    return False
                qn = qn.rsplit(".", 1)[0]

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node) or ""
            if _open_write_mode(node):
                if blessed(node):
                    continue
                findings.append(mod.finding(
                    RULE, node,
                    "bare write-mode open() outside an atomic commit "
                    "protocol",
                    hint="route through utils.atomic_write_json/"
                         "_text/_npy, or write into a temp path and "
                         "os.replace() it in this function",
                ))
            elif name in _PERSIST_CALLS:
                # np.save(f, arr) into an open handle is governed by the
                # open() that produced the handle; only flag path-like
                # first arguments (string constants, joins, f-strings,
                # names — everything except an obvious handle is
                # indistinguishable statically, so flag unless blessed).
                if blessed(node):
                    continue
                findings.append(mod.finding(
                    RULE, node,
                    f"{name}() persists outside an atomic commit "
                    f"protocol",
                    hint="use utils.atomic_write_npy / atomic_write_json "
                         "or confine to a temp dir committed by one "
                         "os.replace()",
                ))
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("write_text", "write_bytes"):
                if blessed(node):
                    continue
                findings.append(mod.finding(
                    RULE, node,
                    f"Path.{node.func.attr}() persists outside an "
                    f"atomic commit protocol",
                    hint="use utils.atomic_write_text or temp + "
                         "os.replace()",
                ))
    return findings
