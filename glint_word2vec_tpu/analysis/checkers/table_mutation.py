"""table-tick: no assignment to an engine table buffer outside a method
that ticks ``table_version``.

The serving result cache, the norms cache, and every downstream
``table_version`` consumer (hot-swap, ANN rebuild plans in the ROADMAP)
assume that EVERY mutation of ``syn0``/``syn1`` goes through
``EmbeddingEngine._tick_tables``. A stray ``self.syn0 = ...`` in a new
train path would silently serve stale cached results — the exact bug
class PR 2 fixed once by centralizing the tick. The rule: inside any
class that defines ``_tick_tables``, a method assigning a table buffer
attribute must itself call ``self._tick_tables(...)`` (``__init__`` and
the tick helper are exempt: construction precedes any reader).
"""

from __future__ import annotations

import ast
from typing import List

from glint_word2vec_tpu.analysis.core import Finding, ModuleCache, checker
from glint_word2vec_tpu.analysis.checkers.common import (
    assign_target_attrs,
    call_name,
    is_self_attr,
)

RULE = "table-tick"

#: The device-resident table buffers the serving caches key on.
TABLE_ATTRS = ("syn0", "syn1")

#: Methods allowed to assign tables without ticking: construction runs
#: before any reader exists, and the tick helper is the seam itself.
EXEMPT_METHODS = ("__init__", "_tick_tables")


@checker(RULE,
         "assignments to engine table buffers (syn0/syn1) must live in "
         "methods that call self._tick_tables(...)")
def check_table_mutation(cache: ModuleCache) -> List[Finding]:
    findings: List[Finding] = []
    for mod in cache.modules():
        if mod.tree is None:
            continue
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            methods = [n for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
            if not any(m.name == "_tick_tables" for m in methods):
                continue
            for m in methods:
                if m.name in EXEMPT_METHODS:
                    continue
                ticks = any(
                    isinstance(n, ast.Call)
                    and call_name(n) == "self._tick_tables"
                    for n in ast.walk(m)
                )
                if ticks:
                    continue
                for stmt in ast.walk(m):
                    for target in assign_target_attrs(stmt):
                        if is_self_attr(target) and \
                                target.attr in TABLE_ATTRS:
                            findings.append(mod.finding(
                                RULE, stmt,
                                f"{cls.name}.{m.name} assigns table "
                                f"buffer self.{target.attr} without "
                                f"calling self._tick_tables(...)",
                                hint="tick the version (invalidates "
                                     "norms + serving caches) or route "
                                     "the mutation through a ticking "
                                     "method",
                            ))
    return findings
