"""fault-point: every ``faults.fire("name")`` literal must name a point
declared in the ``POINTS`` registry of ``utils/faults.py`` — and every
declared point must have at least one live call site.

Before PR 9 the five point names existed only as string literals at the
call sites, so a typo'd name armed a fault that never fired and a
renamed point silently orphaned its tests. The registry (name ->
docstring) is the single source of truth; ``arm()`` validates specs
against it at runtime and this checker closes the static side: call
sites, registry, and the README fault-injection table can no longer
drift apart.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from glint_word2vec_tpu.analysis.core import (
    Finding,
    ModuleCache,
    checker,
    default_targets,
)
from glint_word2vec_tpu.analysis.checkers.common import call_name, const_str

FAULTS_REL = "glint_word2vec_tpu/utils/faults.py"

RULE = "fault-point"


def declared_points(cache: ModuleCache) -> Optional[Dict[str, int]]:
    """Extract the POINTS registry statically: name -> declaration line.
    Supports the dict (name -> docstring) form; returns None when the
    registry cannot be found or is not statically evaluable."""
    mod = cache.module(FAULTS_REL)
    if mod is None or mod.tree is None:
        return None
    for node in mod.tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        if not any(isinstance(t, ast.Name) and t.id == "POINTS"
                   for t in targets):
            continue
        value = node.value
        if isinstance(value, ast.Dict):
            out = {}
            for k in value.keys:
                s = const_str(k)
                if s is None:
                    return None
                out[s] = k.lineno
            return out
        if isinstance(value, (ast.Tuple, ast.List)):
            out = {}
            for e in value.elts:
                s = const_str(e)
                if s is None:
                    return None
                out[s] = e.lineno
            return out
    return None


@checker(RULE,
         "faults.fire(...) literals and the utils/faults.py POINTS "
         "registry must match exactly, in both directions")
def check_fault_points(cache: ModuleCache) -> List[Finding]:
    findings: List[Finding] = []
    points = declared_points(cache)
    faults_mod = cache.module(FAULTS_REL)
    if points is None:
        if faults_mod is not None:
            findings.append(faults_mod.finding(
                RULE, 1,
                "POINTS registry missing or not statically evaluable "
                "in utils/faults.py",
                hint="declare POINTS = {\"name\": \"docstring\", ...} "
                     "with literal keys",
            ))
        return findings

    fired: Dict[str, int] = {}  # name -> count of call sites
    for mod in cache.modules():
        if mod.tree is None or mod.rel == FAULTS_REL:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None or not (name == "faults.fire"
                                    or name.endswith(".faults.fire")):
                continue
            if not node.args:
                continue
            point = const_str(node.args[0])
            if point is None:
                findings.append(mod.finding(
                    RULE, node,
                    "faults.fire() argument must be a string literal so "
                    "the point name is statically checkable",
                    hint="pass the point name directly, not through a "
                         "variable",
                ))
                continue
            fired[point] = fired.get(point, 0) + 1
            if point not in points:
                findings.append(mod.finding(
                    RULE, node,
                    f"faults.fire({point!r}) names an undeclared "
                    f"injection point",
                    hint="declare it in utils/faults.py POINTS (with a "
                         "docstring) or fix the typo; valid: "
                         + ", ".join(sorted(points)),
                ))
    # The declared-but-never-fired direction is only meaningful over the
    # full target set: a partial run (explicit CLI paths) cannot see the
    # other files' call sites.
    full_run = set(default_targets(cache.root)) <= set(cache.targets)
    if not full_run:
        return findings
    for point, line in sorted(points.items()):
        if point not in fired and faults_mod is not None:
            findings.append(faults_mod.finding(
                RULE, line,
                f"declared injection point {point!r} has no "
                f"faults.fire() call site in the analysis target set",
                hint="wire the point in, or drop it from POINTS (and "
                     "the README table)",
            ))
    return findings
