"""lock-discipline: shared mutable attributes are accessed under their
owning lock, or declared atomic on purpose.

Ten modules run writer/producer/heartbeat/supervisor threads against
state the request/fit thread also touches. The convention since PR 3 is
one ``threading.Lock`` per class guarding its mutable attributes; a new
access added outside the ``with self._mu:`` block is a data race that
no test reliably catches (CPython happens to make many of them benign —
until the attribute becomes a compound update). This is a *static race
heuristic* via guarded-by inference:

- a class owns locks (attributes assigned ``threading.Lock()`` /
  ``RLock()`` / ``Condition()`` / ``_TrackedLock()``);
- for each plain data attribute, if SOME access runs under a ``with
  self.<lock>:`` block AND the attribute is written outside
  ``__init__``, then EVERY access outside ``__init__`` must either hold
  the lock or the attribute must be listed in the class-level
  ``_ATOMIC_ATTRS`` allowlist (a set of attribute names whose
  lock-free access is deliberate: monotonic counters read for
  telemetry, thread handles touched only by the owning thread, ...).

``__init__`` is exempt (construction precedes thread start), a method
named ``*_locked`` is treated as running with the lock held (the
guarded-by-caller naming convention this rule also canonizes), and a
nested function body does NOT inherit an enclosing ``with`` (the thread
target defined inside ``start()`` runs after the lock is released).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from glint_word2vec_tpu.analysis.core import Finding, ModuleCache, checker
from glint_word2vec_tpu.analysis.checkers.common import (
    call_name,
    is_self_attr,
    literal_str_collection,
)

RULE = "lock-discipline"

_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition", "_TrackedLock",
}

#: (attr, method, line, is_store, under_lock)
_Access = Tuple[str, str, int, bool, bool]


def _class_lock_attrs(cls: ast.ClassDef) -> Set[str]:
    locks: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            name = call_name(node.value)
            if name in _LOCK_CTORS:
                for t in node.targets:
                    if is_self_attr(t):
                        locks.add(t.attr)
    return locks


def _atomic_attrs(cls: ast.ClassDef) -> Set[str]:
    for node in cls.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        if any(isinstance(t, ast.Name) and t.id == "_ATOMIC_ATTRS"
               for t in targets):
            vals = literal_str_collection(node.value)
            if vals is not None:
                return set(vals)
    return set()


def _data_attrs(cls: ast.ClassDef, locks: Set[str],
                methods: Set[str]) -> Set[str]:
    """Attributes ever assigned on self, minus locks and methods."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            stack = list(targets)
            while stack:
                t = stack.pop()
                if isinstance(t, (ast.Tuple, ast.List)):
                    stack.extend(t.elts)
                elif is_self_attr(t):
                    out.add(t.attr)
    return out - locks - methods


def _collect_accesses(method: ast.AST, method_name: str,
                      locks: Set[str]) -> List[_Access]:
    accesses: List[_Access] = []

    def rec(node: ast.AST, under: bool) -> None:
        if isinstance(node, ast.With):
            holds = under or any(
                is_self_attr(item.context_expr) and
                item.context_expr.attr in locks
                for item in node.items
            )
            for item in node.items:
                rec(item.context_expr, under)
            for stmt in node.body:
                rec(stmt, holds)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                node is not method:
            # A nested def runs later, on its own thread, without the
            # lexically-enclosing lock.
            for stmt in node.body:
                rec(stmt, False)
            return
        if isinstance(node, ast.Attribute) and is_self_attr(node):
            is_store = isinstance(node.ctx, (ast.Store, ast.Del)) or \
                False
            accesses.append(
                (node.attr, method_name, node.lineno, is_store, under)
            )
        elif isinstance(node, ast.AugAssign) and is_self_attr(node.target):
            accesses.append(
                (node.target.attr, method_name, node.target.lineno,
                 True, under)
            )
        for child in ast.iter_child_nodes(node):
            rec(child, under)
        return

    # The guarded-by-caller convention: a method named *_locked is
    # specified to be called with the lock already held.
    held_on_entry = method_name.endswith("_locked")
    for stmt in ast.iter_child_nodes(method):
        rec(stmt, held_on_entry)
    return accesses


@checker(RULE,
         "attributes guarded by a lock somewhere must be accessed "
         "under it everywhere (or declared in _ATOMIC_ATTRS)")
def check_lock_discipline(cache: ModuleCache) -> List[Finding]:
    findings: List[Finding] = []
    for mod in cache.modules():
        if mod.tree is None:
            continue
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            locks = _class_lock_attrs(cls)
            if not locks:
                continue
            methods = [n for n in ast.walk(cls)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
            method_names = {m.name for m in methods}
            atomic = _atomic_attrs(cls)
            data = _data_attrs(cls, locks, method_names)
            accesses: List[_Access] = []
            for m in [n for n in cls.body
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))]:
                accesses.extend(_collect_accesses(m, m.name, locks))
            by_attr: Dict[str, List[_Access]] = {}
            for a in accesses:
                if a[0] in data and a[0] not in atomic:
                    by_attr.setdefault(a[0], []).append(a)
            for attr, accs in sorted(by_attr.items()):
                locked_any = any(a[4] for a in accs)
                written_live = any(
                    a[3] and a[1] != "__init__" for a in accs
                )
                if not (locked_any and written_live):
                    continue
                seen_lines: Set[int] = set()
                for _, meth, line, _, under in accs:
                    if under or meth == "__init__" or line in seen_lines:
                        continue
                    seen_lines.add(line)
                    findings.append(mod.finding(
                        RULE, line,
                        f"{cls.name}.{meth} accesses self.{attr} "
                        f"without holding the owning lock "
                        f"({', '.join(sorted('self.' + lk for lk in locks))}) "
                        f"that guards it elsewhere",
                        hint="wrap the access in `with self.<lock>:`, "
                             "or declare the attribute in "
                             f"{cls.name}._ATOMIC_ATTRS with a comment "
                             "saying why lock-free access is safe",
                    ))
    return findings
