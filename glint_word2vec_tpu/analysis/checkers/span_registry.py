"""span-registry: every request-path phase span literal must name an
entry in the ``REQUEST_SPANS`` registry of ``obs/events.py`` — and every
registered span must have at least one live call site.

The end-to-end trace (ISSUE 18) is only stitchable because the balancer,
the replica request threads, and the coalescer leader all tag their
phases with the SAME eight names; ``scripts/trace_summarize.py`` and the
Perfetto track grouping key on them. A typo'd name at one hop would
silently drop that phase from every per-span latency rollup. The
registry (name -> docstring) is the single source of truth; this checker
closes the static side exactly like the fault-point rule does for
``GLINT_FAULTS``: call sites (``tr.phase(...)``, ``tr.add_phase(...)``,
``obs_events.phase_span(...)``), registry, and the README span table can
no longer drift apart.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from glint_word2vec_tpu.analysis.core import (
    Finding,
    ModuleCache,
    checker,
    default_targets,
)
from glint_word2vec_tpu.analysis.checkers.common import call_name, const_str

EVENTS_REL = "glint_word2vec_tpu/obs/events.py"

RULE = "span-registry"


def declared_spans(cache: ModuleCache) -> Optional[Dict[str, int]]:
    """Extract the REQUEST_SPANS registry statically: name ->
    declaration line. Supports the dict (name -> docstring) form;
    returns None when the registry cannot be found or is not statically
    evaluable."""
    mod = cache.module(EVENTS_REL)
    if mod is None or mod.tree is None:
        return None
    for node in mod.tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        if not any(isinstance(t, ast.Name) and t.id == "REQUEST_SPANS"
                   for t in targets):
            continue
        value = node.value
        if isinstance(value, ast.Dict):
            out = {}
            for k in value.keys:
                s = const_str(k)
                if s is None:
                    return None
                out[s] = k.lineno
            return out
    return None


def _is_phase_call(name: str) -> bool:
    """True for ``<trace>.phase(...)``, ``<trace>.add_phase(...)`` and
    ``[obs_events.]phase_span(...)`` call shapes."""
    leaf = name.rsplit(".", 1)[-1]
    return leaf in ("phase", "add_phase", "phase_span")


@checker(RULE,
         "request-path span literals and the obs/events.py "
         "REQUEST_SPANS registry must match exactly, in both directions")
def check_span_registry(cache: ModuleCache) -> List[Finding]:
    findings: List[Finding] = []
    spans = declared_spans(cache)
    events_mod = cache.module(EVENTS_REL)
    if spans is None:
        if events_mod is not None:
            findings.append(events_mod.finding(
                RULE, 1,
                "REQUEST_SPANS registry missing or not statically "
                "evaluable in obs/events.py",
                hint="declare REQUEST_SPANS = {\"req.x\": \"docstring\", "
                     "...} with literal keys",
            ))
        return findings

    used: Dict[str, int] = {}  # name -> count of call sites
    for mod in cache.modules():
        # events.py itself defines phase()/add_phase()/phase_span() and
        # documents the registry — its own bodies are not call sites.
        if mod.tree is None or mod.rel == EVENTS_REL:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None or not _is_phase_call(name):
                continue
            if not node.args:
                continue
            span = const_str(node.args[0])
            if span is None:
                findings.append(mod.finding(
                    RULE, node,
                    "phase span name must be a string literal so the "
                    "registry membership is statically checkable",
                    hint="pass the REQUEST_SPANS key directly, not "
                         "through a variable",
                ))
                continue
            used[span] = used.get(span, 0) + 1
            if span not in spans:
                findings.append(mod.finding(
                    RULE, node,
                    f"phase span {span!r} is not a REQUEST_SPANS "
                    f"registry entry",
                    hint="add it to obs/events.py REQUEST_SPANS (with a "
                         "docstring) or fix the typo; valid: "
                         + ", ".join(sorted(spans)),
                ))
    # The registered-but-never-recorded direction is only meaningful
    # over the full target set: a partial run (explicit CLI paths)
    # cannot see the other files' call sites.
    full_run = set(default_targets(cache.root)) <= set(cache.targets)
    if not full_run:
        return findings
    for span, line in sorted(spans.items()):
        if span not in used and events_mod is not None:
            findings.append(events_mod.finding(
                RULE, line,
                f"registered span {span!r} has no phase call site in "
                f"the analysis target set",
                hint="record the phase somewhere on the request path, "
                     "or drop it from REQUEST_SPANS (and the README "
                     "span table)",
            ))
    return findings
