"""prom-consistency: the Prometheus renderers must stay statically
consistent with each other and with the snapshot builders feeding them.

``obs/prometheus.py`` renders three expositions (training, gang,
serving) from the exact JSON snapshots the HTTP layers serve. Three
drift modes have bitten or nearly bitten before: a renderer referencing
a snapshot key the builder stopped producing (silently renders NaN/0
forever), a metric emitted under two different TYPEs by two renderers
(the gang endpoint concatenates expositions — a collision corrupts the
scrape), and a name violating the text-format rules only caught at
runtime by ``lint_prometheus_text``. This checker closes all three at
lint time:

- every ``p.head``/``p.sample`` metric name must be a string literal
  (statically checkable), match the name charset, carry the
  ``glint_`` prefix, and counters (and only counters) end ``_total``;
- every sample needs a prior head in the same renderer (modulo the
  ``_sum``/``_count``/``_bucket`` suffixes of summary/histogram
  families), and no duplicate heads;
- a name used by two renderers must have the identical (type, help) —
  families are disjoint-or-identical, so concatenated scrapes lint;
- every snapshot key a renderer maps (``snap.get("k")``, ``x["k"]``,
  and the key element of the (name, key, help) mapping tuples) must be
  produced by the snapshot builders for that renderer (dict-literal
  keys, ``d["k"] = ...`` stores, or ``dict(k=...)`` keywords in the
  producer modules).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from glint_word2vec_tpu.analysis.core import Finding, ModuleCache, checker
from glint_word2vec_tpu.analysis.checkers.common import const_str

RULE = "prom-consistency"

RENDERER_REL = "glint_word2vec_tpu/obs/prometheus.py"

#: renderer function -> the modules that build the snapshot it maps.
PRODUCERS: Dict[str, Tuple[str, ...]] = {
    "training_to_prometheus": (
        "glint_word2vec_tpu/obs/heartbeat.py",
        "glint_word2vec_tpu/utils/metrics.py",
        "glint_word2vec_tpu/obs/events.py",
        "glint_word2vec_tpu/obs/canary.py",
        "glint_word2vec_tpu/parallel/engine.py",
        "glint_word2vec_tpu/obs/slo.py",
    ),
    "serving_to_prometheus": (
        "glint_word2vec_tpu/utils/metrics.py",
        "glint_word2vec_tpu/serving.py",
        "glint_word2vec_tpu/parallel/engine.py",
        "glint_word2vec_tpu/obs/slo.py",
    ),
    "gang_to_prometheus": (
        "glint_word2vec_tpu/obs/aggregate.py",
        "glint_word2vec_tpu/obs/heartbeat.py",
        "glint_word2vec_tpu/utils/metrics.py",
        "glint_word2vec_tpu/obs/slo.py",
    ),
    "fleet_to_prometheus": (
        "glint_word2vec_tpu/fleet.py",
        "glint_word2vec_tpu/obs/aggregate.py",
        "glint_word2vec_tpu/utils/metrics.py",
    ),
}

_NAME_RE = re.compile(r"^[a-z_:][a-z0-9_:]*$")
_KEY_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")
_SUFFIXES = ("_bucket", "_sum", "_count")


def _producer_keys(cache: ModuleCache, rels: Tuple[str, ...]) -> Set[str]:
    """Every key the builder modules can put in a snapshot dict:
    dict-display keys, constant subscript stores, dict(k=...) kwargs."""
    keys: Set[str] = set()
    for rel in rels:
        mod = cache.module(rel)
        if mod is None or mod.tree is None:
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Dict):
                for k in node.keys:
                    s = const_str(k) if k is not None else None
                    if s is not None:
                        keys.add(s)
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        s = const_str(t.slice)
                        if s is not None:
                            keys.add(s)
                # Key tables: a literal tuple/list of (out_key, src)
                # tuples later expanded by a comprehension
                # (`{out: 0 for out, _ in _SUM_COUNTERS}`) — collect
                # the first string of each inner tuple.
                value = getattr(node, "value", None)
                if isinstance(value, (ast.Tuple, ast.List)):
                    for e in value.elts:
                        if isinstance(e, (ast.Tuple, ast.List)) and e.elts:
                            s = const_str(e.elts[0])
                            if s is not None:
                                keys.add(s)
            elif isinstance(node, ast.Call):
                fn = node.func
                if isinstance(fn, ast.Name) and fn.id == "dict":
                    keys.update(kw.arg for kw in node.keywords if kw.arg)
                elif isinstance(fn, ast.Attribute) and \
                        fn.attr in ("setdefault", "update"):
                    keys.update(kw.arg for kw in node.keywords if kw.arg)
                    if node.args:
                        s = const_str(node.args[0])
                        if s is not None and fn.attr == "setdefault":
                            keys.add(s)
    return keys


def _loop_envs(fn: ast.AST) -> Dict[int, Dict[str, Optional[Set[str]]]]:
    """Statically resolve loop variables bound over literal tuple
    lists — the renderers' ``for name, key, help_ in gauges:`` mapping
    idiom. Returns id(node) -> {var: possible constant values} with
    proper loop scoping (two loops reusing ``name`` don't bleed into
    each other); a value of None marks a loop-bound-but-unresolvable
    variable."""
    lists: Dict[str, list] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, (ast.List, ast.Tuple)):
            lists[node.targets[0].id] = node.value.elts

    envs: Dict[int, Dict[str, Optional[Set[str]]]] = {}

    def loop_bindings(node: ast.For) -> Dict[str, Optional[Set[str]]]:
        it = node.iter
        elems = None
        if isinstance(it, (ast.List, ast.Tuple)):
            elems = it.elts
        elif isinstance(it, ast.Name) and it.id in lists:
            elems = lists[it.id]
        targets = (node.target.elts
                   if isinstance(node.target, ast.Tuple)
                   else [node.target])
        bound: Dict[str, Optional[Set[str]]] = {}
        for i, t in enumerate(targets):
            if not isinstance(t, ast.Name):
                continue
            if elems is None:
                bound[t.id] = None
                continue
            vals: Set[str] = set()
            ok = True
            for e in elems:
                ee = (e.elts if isinstance(e, (ast.Tuple, ast.List))
                      else ([e] if len(targets) == 1 else None))
                if ee is None or i >= len(ee):
                    ok = False
                    break
                c = ee[i]
                if isinstance(c, ast.Constant):
                    if c.value is not None:
                        vals.add(c.value)
                else:
                    ok = False
                    break
            bound[t.id] = vals if ok else None
        return bound

    def rec(node: ast.AST, env: Dict[str, Optional[Set[str]]]) -> None:
        envs[id(node)] = env
        if isinstance(node, ast.For):
            inner = dict(env)
            inner.update(loop_bindings(node))
            for child in node.body:
                rec(child, inner)
            for child in node.orelse:
                rec(child, env)
            rec(node.iter, env)
            return
        for child in ast.iter_child_nodes(node):
            rec(child, env)

    rec(fn, {})
    return envs


def _renderer_calls(fn: ast.AST):
    """Yield (kind, call) for p.head / p.sample calls."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("head", "sample") and \
                isinstance(node.func.value, ast.Name):
            yield node.func.attr, node


def _mapped_keys(fn: ast.AST) -> List[Tuple[str, int]]:
    """Snapshot keys the renderer maps: .get("k") args, constant
    subscript reads, and the key element of (metric, key, help)
    mapping tuples."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "get" and node.args:
            s = const_str(node.args[0])
            if s is not None and _KEY_RE.match(s):
                out.append((s, node.lineno))
        elif isinstance(node, ast.Subscript):
            s = const_str(node.slice)
            if s is not None and _KEY_RE.match(s):
                out.append((s, node.lineno))
        elif isinstance(node, ast.Tuple) and len(node.elts) >= 2:
            first = const_str(node.elts[0])
            second = const_str(node.elts[1])
            if first is not None and first.startswith("glint_") and \
                    second is not None and _KEY_RE.match(second):
                out.append((second, node.lineno))
            # ("0.5", "p50_ms")-style quantile->key pairs: the second
            # element is a snapshot key, the first fails _KEY_RE.
            elif first is not None and not _KEY_RE.match(first) and \
                    second is not None and _KEY_RE.match(second) and \
                    len(node.elts) == 2:
                out.append((second, node.lineno))
    return out


@checker(RULE,
         "Prometheus renderers: literal lint-clean metric names, "
         "disjoint-or-identical families across renderers, and every "
         "mapped snapshot key produced by the snapshot builders")
def check_prometheus(cache: ModuleCache) -> List[Finding]:
    findings: List[Finding] = []
    if RENDERER_REL not in cache.targets:
        # Partial run that does not cover the renderer module: nothing
        # to check (its producers are loaded on demand either way).
        return findings
    mod = cache.module(RENDERER_REL)
    if mod is None or mod.tree is None:
        return findings
    # name -> (renderer, type, help-or-None) for cross-renderer family
    # checks; help is None when the head's help arg is not a literal
    # (loop-carried), in which case only the type is compared.
    families: Dict[str, Tuple[str, str, Optional[str]]] = {}
    for fn in mod.tree.body:
        if not isinstance(fn, ast.FunctionDef) or \
                not fn.name.endswith("_to_prometheus"):
            continue
        envs = _loop_envs(fn)
        heads: Dict[str, str] = {}

        def resolve(arg: ast.AST, call: ast.Call) -> Optional[Set[str]]:
            s = const_str(arg)
            if s is not None:
                return {s}
            if isinstance(arg, ast.Name):
                return envs.get(id(call), {}).get(arg.id)
            return None

        for kind, call in _renderer_calls(fn):
            if not call.args:
                continue
            names = resolve(call.args[0], call)
            if not names:
                findings.append(mod.finding(
                    RULE, call,
                    f"p.{kind}() metric name is not statically "
                    f"resolvable — graftlint cannot check it",
                    hint="use a literal, or loop over a literal list "
                         "of (name, ...) tuples",
                ))
                continue
            bad = [n for n in names
                   if not isinstance(n, str) or not _NAME_RE.match(n)
                   or not n.startswith("glint_")]
            if bad:
                findings.append(mod.finding(
                    RULE, call,
                    f"metric name {bad[0]!r} violates the naming rules "
                    f"(charset [a-z0-9_:], glint_ prefix)",
                ))
                continue
            if kind == "head":
                mtype = const_str(call.args[1]) if len(call.args) > 1 \
                    else None
                help_ = const_str(call.args[2]) if len(call.args) > 2 \
                    else None
                if mtype not in _TYPES:
                    findings.append(mod.finding(
                        RULE, call,
                        f"metric {sorted(names)[0]} declares invalid "
                        f"type {mtype!r}",
                    ))
                    continue
                for name in sorted(names):
                    if name in heads:
                        findings.append(mod.finding(
                            RULE, call,
                            f"duplicate head for metric {name} in "
                            f"{fn.name}",
                        ))
                    heads[name] = mtype
                    if mtype == "counter" and not name.endswith("_total"):
                        findings.append(mod.finding(
                            RULE, call,
                            f"counter {name} must end in _total",
                        ))
                    if mtype != "counter" and name.endswith("_total"):
                        findings.append(mod.finding(
                            RULE, call,
                            f"non-counter {name} must not end in _total",
                        ))
                    prior = families.get(name)
                    help_drift = (prior is not None
                                  and prior[2] is not None
                                  and help_ is not None
                                  and prior[2] != help_)
                    if prior is not None and (prior[1] != mtype
                                              or help_drift):
                        what = ("type" if prior[1] != mtype
                                else "HELP text")
                        findings.append(mod.finding(
                            RULE, call,
                            f"metric {name} declares a different "
                            f"{what} in {fn.name} than in {prior[0]} "
                            f"— families must be disjoint or identical "
                            f"(concatenated scrapes share one "
                            f"namespace)",
                        ))
                    else:
                        families.setdefault(name, (fn.name, mtype, help_))
            else:  # sample
                for name in sorted(names):
                    base = name
                    for suf in _SUFFIXES:
                        if name.endswith(suf) and \
                                name[: -len(suf)] in heads:
                            base = name[: -len(suf)]
                            break
                    if base not in heads:
                        findings.append(mod.finding(
                            RULE, call,
                            f"sample for {name} has no head "
                            f"(TYPE/HELP) in {fn.name}",
                            hint="p.head() the family before sampling "
                                 "it",
                        ))
                    elif base != name and heads[base] not in (
                            "summary", "histogram"):
                        findings.append(mod.finding(
                            RULE, call,
                            f"{name} uses a {'/'.join(_SUFFIXES)} "
                            f"suffix but {base} is a {heads[base]}",
                        ))
        produced = _producer_keys(cache, PRODUCERS.get(fn.name, ()))
        if not produced:
            continue
        seen: Set[str] = set()
        for key, lineno in _mapped_keys(fn):
            if key in produced or key in seen:
                continue
            seen.add(key)
            findings.append(mod.finding(
                RULE, lineno,
                f"{fn.name} maps snapshot key {key!r} that no producer "
                f"module builds "
                f"({', '.join(PRODUCERS[fn.name])})",
                hint="fix the key, or update the snapshot builder — a "
                     "renderer-only key scrapes as a permanent "
                     "NaN/0",
            ))
    return findings
