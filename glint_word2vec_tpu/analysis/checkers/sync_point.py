"""sync-point: host<->device synchronization only in blessed seams.

PR 5 made the fit loop stall-free by confining every device->host
readback to explicit harvest seams (one-group-deferred scalar harvest,
the checkpoint snapshot, serving query ops that must return host
values). A stray ``float(tracer_result)`` or ``.block_until_ready()``
anywhere else re-serializes the loop — the device waits on the host
again and the stall telemetry quietly degrades. This rule flags the
sync-inducing forms (``float()`` / ``int()`` / ``np.asarray()`` /
``np.array()`` on non-obviously-host values, ``.block_until_ready()``,
``jax.device_get`` / ``jax.block_until_ready``) in every jax-importing
module of the package, EXCEPT inside the ``SYNC_SEAMS`` allowlist
below — the audited harvest/readback seams where syncing is the whole
point.

Scope note: ``scripts/`` and ``bench.py`` are exempt by design —
benches and probes measure by syncing (that is what a measurement IS);
the rule guards the library's hot paths, where an eager sync is a perf
regression. Their persistence sites remain covered by atomic-persist.

The heuristic is deliberately about *candidate* sites: a ``float(x)``
on a config value in a jax module is noise the HOST_ROOTS skip-list
removes, and anything left that is genuinely host-only gets an inline
``# graftlint: ignore[sync-point] <why>`` — the audit trail is the
feature.
"""

from __future__ import annotations

import ast
from typing import Dict, List

from glint_word2vec_tpu.analysis.core import Finding, ModuleCache, checker
from glint_word2vec_tpu.analysis.checkers.common import (
    call_name,
    enclosing_map,
    root_name,
)

RULE = "sync-point"

#: Blessed harvest/readback seams: "<repo-relative path>::<qualname>"
#: -> why syncing is legal there. A seam blesses everything lexically
#: inside the named function (including nested helpers).
SYNC_SEAMS: Dict[str, str] = {
    # The deferred-readback harvests: sync group g's scalars while
    # group g+1 runs — the PR 5 design's one legal fit-loop sync.
    "glint_word2vec_tpu/models/word2vec.py::"
    "Word2Vec._fit_corpus_resident._harvest_packed":
        "the one-group-deferred scalar harvest seam (PR 5): syncs "
        "group g while group g+1 runs; since ISSUE 11 these are the "
        "fused Pallas megakernel's result scalars (losses/pair "
        "counts/position advances) whenever the engine runs "
        "pallas-fused — the kernel's ONLY host-visible outputs",
    "glint_word2vec_tpu/models/word2vec.py::"
    "Word2Vec._fit_with_batcher._harvest_host":
        "host-batcher twin of the deferred harvest: one-group-lagged "
        "loss/word records",
    "glint_word2vec_tpu/streaming/trainer.py::StreamTrainer._harvest":
        "streaming mini-epoch harvest seam (ISSUE 10): syncs one "
        "dispatched group's result scalars (the fused megakernel's "
        "scalars under ISSUE 11 pallas-fused engines); the buffer is "
        "already uploaded, so nothing starves behind the sync",
    # Checkpoint harvest: device->host shard copies on the save path
    # run on the caller thread by design (PR 5's async protocol).
    "glint_word2vec_tpu/parallel/engine.py::"
    "EmbeddingEngine._iter_owned_blocks":
        "checkpoint harvest seam: device->host copies of the owned "
        "table blocks",
    # Corpus staging + compaction: upload is host->device staging, the
    # compaction sync is stall-accounted and overlapped by prefetch.
    "glint_word2vec_tpu/parallel/engine.py::EmbeddingEngine.upload_corpus":
        "host->device corpus staging; np.asarray here normalizes host "
        "input, the device transfer is the put",
    "glint_word2vec_tpu/parallel/engine.py::EmbeddingEngine.compact_corpus":
        "subsample-compaction readback seam: the n_kept sync is "
        "stall-accounted and prefetch-overlapped (PR 5)",
    "glint_word2vec_tpu/parallel/engine.py::"
    "EmbeddingEngine.prefetch_compact_corpus":
        "async twin of compact_corpus: dispatches next epoch's "
        "compaction, harvest deferred to adoption",
    "glint_word2vec_tpu/parallel/engine.py::"
    "EmbeddingEngine.compacted_offsets":
        "compaction offsets readback: host accounting needs the "
        "compacted offsets once per epoch",
    # Checkpoint snapshot seams: device->host table copies on the save
    # path, by design on the calling thread (PR 5's async protocol).
    "glint_word2vec_tpu/parallel/engine.py::EmbeddingEngine._snapshot_host":
        "checkpoint harvest seam: device->host copy of tables + counts "
        "before handing off to the writer",
    "glint_word2vec_tpu/parallel/engine.py::EmbeddingEngine._save_multihost":
        "legacy multihost in-place checkpoint harvest: per-process "
        "device->host shard copies",
    # Serving query ops return host values to HTTP clients — the
    # dispatch IS the sync, coalesced and warmed upstream (PR 2).
    "glint_word2vec_tpu/parallel/engine.py::EmbeddingEngine.multiply":
        "serving query op: stages the host query vector and returns "
        "host scores by contract",
    "glint_word2vec_tpu/parallel/engine.py::EmbeddingEngine.top_k_cosine":
        "serving query op: returns host (vals, ids) by contract",
    "glint_word2vec_tpu/parallel/engine.py::"
    "EmbeddingEngine.top_k_cosine_batch":
        "serving query op: returns host (vals, ids) by contract",
    # The model query surface: host numpy out by contract (PR 2 warms
    # and buckets the device dispatches underneath).
    "glint_word2vec_tpu/models/word2vec.py::Word2VecModel._decode_hits":
        "serving surface: decodes device top-k hits into host "
        "(word, score) pairs",
    "glint_word2vec_tpu/models/word2vec.py::"
    "Word2VecModel.find_synonyms_vector":
        "model query surface: stages the host query vector, returns "
        "host (word, score) pairs",
    "glint_word2vec_tpu/models/word2vec.py::"
    "Word2VecModel.find_synonyms_batch":
        "model query surface: stages host query vectors, returns host "
        "(word, score) pairs",
    "glint_word2vec_tpu/models/word2vec.py::Word2VecModel.transform":
        "model query surface: returns host vector by contract",
    "glint_word2vec_tpu/models/word2vec.py::"
    "Word2VecModel.transform_sentences":
        "model query surface: returns host vectors by contract",
    "glint_word2vec_tpu/models/word2vec.py::Word2VecModel.transform_words":
        "model query surface: returns host vectors by contract",
    "glint_word2vec_tpu/models/word2vec.py::"
    "Word2VecModel.transform_packed":
        "bulk-transform hot path (ISSUE 17): harvests one packed "
        "pull_average block to host vectors by contract — the batch "
        "pipeline's only device sync",
    "glint_word2vec_tpu/models/word2vec.py::Word2VecModel.get_vectors":
        "model export surface: pulls the table to host by contract",
    "glint_word2vec_tpu/models/word2vec.py::Word2VecModel.to_local":
        "model export surface: materializes a host-numpy local model",
    "glint_word2vec_tpu/models/word2vec.py::"
    "LocalWord2VecModel.find_synonyms_vector":
        "local numpy model: every value is already host",
    # ANN index lifecycle seams (ISSUE 12): builds and incremental
    # re-bucketing run OFF the request path by contract (boot, the
    # hot-swap staging thread, or a streaming promotion burst) — the
    # assignment readbacks and host member packing are the design.
    "glint_word2vec_tpu/ops/ann.py::build":
        "index build seam: k-means assignment readbacks + host member "
        "packing, off the request path (boot / hot-swap staging)",
    "glint_word2vec_tpu/ops/ann.py::add_rows":
        "incremental re-bucket seam: score readback for only the "
        "touched rows (streaming promotions), off the request path",
    "glint_word2vec_tpu/ops/ann.py::_pack_members":
        "host member packing invoked only from the build seam: every "
        "value is a host numpy scalar by then",
    "glint_word2vec_tpu/ops/ann.py::_drop_row":
        "host member-layout bookkeeping: slot ids are host numpy ints",
    "glint_word2vec_tpu/ops/ann.py::remove_rows":
        "host member-layout bookkeeping: freed row ids arrive as host "
        "ints from the engine",
    "glint_word2vec_tpu/parallel/engine.py::"
    "EmbeddingEngine.ann_top_k_batch":
        "serving query op: returns host (vals, ids) by contract, the "
        "approximate twin of top_k_cosine_batch",
    "glint_word2vec_tpu/parallel/engine.py::"
    "EmbeddingEngine.ann_recall_at_k":
        "recall-gate seam: compares exact vs approximate host id sets "
        "at build/refresh time, off the request path",
    # Replica-exchange seams (ISSUE 15): a reconciliation round IS a
    # sync point by design — the harvest brings the fixed-capacity
    # payload buffers to host for the cross-rank transport, and the
    # protocol drivers shuffle host numpy throughout.
    "glint_word2vec_tpu/parallel/exchange.py::ReplicaExchanger.harvest":
        "exchange harvest seam: the padded (ids, deltas) buffers must "
        "reach host for the cross-rank transport",
    "glint_word2vec_tpu/parallel/exchange.py::"
    "ReplicaExchanger._dense_delta":
        "dense/spill harvest seam: the full per-rank delta is by "
        "definition a host wire payload",
    "glint_word2vec_tpu/parallel/exchange.py::ReplicaExchanger.sync":
        "the exchange round itself: a deliberate reconciliation "
        "barrier between dispatch groups (headers and payloads are "
        "host numpy)",
    "glint_word2vec_tpu/parallel/exchange.py::"
    "ReplicaExchanger._twolevel_round":
        "level-1/level-2 legs of the sync seam (ISSUE 16): node fold "
        "and leader payloads are host wire traffic of the same "
        "reconciliation barrier",
    "glint_word2vec_tpu/parallel/exchange.py::sync_group":
        "in-process N-replica exchange driver (tests/harness): same "
        "reconciliation barrier as ReplicaExchanger.sync",
    "glint_word2vec_tpu/parallel/exchange.py::NullTransport.allgather":
        "1-replica transport: wraps an already-host payload",
    "glint_word2vec_tpu/parallel/exchange.py::"
    "ProcessTransport.allgather":
        "cross-process transport: process_allgather returns host "
        "arrays by contract",
    "glint_word2vec_tpu/parallel/distributed.py::allgather_host":
        "host-level collective wire of the replica exchange: input and "
        "output are host numpy by contract",
    "glint_word2vec_tpu/parallel/engine.py::"
    "EmbeddingEngine._iter_owned_block_producers":
        "checkpoint harvest seam (shard-streaming form of "
        "_iter_owned_blocks): each producer copies exactly one owned "
        "block to host for the writer",
}

#: Expression roots that are host values by construction — calling
#: float()/int() on them synchronizes nothing.
HOST_ROOTS = frozenset({
    "os", "time", "len", "sys", "math", "random", "args", "json", "re",
    "str", "repr", "round", "min", "max", "sum", "abs", "sorted", "ord",
    "int", "float", "bool", "env", "environ",
})

_CAST_CALLS = ("float", "int", "np.asarray", "numpy.asarray",
               "np.array", "numpy.array")

_FORCED_SYNCS = ("jax.device_get", "jax.block_until_ready")


def _is_candidate_arg(arg: ast.AST) -> bool:
    """Could this expression hold a device value? Literals and
    host-rooted chains cannot."""
    if isinstance(arg, ast.Constant):
        return False
    if isinstance(arg, (ast.JoinedStr, ast.Compare, ast.BoolOp)):
        return False  # strings and python bools are host values
    root = root_name(arg)
    if root is not None and root in HOST_ROOTS:
        return False
    if isinstance(arg, ast.BinOp):
        # A binop of two non-candidates is a non-candidate.
        return _is_candidate_arg(arg.left) or _is_candidate_arg(arg.right)
    return True


@checker(RULE,
         "host<->device syncs (float()/int()/np.asarray on device "
         "values, .block_until_ready(), jax.device_get) only in the "
         "blessed harvest/readback seams")
def check_sync_point(cache: ModuleCache) -> List[Finding]:
    findings: List[Finding] = []
    for mod in cache.modules():
        if mod.tree is None:
            continue
        if not mod.rel.startswith("glint_word2vec_tpu/"):
            continue  # scripts/bench measure by syncing — see docstring
        if "jax" not in mod.imports():
            continue
        enclosing = enclosing_map(mod.tree)

        def in_seam(node: ast.AST) -> bool:
            qn = enclosing.get(id(node), "")
            while qn:
                if SYNC_SEAMS.get(f"{mod.rel}::{qn}") is not None:
                    return True
                qn = qn.rsplit(".", 1)[0] if "." in qn else ""
            return False

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node) or ""
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "block_until_ready":
                if not in_seam(node):
                    findings.append(mod.finding(
                        RULE, node,
                        ".block_until_ready() outside a blessed seam "
                        "serializes the dispatch pipeline",
                        hint="defer the sync into a harvest seam, or "
                             "bless this function in SYNC_SEAMS with "
                             "its reason",
                    ))
                continue
            if name in _FORCED_SYNCS:
                if not in_seam(node):
                    findings.append(mod.finding(
                        RULE, node,
                        f"{name}() outside a blessed seam forces a "
                        f"device->host transfer",
                        hint="harvest through the deferred-readback "
                             "seam instead",
                    ))
                continue
            if name in _CAST_CALLS and node.args:
                if name in ("float", "int") and (
                        len(node.args) != 1 or node.keywords):
                    # int(s, 16) / float(x, ...) forms are string
                    # parses, never device syncs.
                    continue
                # np.asarray/np.array keep their dtype arg/kwarg — the
                # first positional is the (possibly device) value.
                if not _is_candidate_arg(node.args[0]):
                    continue
                if in_seam(node):
                    continue
                findings.append(mod.finding(
                    RULE, node,
                    f"{name}() on a possibly-device value outside a "
                    f"blessed seam is an implicit sync",
                    hint="if the value is host-only, add `# graftlint: "
                         "ignore[sync-point] <why>`; if it is a device "
                         "value, harvest it in a seam",
                ))
    return findings
